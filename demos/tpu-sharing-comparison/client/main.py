"""Benchmark client for the TPU sharing-comparison demo.

Port of the reference's client (`demos/gpu-sharing-comparison/client/main.py`,
which exports a Prometheus `inference_time_seconds` Summary): continuously
POSTs /infer to the target servers and serves the same summary metric on
/metrics so the comparison query from the reference README works unchanged:

    avg(sum(rate(inference_time_seconds_sum[2m]))
        / sum(rate(inference_time_seconds_count[2m])))
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.request

from walkai_nos_tpu.health import HealthServer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--targets", required=True,
        help="comma-separated inference server URLs",
    )
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument(
        "--concurrency", type=int, default=1,
        help="in-flight requests per target (each new connection is "
        "load-balanced across the Service's server pods, so concurrency N "
        "against one ClusterIP keeps ~N requests in flight cluster-wide)",
    )
    parser.add_argument("--metrics-addr", default=":9090")
    args = parser.parse_args()

    server = HealthServer(args.metrics_addr)
    server.start()
    server.mark_ready()

    def hammer(target: str) -> None:
        while True:
            try:
                req = urllib.request.Request(
                    f"{target}/infer",
                    data=json.dumps({"batch": args.batch}).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    elapsed = json.loads(resp.read())["inference_time_seconds"]
                server.metrics.counter_add(
                    "inference_time_seconds_sum", elapsed, {"target": target}
                )
                server.metrics.counter_add(
                    "inference_time_seconds_count", 1, {"target": target}
                )
            except Exception:
                server.metrics.counter_add(
                    "inference_errors_total", 1, {"target": target}
                )
                time.sleep(1.0)  # back off while the target is unreachable

    for target in args.targets.split(","):
        for _ in range(max(1, args.concurrency)):
            threading.Thread(
                target=hammer, args=(target,), daemon=True
            ).start()
    threading.Event().wait()


if __name__ == "__main__":
    main()

"""Inference server pod for the TPU sharing-comparison demo.

TPU-native rebuild of the reference's demo workload
(`demos/gpu-sharing-comparison/app/main.py`, a torch YOLOS-small HTTP
server): serves the flagship YOLOS-style ViT over HTTP on whatever slice
the device plugin granted this pod (TPU_VISIBLE_CHIPS et al. are injected
by the walkai device plugin at Allocate time).

Two serving-path design points, both TPU-native:

1. **Micro-batching.** Unlike the reference (one CUDA forward per
   request), concurrent POST /infer requests are coalesced by a single
   device worker into one padded forward per tick, bucketed to
   power-of-two batch sizes so XLA compiles each shape once. N clients
   sharing a slice drive one batch=N matmul pipeline instead of N
   serialized batch-1 passes.
2. **Fence-based completion.** Dispatch is asynchronous and the device
   runtime may acknowledge enqueue long before compute finishes (remote/
   tunneled PJRT backends do), so requests are acked by a fencer thread
   that host-fetches a scalar from the NEWEST dispatched batch — same-
   device executions complete in dispatch order, so one fence
   acknowledges every earlier batch. In-flight batches are bounded by a
   semaphore for backpressure. All throughput counters count only FENCED
   (provably completed) work; a startup calibration measures the host
   round-trip and the chip's attainable FLOP/s through the same fencing
   so utilization can be reported against what the runtime can actually
   deliver.

Endpoints:
- POST /infer  {"batch": N}  -> {"inference_time_seconds": s, ...}
- GET  /stats  -> cumulative fenced {images, requests, batches, flops,
  monotonic_s} + {device_kind, peak_bf16_flops,
  model_ceiling_images_per_s, fence_rtt_s} for utilization measurement.
- GET  /healthz -> readiness payload: {"ok": true, "monotonic_s":
  this process's clock read (the fleet router's trace clock-offset
  estimate), "engine": {alive, queue_depth,
  seconds_since_last_dispatch, has_work, draining,
  slots} | null} (engine block present when continuous batching is
  enabled). POST /generate accepts an `X-Walkai-Trace` header (the
  fleet router's cross-process trace id), stores it on the engine
  submit, and echoes it on the response (header + "trace_id" field)
  so clients can correlate a slow call with /debug/trace.
- GET  /metrics -> Prometheus text exposition of the obs registry
  (serving-engine dispatch/TTFT/TPOT/pool telemetry; see
  docs/observability.md for every exported name).
- GET  /debug/trace -> Chrome trace-event JSON of recent request
  lifecycles plus per-dispatch device-vs-host attribution phases
  (load into chrome://tracing or Perfetto).
- GET/POST /debug/profile -> jax.profiler capture-window status / arm
  ({"dispatches": N, "logdir": ...}).
- GET  /debug/state -> one fenced engine snapshot: slots, KV block
  pool, prefix trie, spec controller, attribution, SLO windows.
- GET  /debug/slo -> the sliding-window SLO view alone (windowed
  quantiles, objective compliance + burn rate, saturation).
- GET/POST /debug/capture, GET /debug/capture/download -> the
  deterministic capture plane's status / rotate / download
  (WALKAI_CAPTURE_DIR arms it; every /generate completion then
  carries the engine's config-fingerprint id, and the downloaded
  ndjson replays token-identically via cmd/replay.py).

Env knobs: WALKAI_MAX_BATCH (default 32), WALKAI_BATCH_WINDOW_MS
(default 2.0), WALKAI_WARM_BUCKETS (comma list, default "1,8,32"),
WALKAI_MAX_INFLIGHT (default 8), WALKAI_CALIB_BATCHES (initial
calibration chain length, default 4, doubled until the run is long
enough to dominate fence noise).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# Tensor-parallel EMULATION seam (WALKAI_TP_EMULATE=N): force N
# virtual CPU devices before jax initializes its backend, so a
# WALKAI_CB_TP>1 engine runs its real sharded programs on a laptop /
# CI box with no TPU — the same trick tests/conftest.py plays for the
# tier-1 tp parity suite. Must run at import time, ahead of any jax
# import below.
if os.environ.get("WALKAI_TP_EMULATE"):
    _emu = int(os.environ["WALKAI_TP_EMULATE"])
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_emu}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


@dataclass
class _Request:
    n_images: int
    arrived: float
    done: threading.Event = field(default_factory=threading.Event)
    elapsed: float = 0.0
    batched_with: int = 0


@dataclass
class _Dispatched:
    requests: list
    n_images: int
    bucket: int  # padded dispatch size (>= n_images)
    output: object  # device array to fence on


class _Stats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.images = 0
        self.requests = 0
        self.batches = 0
        self.flops = 0.0
        # Diagnostics for the utilization gap: padded images dispatched
        # (bucket - actual, counted at fence time with the images they
        # belong to) and two DISTINCT idleness measures —
        # `dispatcher_idle_s`: time the dispatcher thread spent waiting
        #   for the first request of a batch. With deep pipelining this
        #   can be large while the device stays fully fed (up to
        #   max_inflight batches are queued on-device), so it is NOT a
        #   device-starvation signal;
        # `device_starved_s`: time with ZERO dispatched-but-unfenced
        #   batches — the device truly had nothing queued. Slightly
        #   underestimates idleness (a batch counts as in-flight until
        #   the fencer acks it, after completion), so treat small values
        #   as "fed", not as an exact busy integral.
        self.padded_images = 0
        self.dispatcher_idle_s = 0.0
        self.worker_waiting_since: float | None = None
        self.inflight = 0
        self.device_starved_s = 0.0
        self.device_idle_since: float | None = time.monotonic()

    def record(self, images, requests, padded, flops) -> None:
        with self._lock:
            self.images += images
            self.requests += requests
            self.batches += 1
            self.padded_images += padded
            self.flops += flops

    def wait_started(self) -> None:
        with self._lock:
            self.worker_waiting_since = time.monotonic()

    def wait_ended(self) -> None:
        with self._lock:
            if self.worker_waiting_since is not None:
                self.dispatcher_idle_s += (
                    time.monotonic() - self.worker_waiting_since
                )
                self.worker_waiting_since = None

    def mark_dispatch(self) -> None:
        with self._lock:
            if self.inflight == 0 and self.device_idle_since is not None:
                self.device_starved_s += (
                    time.monotonic() - self.device_idle_since
                )
                self.device_idle_since = None
            self.inflight += 1

    def mark_fenced(self, n: int) -> None:
        with self._lock:
            self.inflight -= n
            if self.inflight == 0:
                self.device_idle_since = time.monotonic()

    def snapshot(self) -> dict:
        with self._lock:
            now = time.monotonic()
            idle = self.dispatcher_idle_s
            if self.worker_waiting_since is not None:
                idle += now - self.worker_waiting_since
            starved = self.device_starved_s
            if self.inflight == 0 and self.device_idle_since is not None:
                starved += now - self.device_idle_since
            return {
                "images": self.images,
                "requests": self.requests,
                "batches": self.batches,
                "flops": self.flops,
                "padded_images": self.padded_images,
                "dispatcher_idle_s": idle,
                "device_starved_s": starved,
                "monotonic_s": now,
            }


def engine_health(engine, alive: bool) -> dict | None:
    """The /healthz readiness payload's engine block: liveness of the
    driver loop plus the two "is it actually moving" signals a probe
    or an operator wants first — queue depth and staleness of the last
    dispatch — and the two scale signals a kube autoscaler consumes
    without scraping Prometheus text: `saturation` (the engine's
    composed [0, 1] pressure signal) and `slo_ok` (windowed SLO
    compliance; both None before the first dispatch or with telemetry
    off). None when continuous batching is not enabled."""
    if engine is None:
        return None
    age = engine.seconds_since_last_dispatch
    saturation = engine.saturation
    payload = {
        "alive": bool(alive),
        "queue_depth": engine.queue_depth,
        "seconds_since_last_dispatch": (
            None if age is None else round(age, 3)
        ),
        "has_work": engine.has_work,
        # Drain lifecycle: True once drain() was called; together with
        # has_work=False it means "fully drained" — what the fleet
        # router's scale-down reconciler polls before returning the
        # slice.
        "draining": getattr(engine, "draining", False),
        "slots": engine.slots,
        "saturation": (
            None if saturation is None else round(saturation, 4)
        ),
        "slo_ok": engine.slo_ok,
    }
    drain_stats = getattr(engine, "drain_stats", None)
    if drain_stats is not None:
        # Drain-down PROGRESS, not just the flag: resident slots,
        # queued/prefilling counts, and the KV blocks live requests
        # still hold — the numbers an operator (or the reconciler)
        # watches converge to zero while a drain runs.
        payload["drain"] = drain_stats()
    return payload


def request_trace_id(*candidates) -> str:
    """First well-formed candidate (header value, body field), else a
    freshly minted local id — every /generate response carries SOME
    id, so a client can correlate a slow call with /debug/trace
    without guessing. Validation is `obs/trace.valid_trace_id`, the
    ONE charset contract shared with the router: a drifted copy
    would make one side reject and re-mint the other side's ids,
    silently breaking cross-process correlation."""
    from walkai_nos_tpu.obs.trace import valid_trace_id

    for candidate in candidates:
        adopted = valid_trace_id(candidate)
        if adopted is not None:
            return adopted
    return "d" + uuid.uuid4().hex[:15]


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


def _fence(output) -> None:
    """Force true completion of `output` (and every earlier dispatch on
    the same device) by fetching one scalar to the host. block_until_ready
    alone is NOT a completion guarantee on remote/tunneled backends."""
    import numpy as np

    np.asarray(output["logits"][0, 0, 0])


def _calibrate(jnp, jax, infer, params, images_of, max_batch):
    """Measure (fence_rtt_s, model_ceiling_images_per_s): the chip's
    flat-out throughput ON THE SERVED MODEL through the same
    dispatch+fence path the server uses. Utilization is reported against
    this ceiling — the TPU analogue of device-utilization uplift in the
    reference's comparison: what fraction of the chip's attainable
    delivery the shared serving path sustains. (Model FLOPs over the
    theoretical bf16 peak — MFU — is reported separately; for a
    memory-bound model the two differ by design.)"""
    import numpy as np

    tiny = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0)
    np.asarray(tiny(x))
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny(x))
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[len(rtts) // 2]

    images = images_of(max_batch)
    _fence(infer(params, images))  # compile
    n = max(4, int(os.environ.get("WALKAI_CALIB_BATCHES", "0")) or 4)
    while True:
        # Dispatch the whole chain asynchronously, fence once: the chip
        # runs back-to-back with no host stalls — the true flat-out rate.
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = infer(params, images)
        _fence(out)
        wall = time.perf_counter() - t0
        # A >=4s window keeps the single fence RTT (~100ms on tunneled
        # runtimes) under ~3% of the estimate — utilization is reported
        # against this ceiling, so its noise is the metric's noise.
        # (Tests shrink it via WALKAI_CALIB_WINDOW_S: CPU CI pays compile
        # + calibration serially and doesn't read the ceiling.)
        window = float(os.environ.get("WALKAI_CALIB_WINDOW_S", "4.0"))
        if wall > window or n >= 1024:
            break
        n *= 2
    # Best of two windows: a single window's downward noise (a slow
    # dispatch, a GC pause) understates the ceiling and shows up as
    # >100% utilization; the max of two independent windows halves that
    # bias while an overstated ceiling remains impossible (the chip
    # cannot run faster than itself). Residual noise is ~±2-3%.
    best = max(wall - rtt, 1e-9)
    if wall > 0.5:  # skip for test-sized windows
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = infer(params, images)
        _fence(out)
        best = min(best, max(time.perf_counter() - t0 - rtt, 1e-9))
    return rtt, max_batch * n / best


def main() -> None:
    import jax
    import jax.numpy as jnp

    from walkai_nos_tpu.models.train import make_infer_step
    from walkai_nos_tpu.models.vit import VIT_SMALL, VIT_TINY, ViTDetector
    from walkai_nos_tpu.utils.flops import peak_bf16_flops, vit_flops_per_image

    # WALKAI_DEMO_MODEL=tiny is the test seam: same serving path, a
    # seconds-not-minutes compile on CPU CI.
    cfg = (
        VIT_TINY
        if os.environ.get("WALKAI_DEMO_MODEL") == "tiny"
        else VIT_SMALL
    )
    # Serving precision policy: weights are cast to bf16 ONCE at load
    # (training keeps f32 masters). The forward computes in bf16 with
    # f32 accumulation either way; f32 weights would double the
    # per-batch weight traffic and add a cast pass per dispatch.
    params = jax.device_put(
        jax.tree.map(
            lambda p: p.astype(jnp.bfloat16),
            ViTDetector(cfg).init_params(jax.random.PRNGKey(0)),
        )
    )
    infer = make_infer_step(cfg)
    max_batch = int(os.environ.get("WALKAI_MAX_BATCH", "32"))
    window_s = float(os.environ.get("WALKAI_BATCH_WINDOW_MS", "2.0")) / 1e3
    max_inflight = int(os.environ.get("WALKAI_MAX_INFLIGHT", "8"))

    # One cached zero-input per bucket: inputs never leave the device, so
    # in-flight batches cost no transfers and bounded output memory.
    inputs = {}

    def images_of(batch: int):
        if batch not in inputs:
            # bf16 inputs: the model's first act is the cast anyway;
            # staging f32 would double the input read per dispatch.
            inputs[batch] = jnp.zeros(
                (batch, cfg.image_size, cfg.image_size, 3), jnp.bfloat16
            )
        return inputs[batch]

    # Per-image FLOPs and bytes: prefer XLA's own cost analysis of the
    # compiled forward AT THE SERVING BATCH (per-image traffic shrinks
    # with batch as weight reads amortize), fall back to the analytic
    # FLOP count with no byte estimate. The AOT executable this builds
    # is REUSED for max_batch dispatches (a jit call would compile the
    # same most-expensive shape a second time — the AOT cache and the
    # jit dispatch cache don't share entries).
    flops_per_image = vit_flops_per_image(cfg)
    bytes_per_image = 0.0
    try:
        compiled_max = infer.lower(params, images_of(max_batch)).compile()
        cost = compiled_max.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        analyzed = float(cost.get("flops", 0.0))
        if analyzed > 0:
            flops_per_image = analyzed / max_batch
        bytes_per_image = float(cost.get("bytes accessed", 0.0)) / max_batch

        jit_infer = infer

        def infer(params, images, _c=compiled_max, _j=jit_infer):  # noqa: F811
            if images.shape[0] == max_batch:
                return _c(params, images)
            return _j(params, images)
    except Exception:
        pass

    fence_rtt, ceiling_img_s = _calibrate(
        jnp, jax, infer, params, images_of, max_batch
    )

    warm = os.environ.get("WALKAI_WARM_BUCKETS", "1,8,32")
    for b in sorted({int(x) for x in warm.split(",") if x.strip()}):
        if 1 <= b <= max_batch:
            _fence(infer(params, images_of(b)))

    device = jax.devices()[0]
    slice_id = os.environ.get("TPU_SLICE_ID", "whole-host")
    print(
        f"serving on slice {slice_id} with {jax.device_count()} "
        f"{device.device_kind} device(s), max_batch={max_batch}, "
        f"fence_rtt={fence_rtt * 1e3:.1f}ms, "
        f"model_ceiling={ceiling_img_s:.0f} img/s"
    )

    # Optional LM text-generation endpoint (WALKAI_DEMO_LM=1): the same
    # slice serves KV-cache decoding beside the vision dispatcher. Kept
    # strictly out of the default path — the headline bench measures the
    # vision pipeline and must not pay a second model's compile/memory.
    lm_generate = lm_params = lm_cfg = None
    lm_spec_generate = spec_draft_params = None
    spec_k = 0
    lm_lock = threading.Lock()
    lm_max_new = int(os.environ.get("WALKAI_LM_MAX_NEW", "64"))
    cb_engine = cb_queue = None
    cb_slots = cb_bucket = 0
    cb_enabled = [False]
    # Telemetry bundle (walkai_nos_tpu/obs): the registry behind
    # /metrics, the lifecycle trace behind /debug/trace, and the
    # jax.profiler hook behind /debug/profile. WALKAI_OBS=0 builds the
    # no-op bundle (the disabled arm of the bench's obs_overhead_pct
    # measurement).
    from walkai_nos_tpu.obs import ServingObs

    obs = ServingObs(enabled=os.environ.get("WALKAI_OBS", "1") == "1")
    if os.environ.get("WALKAI_DEMO_LM") == "1":
        from walkai_nos_tpu.models.decode import make_generate_fn
        from walkai_nos_tpu.models.lm import LM_TINY, LM_SMALL, DecoderLM

        # WALKAI_LM_MODEL decouples the LM size from the vision model
        # (the CB serving benchmark wants a tiny ViT beside the real
        # serving LM); WALKAI_LM_VOCAB shrinks the vocab so sampled
        # workloads hit EOS with measurable probability — a bench/test
        # seam, not a serving feature.
        lm_choice = os.environ.get(
            "WALKAI_LM_MODEL",
            "tiny" if os.environ.get("WALKAI_DEMO_MODEL") == "tiny"
            else "small",
        )
        lm_cfg = LM_TINY if lm_choice == "tiny" else LM_SMALL
        if os.environ.get("WALKAI_LM_VOCAB") or os.environ.get(
            "WALKAI_LM_SEQ"
        ):
            import dataclasses as _dcv

            # WALKAI_LM_SEQ stretches max_seq_len the same way
            # WALKAI_LM_VOCAB shrinks the vocab: the prefix-reuse
            # bench needs >= 129-token prompts (a full shareable
            # cache block) on the tiny CPU model whose default
            # context is 128.
            lm_cfg = _dcv.replace(
                lm_cfg,
                vocab_size=int(
                    os.environ.get("WALKAI_LM_VOCAB")
                    or lm_cfg.vocab_size
                ),
                max_seq_len=int(
                    os.environ.get("WALKAI_LM_SEQ")
                    or lm_cfg.max_seq_len
                ),
            )
        lm_params = jax.device_put(
            DecoderLM(lm_cfg).init_params(jax.random.PRNGKey(0))
        )
        lm_generate = make_generate_fn(lm_cfg)
        # Warm the common signature (prompt 16) so the first request
        # isn't a compile.
        warm_prompt = jnp.zeros((1, 16), jnp.int32)
        _ = lm_generate(lm_params, warm_prompt, max_new_tokens=lm_max_new)
        import numpy as _np

        _np.asarray(jnp.ravel(_))
        print(
            f"lm generation enabled: {lm_cfg.num_layers} layers, "
            f"max_new={lm_max_new}"
        )
        if os.environ.get("WALKAI_DEMO_SPEC") == "1":
            # Speculative path for {"speculative": true} requests: a
            # 1-layer draft proposes, the target verifies — the output
            # stays the target's greedy sequence for ANY draft weights
            # (models/speculative.py), so serving it untrained is
            # correct; a deployment would load a distilled draft here.
            import dataclasses as _dc

            from walkai_nos_tpu.models.speculative import (
                make_speculative_generate_fn,
            )

            spec_k = int(os.environ.get("WALKAI_SPEC_K", "6"))
            spec_draft_cfg = _dc.replace(
                lm_cfg,
                num_layers=1,
                hidden_dim=max(32, lm_cfg.hidden_dim // 4),
                num_heads=max(2, lm_cfg.num_heads // 4),
            )
            spec_draft_params = jax.device_put(
                DecoderLM(spec_draft_cfg).init_params(
                    jax.random.PRNGKey(1)
                )
            )
            lm_spec_generate = make_speculative_generate_fn(
                lm_cfg, spec_draft_cfg, k=spec_k, return_stats=True,
            )
            _spec_out, _ = lm_spec_generate(
                lm_params, spec_draft_params, warm_prompt, lm_max_new
            )
            _np.asarray(jnp.ravel(_spec_out))
            print(f"speculative generation enabled: k={spec_k}")
        if os.environ.get("WALKAI_DEMO_CB", "1") == "1":
            # Continuous batching IS the greedy /generate path:
            # concurrent generations share a slot pool instead of
            # serializing behind lm_lock (models/serve.py; measured
            # 2.1x/3.4x/5.2x aggregate tokens/s over the serialized
            # path at 8/16/32 slots on v5e — lower bounds, see the
            # module docstring).
            # Speculative requests keep the one-shot path (the spec
            # round structure doesn't chunk).
            from walkai_nos_tpu.models.decode import cache_bucket
            from walkai_nos_tpu.models.serve import ContinuousBatcher

            cb_slots = int(os.environ.get("WALKAI_CB_SLOTS", "4"))
            cb_bucket = int(os.environ.get("WALKAI_CB_BUCKET", "64"))
            # Batched speculative decoding inside the engine
            # (WALKAI_CB_SPEC=1): a shared draft proposes
            # WALKAI_CB_SPEC_K tokens per slot per round, one
            # multi-step target dispatch verifies them — outputs stay
            # token-identical to spec-off, /generate is unchanged.
            # WALKAI_CB_SPEC_DRAFT picks the draft: "tiny" (default,
            # a draft_config-scaled random init — a deployment loads
            # a distilled draft here; untrained acceptance is near
            # zero, so the engine's adaptive controller will disable
            # drafting) or "self" (draft = target: the full-acceptance
            # seam the spec bench uses to exercise the machinery).
            # Sliding-window SLO layer (obs/slo.py): WALKAI_SLO_*
            # knobs configure the window and the objectives the
            # engine's windowed compliance/burn gauges (and the
            # /healthz slo_ok field) are judged against. Unset
            # objectives leave compliance vacuously ok.
            cb_slo_kwargs = {}
            if os.environ.get("WALKAI_SLO_WINDOW_S"):
                cb_slo_kwargs["slo_window_s"] = float(
                    os.environ["WALKAI_SLO_WINDOW_S"]
                )
            slo_objectives = {}
            if os.environ.get("WALKAI_SLO_TTFT_P99_S"):
                slo_objectives["ttft_p99_s"] = float(
                    os.environ["WALKAI_SLO_TTFT_P99_S"]
                )
            if os.environ.get("WALKAI_SLO_TPOT_P99_S"):
                slo_objectives["tpot_p99_s"] = float(
                    os.environ["WALKAI_SLO_TPOT_P99_S"]
                )
            if slo_objectives:
                cb_slo_kwargs["slo_objectives"] = slo_objectives
            cb_spec_kwargs = {}
            if os.environ.get("WALKAI_CB_SPEC") == "1":
                from walkai_nos_tpu.models.lm import draft_config

                if os.environ.get(
                    "WALKAI_CB_SPEC_DRAFT", "tiny"
                ) == "self":
                    cb_draft_cfg, cb_draft_params = lm_cfg, lm_params
                else:
                    cb_draft_cfg = draft_config(lm_cfg)
                    cb_draft_params = jax.device_put(
                        DecoderLM(cb_draft_cfg).init_params(
                            jax.random.PRNGKey(2)
                        )
                    )
                cb_spec_kwargs = {
                    "spec": True,
                    "spec_k": int(
                        os.environ.get("WALKAI_CB_SPEC_K", "3")
                    ),
                    "draft_cfg": cb_draft_cfg,
                    "draft_params": cb_draft_params,
                }
            # Quantized serving (WALKAI_CB_KV_DTYPE /
            # WALKAI_LM_W_DTYPE ∈ model|int8|int8-sim): int8 paged KV
            # blocks with per-row scale pools, and/or int8 projection/
            # MLP weights dequantized on-chip — the engine quantizes
            # its own copy of the params, so the one-shot /generate
            # path keeps serving the full-precision tree. Applied to
            # the CB engine's config only (the dense one-shot cache
            # has no scale store); an unknown value fails HERE, at
            # LMConfig construction, with a bad_request-style
            # ValueError naming the knob — never as a jit crash
            # mid-traffic.
            import dataclasses as _dcq

            # Tensor-parallel serving (WALKAI_CB_TP=N): shard the CB
            # engine's decode step over N chips on the serving mesh's
            # model axis (models/serve.py). A degree the model's head/
            # MLP dims don't divide fails HERE, at LMConfig
            # construction, with the bad_request-style ValueError —
            # never as a jit crash mid-traffic. The one-shot path
            # stays single-device.
            cb_cfg = _dcq.replace(
                lm_cfg,
                kv_dtype=os.environ.get("WALKAI_CB_KV_DTYPE", "model"),
                w_dtype=os.environ.get("WALKAI_LM_W_DTYPE", "model"),
                tp_devices=int(os.environ.get("WALKAI_CB_TP", "1")),
            )
            if cb_spec_kwargs:
                cb_spec_kwargs["draft_cfg"] = _dcq.replace(
                    cb_spec_kwargs["draft_cfg"],
                    kv_dtype=cb_cfg.kv_dtype,
                    w_dtype=cb_cfg.w_dtype,
                )
            # Deterministic capture plane (obs/capture.py):
            # WALKAI_CAPTURE_DIR arms a bounded rotating on-disk
            # recorder of every accepted request + completion digest
            # behind the engine's config fingerprint —
            # `cmd/replay.py` re-executes it token-identically
            # offline. Served at /debug/capture (status / rotate /
            # download); WALKAI_CAPTURE_MAX_BYTES /
            # WALKAI_CAPTURE_MAX_FILES bound the ring.
            from walkai_nos_tpu.obs.capture import CaptureLog

            cb_capture = CaptureLog.from_env()
            # Batched multi-LoRA serving (WALKAI_CB_LORA=K,
            # models/lora.py): arm the paged batcher with K synthetic
            # low-rank adapters (rank bucket WALKAI_CB_LORA_RANK) so
            # /generate requests can pick a fine-tuned variant with
            # an `adapter` body field. Synthetic demo adapters: the
            # capture fingerprint records their recipe + digests, so
            # a LoRA-armed capture replays digest-exact with zero
            # stored adapter weights. Real adapter trees hot-load at
            # runtime via the engine's load_adapter seam.
            cb_lora_kwargs = {}
            cb_lora_k = int(os.environ.get("WALKAI_CB_LORA", "0"))
            if cb_lora_k > 0:
                from walkai_nos_tpu.models.lora import AdapterSet

                cb_lora_kwargs["adapters"] = AdapterSet.synthetic(
                    cb_cfg,
                    k=cb_lora_k,
                    rank=int(
                        os.environ.get("WALKAI_CB_LORA_RANK", "4")
                    ),
                )
            cb_engine = ContinuousBatcher(
                cb_cfg,
                lm_params,
                capture=cb_capture,
                slots=cb_slots,
                cache_len=cache_bucket(
                    cb_bucket + lm_max_new, lm_cfg.max_seq_len
                ),
                prompt_bucket=cb_bucket,
                # Chunk sweep on the tunneled v5e (serving bench,
                # Poisson load): chunk 8 -> 2.0k tok/s capacity,
                # TTFT p50 0.24 s; chunk 16 -> 3.1k, 0.31 s; chunk
                # 32 -> 4.6k, 0.93 s (admission waits a whole chunk).
                # 16 is the balanced default; on a local runtime the
                # chunk sync is ~free and smaller chunks cost little.
                chunk_steps=int(os.environ.get("WALKAI_CB_CHUNK", "16")),
                # Device-resident multi-step loop (models/serve.py):
                # WALKAI_CB_LOOP=0 disables the fold entirely;
                # WALKAI_CB_LOOP_STEPS sets how many chunks (or spec
                # rounds) one while_loop dispatch may fold whenever no
                # admission is pending. loop_steps=1 IS the disabled
                # path, bit for bit, so the gate just maps to it.
                loop_steps=(
                    int(os.environ.get("WALKAI_CB_LOOP_STEPS", "8"))
                    if os.environ.get("WALKAI_CB_LOOP", "1") == "1"
                    and os.environ.get("WALKAI_CB_PAGED", "1") == "1"
                    else 1
                ),
                # Paged KV block pool + fused chunked-prefill lane
                # (models/serve.py): admission rides the step program
                # instead of blocking prefill+admit dispatch pairs.
                paged=os.environ.get("WALKAI_CB_PAGED", "1") == "1",
                prefill_lanes=int(os.environ.get("WALKAI_CB_LANES", "4")),
                prefill_chunk=int(
                    os.environ.get("WALKAI_CB_PFCHUNK", "64")
                ),
                # Sequence-parallel prefill lane (WALKAI_CB_SP=1):
                # prompts at least WALKAI_CB_SP_MIN tokens spread
                # their chunk windows across up to WALKAI_CB_SP_SPAN
                # lane rows per dispatch, and admission holds a long
                # prompt while another is prefilling so short-prompt
                # decode tails keep their lane slots. Token-identical
                # to sp off.
                sp_prefill=os.environ.get("WALKAI_CB_SP") == "1",
                sp_min_tokens=int(
                    os.environ.get("WALKAI_CB_SP_MIN", "2048")
                ),
                sp_span=int(os.environ.get("WALKAI_CB_SP_SPAN", "0")),
                # Shared-prefix KV reuse (models/prefix_cache.py):
                # templated prompts share refcounted prefix blocks and
                # skip their prefill. 0 restores the exclusive pool
                # (the bench's cold-start baseline arm).
                prefix_cache=os.environ.get(
                    "WALKAI_CB_PREFIX_CACHE", "1"
                ) == "1",
                **cb_spec_kwargs,
                **cb_slo_kwargs,
                **cb_lora_kwargs,
                obs=obs,
            )
            # Compile prefill + chunk step (and, with loop_steps > 1,
            # the device-resident loop program) off the request path:
            # the engine's own pow2 admission-burst discipline, so
            # every lane-width signature compiles NOW instead of
            # stalling the driver for seconds of XLA compile on the
            # first concurrent admissions mid-traffic (measured ~6 s
            # on a CPU dev box — long enough to zero a short capacity
            # probe's window).
            cb_engine.warm(max_new_tokens=min(2, lm_max_new))
            cb_queue = queue.Queue()
            cb_waiters: dict[int, dict] = {}
            cb_enabled[0] = True

            def cb_fail_waiter(holder, error=None) -> None:
                """Failure notification, one definition: tokens=None
                (the handlers' failure marker), optional error text,
                end-of-stream sentinel for SSE waiters, then wake.
                Engine-death failures (no error text: the submit-time
                rejects count themselves) land in the error taxonomy
                as engine_failure."""
                if error is not None:
                    holder["error"] = error
                else:
                    obs.errors.inc(labels={"reason": "engine_failure"})
                holder["tokens"] = None
                if holder.get("queue") is not None:
                    holder["queue"].put(None)
                holder["done"].set()

            def cb_driver() -> None:
                """Single thread owning the engine: drains submissions
                (blocking when idle), steps the batch, fulfils
                responses as requests finish. A device error (e.g. a
                co-tenant OOM spike) must not silently strand every
                waiter on a dead thread: fail what's pending, flip the
                endpoint to the serialized fallback, and exit — the
                blast radius is the in-flight batch, like one failed
                request on the serialized path."""
                try:
                    while True:
                        try:
                            item = cb_queue.get(
                                block=not cb_engine.has_work
                            )
                            while True:
                                prompt, max_new, knobs, holder = item
                                if (
                                    isinstance(prompt, str)
                                    and prompt == "__job__"
                                ):
                                    # Engine-plane job (the /blocks
                                    # transfer endpoint): runs on THE
                                    # thread that owns the engine, so
                                    # export/import never races a
                                    # step. `max_new` carries the
                                    # callable.
                                    try:
                                        holder["result"] = max_new(
                                            cb_engine
                                        )
                                    except Exception as err:  # noqa: BLE001
                                        holder["error"] = str(err)
                                    holder["done"].set()
                                    item = cb_queue.get_nowait()
                                    continue
                                try:
                                    rid = cb_engine.submit(
                                        prompt, max_new_tokens=max_new,
                                        **knobs,
                                    )
                                except ValueError as bad:
                                    # Bad per-request knobs fail THAT
                                    # request, never the engine thread.
                                    cb_fail_waiter(holder, str(bad))
                                else:
                                    cb_waiters[rid] = holder
                                item = cb_queue.get_nowait()
                        except queue.Empty:
                            pass
                        if cb_engine.has_work:
                            # Streaming consumers want per-chunk token
                            # cadence; the device-resident fold would
                            # batch their SSE events into loop-horizon
                            # bursts. Fold only while every waiter is
                            # a whole-response waiter.
                            cb_engine.step(allow_loop=not any(
                                w.get("queue") is not None
                                for w in cb_waiters.values()
                            ))
                        # Streaming feed: push newly visible tokens to
                        # SSE waiters as each chunk syncs.
                        for rid, delta in (
                            cb_engine.drain_new_tokens().items()
                        ):
                            w = cb_waiters.get(rid)
                            if w is not None and w.get("queue") is not None:
                                w["queue"].put(delta)
                        for rid, rec in (
                            cb_engine.drain_done_records().items()
                        ):
                            waiter = cb_waiters.pop(rid)
                            waiter["tokens"] = rec["tokens"]
                            waiter["ttft_s"] = rec["ttft_s"]
                            waiter["wall_s"] = rec["wall_s"]
                            waiter["truncated"] = rec.get(
                                "truncated", False
                            )
                            waiter["adapter"] = rec.get("adapter", 0)
                            if waiter.get("queue") is not None:
                                waiter["queue"].put(None)  # end of stream
                            waiter["done"].set()
                except Exception as e:  # noqa: BLE001
                    cb_enabled[0] = False
                    print(f"continuous batching disabled: {e!r}")
                    for waiter in cb_waiters.values():
                        cb_fail_waiter(waiter)
                    cb_waiters.clear()
                    while True:  # drain late submissions to the fallback
                        try:
                            _, _, _, holder = cb_queue.get_nowait()
                        except queue.Empty:
                            break
                        cb_fail_waiter(holder)

            threading.Thread(target=cb_driver, daemon=True).start()
            print(
                f"continuous batching enabled: {cb_slots} slots, "
                f"prompt bucket {cb_bucket}"
            )

    stats = _Stats()
    requests_q: "queue.Queue[_Request]" = queue.Queue()
    fence_q: "queue.Queue[_Dispatched]" = queue.Queue()
    inflight = threading.Semaphore(max_inflight)

    # A lone request waits only this long for company before dispatching:
    # keeps sequential (latency-probe-style) clients near-unbatched while
    # streaming load still gets the full coalesce window below.
    lone_wait_s = min(window_s, 1e-3)

    # Interactive QoS mode: while small (batch <= INTERACTIVE_MAX)
    # requests are arriving, bulk traffic must not bury them. An
    # interactive request's latency floor on a shared FIFO device is
    # one bulk compute QUANTUM (the batch executing when it arrives)
    # plus its own ride-along batch — so the dispatcher caps the bucket
    # (quantum 128 -> 32 cuts that floor 4x) and shrinks the coalesce
    # hold. Capping the in-flight COUNT was tried and measured WORSE:
    # slots free on ack (completion + fence RTT), so a depth gate
    # throttles dispatch to the ack rate and queues victims at the
    # gate. Pure-bulk periods (no interactive arrivals for QOS_IDLE_S)
    # run at the full bucket — the throughput benchmark's measure
    # window is unaffected.
    INTERACTIVE_MAX = int(os.environ.get("WALKAI_QOS_INTERACTIVE_MAX", "4"))
    QOS_BUCKET = int(os.environ.get("WALKAI_QOS_BUCKET", "32"))
    QOS_IDLE_S = 1.0
    last_interactive = [float("-inf")]

    def device_worker() -> None:
        """Single dispatcher: coalesce -> pad -> one async forward."""
        while True:
            stats.wait_started()
            first = requests_q.get()
            stats.wait_ended()
            qos = time.monotonic() - last_interactive[0] < QOS_IDLE_S
            eff_max = min(max_batch, QOS_BUCKET) if qos else max_batch
            eff_window = min(window_s, 2e-3) if qos else window_s
            batch_reqs = [first]
            total = first.n_images
            deadline = time.monotonic() + lone_wait_s
            extended = False
            while total < eff_max:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = requests_q.get(timeout=remaining)
                except queue.Empty:
                    break
                if total + nxt.n_images > eff_max:
                    requests_q.put(nxt)  # doesn't fit this tick
                    break
                batch_reqs.append(nxt)
                total += nxt.n_images
                if not extended:
                    # Company arrived: load is streaming, so it's worth
                    # holding the full window to fill the bucket.
                    deadline = time.monotonic() + eff_window
                    extended = True
            inflight.acquire()
            bucket = _bucket(total, max_batch)
            out = infer(params, images_of(bucket))
            stats.mark_dispatch()
            fence_q.put(_Dispatched(batch_reqs, total, bucket, out))

    def fencer() -> None:
        """Ack completed work: one dispatched batch per loop, fenced by
        a host fetch. A POOL of fencers runs so the fetch round-trips
        overlap: with a single drain-newest fencer, any batch landing
        mid-fence waited that whole cycle plus its own (~2 RTTs) —
        under a heavy co-tenant that was every interactive request's
        p99 (measured 2x degradation). Overlapped, an ack costs the
        batch's own completion plus one RTT regardless of what else is
        in flight. Device-order completion makes per-batch fencing
        exact; ack order across batches doesn't matter to HTTP waits."""
        while True:
            d = fence_q.get()
            _fence(d.output)
            stats.mark_fenced(1)
            now = time.monotonic()
            inflight.release()
            stats.record(
                d.n_images,
                len(d.requests),
                d.bucket - d.n_images,
                flops_per_image * d.n_images,
            )
            for r in d.requests:
                r.elapsed = now - r.arrived
                r.batched_with = d.n_images
                r.done.set()

    threading.Thread(target=device_worker, daemon=True).start()
    # Pool size 8: enough overlap that an ack costs completion + one
    # RTT (fence-thread demand is ~batch rate x RTT ~ 6), and no more —
    # a fencer per in-flight batch (24) was measured WORSE under
    # co-tenant load (more concurrent host fetches contending on the
    # GIL/tunnel raised victims' p50 by ~20%).
    for _ in range(min(8, max_inflight)):
        threading.Thread(target=fencer, daemon=True).start()

    from walkai_nos_tpu.utils.flops import roofline

    device_info = {
        "device_kind": device.device_kind,
        "device_count": jax.device_count(),
        "peak_bf16_flops": peak_bf16_flops(device.device_kind),
        "model_ceiling_images_per_s": ceiling_img_s,
        "fence_rtt_s": fence_rtt,
        "flops_per_image": flops_per_image,
        "bytes_per_image": bytes_per_image,
        # Which wall bounds the served model on this chip: memory
        # (intensity below the ridge) or compute — in which case any
        # MFU gap is occupancy/shape-bound, not a bandwidth story.
        "roofline": roofline(
            flops_per_image, bytes_per_image, device.device_kind
        ),
        "max_batch": max_batch,
        "slice": slice_id,
    }

    class Handler(BaseHTTPRequestHandler):
        # Keep-alive: without it every request pays a TCP handshake AND
        # a fresh server thread (ThreadingHTTPServer threads are
        # per-connection), which under ~100 concurrent pipelined clients
        # makes request arrival jitter the measured bottleneck. All
        # responses carry Content-Length, so 1.1 persistence is safe.
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            if self.path == "/generate":
                self._generate()
                return
            if self.path == "/blocks":
                self._blocks()
                return
            if self.path == "/debug/capture":
                # Capture-plane actions: {"action": "rotate"} closes
                # the current capture file and opens a fresh one (to
                # freeze an incident's tail before downloading it).
                cap = (
                    cb_engine.capture if cb_engine is not None
                    else None
                )
                if cap is None:
                    self.send_error(
                        404, "no capture armed (set WALKAI_CAPTURE_DIR)"
                    )
                    return
                from walkai_nos_tpu.obs.capture import (
                    rotate_action_from_body,
                )

                n = int(self.headers.get("Content-Length", 0))
                try:
                    rotate_action_from_body(self.rfile.read(n))
                except (TypeError, ValueError) as e:
                    self.send_error(400, str(e))
                    return
                cap.rotate()
                self._json(200, {"engine": cb_engine.capture_stats()})
                return
            if self.path == "/debug/profile":
                n = int(self.headers.get("Content-Length", 0))
                try:
                    # Malformed JSON and non-object bodies are client
                    # errors too (JSONDecodeError is a ValueError).
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                    obs.profile.arm(
                        int(body.get("dispatches", 20)),
                        body.get("logdir")
                        or os.environ.get(
                            "WALKAI_PROFILE_DIR", "/tmp/walkai-profile"
                        ),
                    )
                except (TypeError, ValueError, RuntimeError) as e:
                    self.send_error(400, str(e))
                    return
                self._json(200, obs.profile.status())
                return
            if self.path != "/infer":
                self.send_error(404)
                return
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            batch = max(1, min(int(body.get("batch", 1)), max_batch))
            req = _Request(n_images=batch, arrived=time.monotonic())
            if batch <= INTERACTIVE_MAX:
                last_interactive[0] = req.arrived
            requests_q.put(req)
            if not req.done.wait(timeout=120.0):
                self.send_error(503, "inference timed out")
                return
            self._json(
                200,
                {
                    "inference_time_seconds": req.elapsed,
                    "batched_with": req.batched_with,
                    "slice": slice_id,
                },
            )

        def _blocks(self):
            """KV block-transfer endpoint (the fleet router's ship
            seam over HTTP): {"action": "export", "hashes": [...]}
            serializes the named prefix blocks out of this pod's
            trie; {"action": "import", "payload": {...}} lands a
            peer's export in the pool + trie. Both run as
            driver-thread jobs — the transfer never races an engine
            step."""
            if cb_engine is None or not cb_enabled[0]:
                self.send_error(404, "continuous batching not enabled")
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                action = body.get("action")
                if action == "export":
                    hashes = body.get("hashes")
                    if not isinstance(hashes, list):
                        raise ValueError("hashes must be a list")

                    def job(eng, hashes=hashes):
                        return eng.export_blocks(hashes)
                elif action == "import":
                    payload = body.get("payload")
                    if not isinstance(payload, dict):
                        raise ValueError(
                            "payload must be a JSON object"
                        )

                    def job(eng, payload=payload):
                        return eng.import_blocks(payload)
                else:
                    raise ValueError(
                        "action must be 'export' or 'import'"
                    )
            except (TypeError, ValueError) as e:
                self.send_error(400, str(e))
                return
            holder = {"done": threading.Event()}
            cb_queue.put(("__job__", job, None, holder))
            t0 = time.perf_counter()
            while not holder["done"].wait(timeout=1.0):
                if not cb_enabled[0]:
                    self.send_error(503, "batch engine failed; retry")
                    return
                if time.perf_counter() - t0 > 120.0:
                    self.send_error(503, "block transfer timed out")
                    return
            if holder.get("error"):
                self.send_error(400, holder["error"])
                return
            if "result" not in holder:
                # The driver died mid-job (its death drain sets done
                # without a result).
                self.send_error(503, "batch engine failed; retry")
                return
            self._json(200, holder["result"])

        def _generate(self):
            if lm_generate is None:
                self.send_error(404, "set WALKAI_DEMO_LM=1 to enable")
                return
            import numpy as np

            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            # The request's cross-process trace id: the router's
            # X-Walkai-Trace header (or a body field), else minted
            # here. Echoed on every success response (header + JSON)
            # and stored on the engine submit so the engine's
            # lifecycle spans carry it into /debug/trace.
            trace_id = request_trace_id(
                self.headers.get("X-Walkai-Trace"),
                body.get("trace_id"),
            )
            prompt = body.get("prompt")
            speculative = bool(body.get("speculative"))
            if speculative and lm_spec_generate is None:
                self.send_error(404, "set WALKAI_DEMO_SPEC=1 to enable")
                return
            if not isinstance(prompt, list) or not prompt:
                self.send_error(400, "prompt must be a non-empty list")
                return
            # The speculative round verifies up to k positions past the
            # last emitted token, so its position budget is tighter.
            budget = lm_max_new + (spec_k if speculative else 0)
            if len(prompt) + budget > lm_cfg.max_seq_len:
                self.send_error(
                    400,
                    f"prompt {len(prompt)} + {budget} positions "
                    f"exceeds max_seq_len {lm_cfg.max_seq_len}",
                )
                return
            if any(
                not isinstance(t, int) or not 0 <= t < lm_cfg.vocab_size
                for t in prompt
            ):
                self.send_error(400, "prompt tokens out of vocab range")
                return
            try:
                knobs = {
                    "temperature": float(body.get("temperature", 0.0)),
                    "top_k": int(body.get("top_k", 0)),
                    "top_p": float(body.get("top_p", 1.0)),
                }
                if body.get("seed") is not None:
                    knobs["seed"] = int(body["seed"])
                if body.get("adapter") is not None:
                    # Multi-LoRA adapter selection (WALKAI_CB_LORA):
                    # validated engine-side — an unknown id fails only
                    # this request (bad_request -> 400).
                    knobs["adapter"] = int(body["adapter"])
                req_max_new = (
                    int(body["max_new_tokens"])
                    if body.get("max_new_tokens") is not None else None
                )
                req_eos = (
                    int(body["eos_id"])
                    if body.get("eos_id") is not None else None
                )
            except (TypeError, ValueError):
                self.send_error(400, "malformed sampling knobs")
                return
            if req_max_new is not None and not 1 <= req_max_new <= lm_max_new:
                self.send_error(
                    400,
                    f"max_new_tokens must be in [1, {lm_max_new}]",
                )
                return
            if req_eos is not None and not 0 <= req_eos < lm_cfg.vocab_size:
                self.send_error(400, "eos_id out of vocab range")
                return
            req_stream = bool(body.get("stream"))
            wants_sampling = (
                knobs["temperature"] != 0.0
                or knobs["top_k"] != 0
                or knobs["top_p"] != 1.0
                or "seed" in knobs
                # Adapter routing exists only on the batched engine —
                # the serialized fallback would silently serve BASE
                # weights for a fine-tuned tenant's request.
                or "adapter" in knobs
                # Per-request budget/EOS/streaming ride the slot pool:
                # the one-shot paths compile per max_new signature,
                # have no EOS scan, and produce tokens all at once.
                or req_max_new is not None
                or req_eos is not None
                or req_stream
            )
            on_batched_path = (
                not speculative
                and cb_engine is not None
                and cb_enabled[0]
                # Any prompt whose footprint fits the engine cache is
                # served by the slot pool: the paged engine streams
                # long prompts through the chunked-prefill lane, and
                # the dense engine buckets them to the next power of
                # two — over-bucket prompts are no longer bounced to
                # the serialized path.
                and len(prompt) + (req_max_new or lm_max_new)
                <= cb_engine.cache_len
            )
            if wants_sampling and not on_batched_path:
                # Never silently return greedy tokens for a sampling
                # request: the serialized fallback and the speculative
                # path are greedy-only.
                self.send_error(
                    400,
                    "sampling knobs are served by the batched path "
                    "only (greedy fallback: speculative, over-bucket "
                    "prompt, or batching disabled)",
                )
                return
            if on_batched_path:
                # Continuous batching: join the running slot pool.
                # (Prompts longer than the bucket fall through to the
                # serialized path below — one compiled program per
                # bucket is the static-shape discipline.) Per-request
                # sampling knobs ride along; the engine validates them
                # and a bad value fails only this request (400).
                if req_eos is not None:
                    knobs["eos_id"] = req_eos
                knobs["trace_id"] = trace_id
                if req_stream:
                    self._generate_stream(
                        prompt, knobs, req_max_new, trace_id
                    )
                    return
                waiter = {"done": threading.Event()}
                t0 = time.perf_counter()
                cb_queue.put(
                    (prompt, req_max_new or lm_max_new, knobs, waiter)
                )
                # Re-check the enabled flag while waiting: a request
                # enqueued just as the driver dies can miss its final
                # queue drain and would otherwise burn the whole
                # timeout before failing.
                while not waiter["done"].wait(timeout=1.0):
                    if not cb_enabled[0]:
                        self.send_error(503, "batch engine failed; retry")
                        return
                    if time.perf_counter() - t0 > 120.0:
                        obs.errors.inc(
                            labels={"reason": "generation_timeout"}
                        )
                        self.send_error(503, "generation timed out")
                        return
                if waiter["tokens"] is None:
                    if waiter.get("error"):  # rejected knobs
                        self.send_error(400, waiter["error"])
                        return
                    self.send_error(503, "batch engine failed; retry")
                    return
                dt = time.perf_counter() - t0
                try:
                    self._json(200, {
                        "trace_id": trace_id,
                        # The engine's config-fingerprint id (None
                        # while no capture is armed): match this
                        # completion to the capture that can replay
                        # it (`/debug/capture`, cmd/replay.py).
                        "fingerprint": cb_engine.fingerprint_id,
                        "tokens": waiter["tokens"],
                        "generate_time_seconds": round(dt, 6),
                        "ttft_seconds": round(
                            waiter.get("ttft_s", 0.0), 6
                        ),
                        # Engine-side wall (submit -> done, same clock
                        # origin as ttft_seconds): lets clients
                        # separate queueing from decode pace.
                        "engine_wall_seconds": round(
                            waiter.get("wall_s", 0.0), 6
                        ),
                        "tokens_per_second": round(
                            len(waiter["tokens"]) / dt, 1
                        ),
                        "slice": slice_id,
                        "batched": True,
                        "cb_slots": cb_slots,
                        # True when the output was cut at a KV-pool
                        # boundary (engine pool_overflow truncation) —
                        # fewer tokens than requested is then a
                        # capacity signal, not a natural completion.
                        "truncated": waiter.get("truncated", False),
                        # Which LoRA adapter served it (0 = base) —
                        # per-tenant attribution for router captures.
                        "adapter": waiter.get("adapter", 0),
                    }, headers={"X-Walkai-Trace": trace_id})
                except (BrokenPipeError, ConnectionResetError):
                    # Client gave up before the response: the work was
                    # done and discarded — that's a served-for-nothing
                    # request the error mix must show.
                    obs.errors.inc(
                        labels={"reason": "client_disconnect"}
                    )
                return
            arr = jnp.asarray([prompt], jnp.int32)
            # Serialized: one generation at a time keeps decode latency
            # predictable next to the vision dispatcher. A new prompt
            # length compiles on first use.
            extra = {}
            with lm_lock:
                t0 = time.perf_counter()
                if speculative:
                    out, sstats = lm_spec_generate(
                        lm_params, spec_draft_params, arr, lm_max_new
                    )
                    hist = np.asarray(sstats["acceptance_hist"])
                    rounds = int(hist.sum())
                    accepted = float(
                        (np.arange(spec_k + 1) * hist).sum()
                    )
                    extra = {
                        "speculative": True,
                        "spec_k": spec_k,
                        "acceptance_rate": round(
                            accepted / max(1, rounds * spec_k), 4
                        ),
                        "tokens_per_round": round(
                            (accepted + rounds) / max(1, rounds), 2
                        ),
                    }
                else:
                    out = lm_generate(
                        lm_params, arr, max_new_tokens=lm_max_new
                    )
                tokens = np.asarray(out)[0].tolist()  # fenced by fetch
                dt = time.perf_counter() - t0
            self._json(200, {
                "trace_id": trace_id,
                "tokens": tokens,
                "generate_time_seconds": round(dt, 6),
                "tokens_per_second": round(lm_max_new / dt, 1),
                "slice": slice_id,
                **extra,
            }, headers={"X-Walkai-Trace": trace_id})

        def _generate_stream(self, prompt, knobs, req_max_new, trace_id):
            """Server-sent events: tokens stream as each engine chunk
            syncs (up to chunk_steps per event), then a final event
            with the request telemetry. The connection closes at end
            of stream (no Content-Length on an open-ended body)."""
            waiter = {
                "done": threading.Event(),
                "queue": queue.SimpleQueue(),
            }
            t0 = time.perf_counter()
            cb_queue.put(
                (prompt, req_max_new or lm_max_new, knobs, waiter)
            )
            # Hold the status line until the FIRST queue item: the
            # engine's submit-time validation runs in the driver
            # thread, and a rejected request must fail with the same
            # 400 the non-streaming path returns — not a 200 wearing
            # an SSE error event.
            while True:
                try:
                    item = waiter["queue"].get(timeout=1.0)
                    break
                except queue.Empty:
                    if not cb_enabled[0]:
                        self.send_error(503, "batch engine failed; retry")
                        return
                    if time.perf_counter() - t0 > 120.0:
                        obs.errors.inc(
                            labels={"reason": "generation_timeout"}
                        )
                        self.send_error(503, "generation timed out")
                        return
            if item is None and waiter.get("error"):
                self.send_error(400, waiter["error"])
                return
            if item is None and waiter.get("tokens") is None:
                self.send_error(503, "batch engine failed; retry")
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.send_header("X-Walkai-Trace", trace_id)
            self.end_headers()
            self.close_connection = True

            def event(payload: dict) -> None:
                self.wfile.write(
                    b"data: " + json.dumps(payload).encode() + b"\n\n"
                )
                self.wfile.flush()

            try:
                while True:
                    if item is None:  # end of stream (or failure)
                        if waiter.get("tokens") is None:
                            event({"error": "batch engine failed; retry"})
                        else:
                            event({
                                "done": True,
                                "trace_id": trace_id,
                                "fingerprint": cb_engine.fingerprint_id,
                                "n_tokens": len(waiter["tokens"]),
                                "ttft_seconds": round(
                                    waiter.get("ttft_s", 0.0), 6
                                ),
                                "engine_wall_seconds": round(
                                    waiter.get("wall_s", 0.0), 6
                                ),
                                "slice": slice_id,
                                "batched": True,
                                "truncated": waiter.get(
                                    "truncated", False
                                ),
                                "adapter": waiter.get("adapter", 0),
                            })
                        return
                    event({"tokens": item})
                    while True:
                        try:
                            item = waiter["queue"].get(timeout=1.0)
                            break
                        except queue.Empty:
                            if not cb_enabled[0]:
                                event({
                                    "error": "batch engine failed; retry"
                                })
                                return
                            if time.perf_counter() - t0 > 120.0:
                                obs.errors.inc(
                                    labels={
                                        "reason": "generation_timeout"
                                    }
                                )
                                event({"error": "generation timed out"})
                                return
            except (BrokenPipeError, ConnectionResetError):
                # Client went away mid-stream: the engine finishes the
                # request on its own; nothing to clean up here beyond
                # recording the disconnect in the error taxonomy.
                obs.errors.inc(labels={"reason": "client_disconnect"})

        def do_GET(self):
            if self.path == "/healthz":
                # Readiness, not bare liveness: a probe (or operator)
                # sees whether the engine loop is alive and moving.
                # `monotonic_s` is this process's clock read at
                # response build: the fleet router's probe estimates
                # this replica's clock offset from it (NTP-style, at
                # the probe's RTT midpoint) to align /debug/trace
                # timelines across processes.
                self._json(200, {
                    "ok": True,
                    "monotonic_s": time.monotonic(),
                    "engine": engine_health(cb_engine, cb_enabled[0]),
                })
            elif self.path == "/metrics":
                data = obs.registry.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/debug/trace":
                self._json(200, obs.trace.chrome_trace())
            elif self.path == "/debug/profile":
                self._json(200, obs.profile.status())
            elif self.path == "/debug/state":
                # One fenced engine snapshot: slots, block pool,
                # prefix trie, spec controller, attribution, SLO
                # windows — the whole engine in a single read
                # (engine null when continuous batching is off).
                self._json(200, {
                    "engine": (
                        cb_engine.debug_state()
                        if cb_engine is not None else None
                    ),
                })
            elif self.path == "/debug/slo":
                self._json(200, {
                    "engine": (
                        cb_engine.slo_stats()
                        if cb_engine is not None else None
                    ),
                })
            elif self.path == "/debug/capture":
                # Capture-plane status: armed/dir/file ring, record
                # and byte tallies, drops, and the config-fingerprint
                # id every completion record carries (engine null
                # when continuous batching is off).
                self._json(200, {
                    "engine": (
                        cb_engine.capture_stats()
                        if cb_engine is not None else None
                    ),
                })
            elif self.path == "/debug/capture/download":
                cap = (
                    cb_engine.capture if cb_engine is not None
                    else None
                )
                if cap is None:
                    self.send_error(
                        404, "no capture armed (set WALKAI_CAPTURE_DIR)"
                    )
                    return
                # Every retained file concatenated, oldest first:
                # each carries its own fingerprint header, so the
                # download parses as ONE capture — save it and hand
                # it to `python -m walkai_nos_tpu.cmd.replay`.
                data = cap.read_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/stats":
                payload = {**stats.snapshot(), **device_info}
                if cb_engine is not None:
                    payload["cb_occupancy"] = cb_engine.occupancy()
                    payload["cb_kv"] = cb_engine.kv_stats()
                    payload["cb_prefix"] = cb_engine.prefix_stats()
                    payload["cb_spec"] = cb_engine.spec_stats()
                    payload["cb_slo"] = cb_engine.slo_stats()
                    payload["cb_attrib"] = cb_engine.attrib_stats()
                    payload["cb_loop"] = cb_engine.loop_stats()
                    payload["cb_quant"] = cb_engine.quant_stats()
                    payload["cb_tp"] = cb_engine.tp_stats()
                    payload["cb_sp"] = cb_engine.sp_stats()
                    payload["cb_lora"] = cb_engine.lora_stats()
                self._json(200, payload)
            else:
                self.send_error(404)

        def _json(self, code, payload, headers=None):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):
            pass

    class Server(ThreadingHTTPServer):
        # Many clients reconnect per request; the stdlib default backlog
        # of 5 drops connections under burst load.
        request_queue_size = 128
        daemon_threads = True

    port = int(os.environ.get("PORT", "8000"))
    Server(("0.0.0.0", port), Handler).serve_forever()


if __name__ == "__main__":
    main()

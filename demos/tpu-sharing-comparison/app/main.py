"""Inference server pod for the TPU sharing-comparison demo.

TPU-native rebuild of the reference's demo workload
(`demos/gpu-sharing-comparison/app/main.py`, a torch YOLOS-small HTTP
server): serves the flagship YOLOS-style ViT over HTTP on whatever slice
the device plugin granted this pod (TPU_VISIBLE_CHIPS et al. are injected
by the walkai device plugin at Allocate time).

POST /infer with a JSON body {"batch": N} runs one jitted forward pass;
GET /healthz for probes.
"""

from __future__ import annotations

import json
import os
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def main() -> None:
    import jax
    import jax.numpy as jnp

    from walkai_nos_tpu.models.train import make_infer_step
    from walkai_nos_tpu.models.vit import VIT_SMALL, ViTDetector

    cfg = VIT_SMALL
    params = jax.device_put(
        ViTDetector(cfg).init_params(jax.random.PRNGKey(0))
    )
    infer = make_infer_step(cfg)
    warm = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    jax.block_until_ready(infer(params, warm))
    slice_id = os.environ.get("TPU_SLICE_ID", "whole-host")
    print(f"serving on slice {slice_id} with {jax.device_count()} device(s)")

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            if self.path != "/infer":
                self.send_error(404)
                return
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            batch = int(body.get("batch", 1))
            images = jnp.zeros(
                (batch, cfg.image_size, cfg.image_size, 3), jnp.float32
            )
            t0 = time.perf_counter()
            jax.block_until_ready(infer(params, images))
            elapsed = time.perf_counter() - t0
            payload = json.dumps(
                {"inference_time_seconds": elapsed, "slice": slice_id}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path == "/healthz":
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")
            else:
                self.send_error(404)

        def log_message(self, *args):
            pass

    port = int(os.environ.get("PORT", "8000"))
    ThreadingHTTPServer(("0.0.0.0", port), Handler).serve_forever()


if __name__ == "__main__":
    main()

"""Fluent builders for dict-shaped k8s test objects.

The `pkg/test/factory/core_factory.go:27-229` analogue: composable
Node/Pod builders so tests read as scenarios, not YAML blobs.
"""

from __future__ import annotations

from walkai_nos_tpu.api import constants


class NodeBuilder:
    def __init__(self, name: str):
        self._obj: dict = {
            "metadata": {"name": name, "labels": {}, "annotations": {}},
            "status": {"capacity": {}, "allocatable": {}},
        }

    def with_label(self, key: str, value: str) -> "NodeBuilder":
        self._obj["metadata"]["labels"][key] = value
        return self

    def with_annotation(self, key: str, value: str) -> "NodeBuilder":
        self._obj["metadata"]["annotations"][key] = value
        return self

    def with_tpu_model(
        self, accelerator: str = "tpu-v5-lite-podslice", topology: str = "2x4"
    ) -> "NodeBuilder":
        return self.with_label(
            constants.LABEL_TPU_ACCELERATOR, accelerator
        ).with_label(constants.LABEL_TPU_TOPOLOGY, topology)

    def with_tiling_enabled(self) -> "NodeBuilder":
        return self.with_label(constants.LABEL_TPU_PARTITIONING, "tiling")

    def with_allocatable(self, resource: str, qty: str) -> "NodeBuilder":
        self._obj["status"]["allocatable"][resource] = qty
        self._obj["status"]["capacity"][resource] = qty
        return self

    def build(self) -> dict:
        import copy

        return copy.deepcopy(self._obj)


class PodBuilder:
    def __init__(self, name: str, namespace: str = "default"):
        self._obj: dict = {
            "metadata": {"name": name, "namespace": namespace, "labels": {}},
            "spec": {"containers": []},
            "status": {"phase": "Pending"},
        }

    def with_container(
        self, name: str = "main", requests: dict | None = None
    ) -> "PodBuilder":
        container: dict = {"name": name}
        if requests:
            container["resources"] = {"requests": dict(requests)}
        self._obj["spec"]["containers"].append(container)
        return self

    def with_slice_request(self, profile: str, qty: int = 1) -> "PodBuilder":
        return self.with_container(
            f"c{len(self._obj['spec']['containers'])}",
            {constants.RESOURCE_TPU_SLICE_PREFIX + profile: str(qty)},
        )

    def with_phase(self, phase: str) -> "PodBuilder":
        self._obj["status"]["phase"] = phase
        return self

    def scheduled_on(self, node: str) -> "PodBuilder":
        self._obj["spec"]["nodeName"] = node
        return self

    def unschedulable(self) -> "PodBuilder":
        self._obj["status"].setdefault("conditions", []).append(
            {
                "type": "PodScheduled",
                "status": "False",
                "reason": "Unschedulable",
            }
        )
        return self

    def preempting(self, node: str = "some-node") -> "PodBuilder":
        self._obj["status"]["nominatedNodeName"] = node
        return self

    def owned_by(self, kind: str, name: str = "owner") -> "PodBuilder":
        self._obj["metadata"].setdefault("ownerReferences", []).append(
            {"kind": kind, "name": name, "apiVersion": "apps/v1"}
        )
        return self

    def with_priority(self, priority: int) -> "PodBuilder":
        self._obj["spec"]["priority"] = priority
        return self

    def build(self) -> dict:
        import copy

        return copy.deepcopy(self._obj)

"""Deterministic capture & replay plane (obs/capture.py, sim/replay.py,
cmd/replay.py, hack/replay_check.py).

The engine's defining invariant — output is a pure function of
(weights, prompt, knobs, seed) — made operational: a capture recorded
through a live engine replays token-identically offline across
spec/prefix/loop/tp axes; an injected config divergence is localized
to the correct first (request, token) with a readable flight bundle;
rotation bounds the on-disk ring; malformed files degrade to skipped
records, never crashes.
"""

import json
import os

import numpy as np
import pytest

import jax

from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher
from walkai_nos_tpu.obs.capture import (
    CaptureLog,
    fingerprint_id,
    token_digest,
    tree_crc32,
)
from walkai_nos_tpu.sim.replay import (
    build_config,
    load_capture,
    replay_capture,
    triage_divergence,
)

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
    max_seq_len=320, dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


def _mixed_traffic(engine):
    """Deterministic mixed greedy/sampled ragged submissions, one
    prompt crossing the 128-row block boundary, EOS-terminating
    budgets — the workload shape every replay axis must reproduce."""
    rng = np.random.default_rng(0)
    rids = []
    for plen, temperature in (
        (3, 0.0), (140, 0.0), (5, 1.0), (9, 1.0), (130, 1.0), (4, 0.0),
    ):
        rids.append(engine.submit(
            rng.integers(0, CFG.vocab_size, plen).tolist(),
            max_new_tokens=int(rng.integers(3, 9)),
            eos_id=3,
            temperature=temperature,
        ))
    return rids


@pytest.fixture(scope="module")
def capture_run(params, tmp_path_factory):
    """ONE captured run shared by the whole replay matrix (each
    replay builds its own engine; the capture itself need only be
    recorded once)."""
    d = str(tmp_path_factory.mktemp("capture"))
    engine = ContinuousBatcher(
        CFG, params, slots=2, cache_len=256, prompt_bucket=16,
        chunk_steps=2, capture=d,
    )
    _mixed_traffic(engine)
    records: dict[int, dict] = {}
    while engine.has_work:
        engine.step()
        records.update(engine.drain_done_records())
    records.update(engine.drain_done_records())
    return {
        "dir": d,
        "records": records,
        "fingerprint": engine.config_fingerprint(),
    }


class TestCaptureLog:
    def test_rotation_bounds_the_ring(self, tmp_path):
        log = CaptureLog(str(tmp_path), max_bytes=400, max_files=2)
        log.attach({"id": "t" * 12})
        for i in range(60):
            log.record_submit(rid=i, prompt=[1, 2, 3], arrival_s=0.0)
        stats = log.stats()
        assert len(stats["files"]) <= 2
        assert stats["dropped"]["rotated"] > 0
        # What survived still parses as one capture (headers agree).
        cap = load_capture(str(tmp_path))
        assert cap.fingerprint["id"] == "t" * 12
        assert len(cap.records) + cap.skipped <= 60
        assert len(cap.records) >= 1

    def test_every_file_carries_a_header(self, tmp_path):
        log = CaptureLog(str(tmp_path), max_bytes=300, max_files=8)
        log.attach({"id": "h" * 12})
        for i in range(30):
            log.record_submit(rid=i, prompt=[7], arrival_s=float(i))
        for path in log.files():
            with open(path) as f:
                first = json.loads(f.readline())
            assert first["kind"] == "header"
            assert first["fingerprint"]["id"] == "h" * 12

    def test_rotate_endpoint_semantics(self, tmp_path):
        log = CaptureLog(str(tmp_path))
        log.attach({"id": "r" * 12})
        log.record_submit(rid=0, prompt=[1], arrival_s=0.0)
        n0 = len(log.stats()["files"])
        log.rotate()
        assert len(log.stats()["files"]) == n0 + 1

    def test_malformed_lines_skipped_not_fatal(self, tmp_path):
        log = CaptureLog(str(tmp_path))
        log.attach({"id": "m" * 12})
        log.record_submit(rid=0, prompt=[1], max_new_tokens=2,
                          arrival_s=0.0)
        log.record_done(rid=0, tokens=[5, 6], digest=token_digest([5, 6]))
        path = log.files()[0]
        with open(path, "a") as f:
            f.write("{not json\n")
            f.write('{"kind": "mystery"}\n')
            f.write('{"kind": "done", "rid": 99, "tokens": [1]}\n')
        cap = load_capture(str(tmp_path))
        # 2 malformed/unknown lines + 1 orphan done (no submit).
        assert cap.skipped == 3
        assert len(cap.records) == 1
        assert cap.records[0].tokens == [5, 6]

    def test_missing_capture_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_capture(str(tmp_path / "nope"))

    def test_headerless_file_rejected(self, tmp_path):
        p = tmp_path / "capture-000001.jsonl"
        p.write_text('{"kind": "submit", "rid": 0}\n')
        with pytest.raises(ValueError, match="header"):
            load_capture(str(tmp_path))

    def test_unwritable_dir_never_raises(self, tmp_path):
        """The recorder must never take serving down: a capture dir
        that cannot be created (path occupied by a FILE) degrades to
        counted write_error drops, not an exception on the engine's
        driver thread."""
        blocker = tmp_path / "occupied"
        blocker.write_text("not a directory")
        log = CaptureLog(str(blocker))
        log.attach({"id": "x" * 12})  # open fails silently
        log.record_submit(rid=0, prompt=[1], arrival_s=0.0)
        log.record_done(rid=0, tokens=[2], digest=token_digest([2]))
        stats = log.stats()
        assert stats["dropped"]["write_error"] == 2
        assert stats["records"] == {"submit": 0, "done": 0}

    def test_multi_run_capture_is_split_not_merged(self, tmp_path):
        """A capture dir spanning a server restart holds two runs
        whose request ids both start at 0: they must never merge (a
        run-1 done pairing a run-2 submit would produce false
        verdicts). Default selection is the LATEST run; --run style
        selection reaches earlier ones."""
        first = CaptureLog(str(tmp_path))
        first.attach({"id": "f" * 12})
        first.record_submit(rid=0, prompt=[1, 1], max_new_tokens=2,
                            arrival_s=0.0)
        first.record_done(rid=0, tokens=[5, 6],
                          digest=token_digest([5, 6]))
        second = CaptureLog(str(tmp_path))  # the restarted server
        second.attach({"id": "f" * 12})
        second.record_submit(rid=0, prompt=[2, 2], max_new_tokens=2,
                             arrival_s=0.0)
        second.record_done(rid=0, tokens=[7, 8],
                           digest=token_digest([7, 8]))
        latest = load_capture(str(tmp_path))
        assert latest.runs == 2 and latest.run == 1
        assert len(latest.records) == 1
        assert latest.records[0].tokens == [7, 8]
        earlier = load_capture(str(tmp_path), run=0)
        assert earlier.records[0].tokens == [5, 6]
        with pytest.raises(ValueError, match="out of range"):
            load_capture(str(tmp_path), run=5)

    def test_failed_header_write_closes_fd_and_removes_stray(
        self, tmp_path, monkeypatch
    ):
        """ENOSPC-shaped failure: the exclusive create succeeds
        (metadata) but the header write raises. The fd must close and
        the stray empty file must go — otherwise every record leaks
        one fd + one file until the SERVER hits EMFILE."""
        import builtins

        log = CaptureLog(str(tmp_path))
        log.attach({"id": "e" * 12})
        closed = []

        class _BadFile:
            def write(self, s):
                raise OSError("no space left on device")

            def flush(self):
                pass

            def close(self):
                closed.append(True)

        real_open = builtins.open

        def fake_open(path, mode="r", *a, **k):
            if mode == "x":
                real_open(path, "x").close()  # metadata succeeds
                return _BadFile()
            return real_open(path, mode, *a, **k)

        monkeypatch.setattr(builtins, "open", fake_open)
        n_files_before = len(log.stats()["files"])
        for _ in range(3):
            log.rotate()
            log.record_submit(rid=0, prompt=[1], arrival_s=0.0)
        monkeypatch.undo()
        assert len(closed) >= 3  # every failed open's fd closed
        stats = log.stats()
        assert stats["dropped"]["write_error"] == 3
        # No unbounded stray-file growth while the disk is sick.
        assert len(stats["files"]) <= n_files_before

    def test_prune_spares_foreign_ring_and_counts_expired(
        self, tmp_path
    ):
        """The ring bound applies to files THIS instance wrote: a
        shared dir's foreign files (a possibly-live overlapping
        writer, or dead runs) are never pruned inside 2x the ring —
        and when dead runs DO expire, their records are counted as
        drops (parsed from the file) instead of silently vanishing."""
        header = '{"kind": "header", "fingerprint": {"id": "%s"}}\n'
        submit = '{"kind": "submit", "rid": %d, "prompt": [1]}\n'
        # Two foreign files (an overlapping writer's ring).
        for i in (1, 2):
            (tmp_path / f"capture-{i:06d}.jsonl").write_text(
                header % ("o" * 12) + submit % 0 + submit % 1
            )
        log = CaptureLog(str(tmp_path), max_bytes=200, max_files=2)
        log.attach({"id": "n" * 12})
        for i in range(10):  # several rotations of our own ring
            log.record_submit(rid=i, prompt=[2, 3], arrival_s=0.0)
        stats = log.stats()
        # Own ring bounded; both foreign files survive (global count
        # own 2 + foreign 2 == 2 * max_files, never above it).
        assert (tmp_path / "capture-000001.jsonl").exists()
        assert (tmp_path / "capture-000002.jsonl").exists()
        own_dropped = stats["dropped"]["rotated"]
        assert own_dropped > 0  # our rotations did prune our files
        # Three more dead-run files push the dir past 2x the ring:
        # oldest foreign files expire, their records counted.
        for i in (3, 4, 5):
            (tmp_path / f"capture-1{i:05d}.jsonl").write_text(
                header % ("d" * 12) + submit % 0 + submit % 1
            )
        log.rotate()
        stats = log.stats()
        assert len(stats["files"]) <= 2 * log.max_files
        assert stats["dropped"]["rotated"] >= own_dropped + 2

    def test_from_env_is_the_one_arming_rule(self, tmp_path):
        env = {
            "WALKAI_CAPTURE_DIR": str(tmp_path),
            "WALKAI_CAPTURE_MAX_BYTES": "1234",
            "WALKAI_CAPTURE_MAX_FILES": "7",
        }
        log = CaptureLog.from_env(env)
        assert log.dir == str(tmp_path)
        assert log.max_bytes == 1234
        assert log.max_files == 7
        assert CaptureLog.from_env({}) is None

    def test_concurrent_process_never_truncates_a_live_file(
        self, tmp_path
    ):
        """Two processes sharing one capture dir (rolling-restart
        overlap): exclusive create must bump past an existing file
        instead of truncating it."""
        victim = tmp_path / "capture-000001.jsonl"
        victim.write_text('{"kind": "header", "fingerprint": '
                          '{"id": "aaaaaaaaaaaa"}}\n')
        log = CaptureLog(str(tmp_path))  # next seq would be 2
        (tmp_path / "capture-000002.jsonl").write_text("other live\n")
        log.attach({"id": "b" * 12})
        log.record_submit(rid=0, prompt=[1], arrival_s=0.0)
        assert (tmp_path / "capture-000002.jsonl").read_text() == (
            "other live\n"
        )
        assert victim.read_text().startswith('{"kind": "header"')
        assert log.stats()["files"][-1] == "capture-000003.jsonl"

    def test_router_capture_rejects_wrong_type(self, tmp_path):
        from walkai_nos_tpu.router.core import FleetRouter

        with pytest.raises(ValueError, match="capture must be"):
            FleetRouter([], capture=12345)

    def test_token_digest_discriminates(self):
        assert token_digest([1, 2, 3]) == token_digest([1, 2, 3])
        assert token_digest([1, 2, 3]) != token_digest([1, 2, 4])
        assert token_digest([]) != token_digest([0])


class TestFingerprint:
    def test_stable_across_rebuilds_and_sensitive_to_weights(
        self, params, capture_run
    ):
        fp = capture_run["fingerprint"]
        rebuilt = ContinuousBatcher(
            CFG, params, slots=2, cache_len=256, prompt_bucket=16,
            chunk_steps=2,
        ).config_fingerprint()
        assert rebuilt["id"] == fp["id"]
        params2 = DecoderLM(CFG).init_params(jax.random.PRNGKey(1))
        other = ContinuousBatcher(
            CFG, params2, slots=2, cache_len=256, prompt_bucket=16,
            chunk_steps=2,
        ).config_fingerprint()
        assert other["id"] != fp["id"]
        assert other["weights_crc32"] != fp["weights_crc32"]
        assert other["cfg"] == fp["cfg"]

    def test_id_ignores_only_the_id_field(self):
        fp = {"cfg": {"a": 1}, "engine": {"b": 2}}
        assert fingerprint_id(fp) == fingerprint_id({**fp, "id": "x"})
        assert fingerprint_id(fp) != fingerprint_id(
            {"cfg": {"a": 2}, "engine": {"b": 2}}
        )

    def test_tree_crc32_order_independent(self):
        a = {"x": np.ones(3, np.float32), "y": np.zeros(2, np.float32)}
        b = {"y": np.zeros(2, np.float32), "x": np.ones(3, np.float32)}
        assert tree_crc32(a) == tree_crc32(b)

    def test_done_records_carry_fingerprint(self, capture_run):
        fp_id = capture_run["fingerprint"]["id"]
        for rec in capture_run["records"].values():
            assert rec["fingerprint"] == fp_id

    def test_uncaptured_engine_skips_the_weights_gather(self, params):
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, prompt_bucket=8,
            chunk_steps=2,
        )
        assert engine.fingerprint_id is None
        engine.submit([1, 2], max_new_tokens=2)
        engine.run()
        recs = engine.drain_done_records()
        assert all(r["fingerprint"] is None for r in recs.values())
        assert engine.capture_stats() == {
            "enabled": False, "fingerprint": None,
        }


class TestRoundTrip:
    """A capture recorded through a live engine replays
    token-identically — same config AND across every determinism-
    preserving axis (the acceptance criterion's matrix)."""

    def test_capture_matches_live_run(self, capture_run):
        cap = load_capture(capture_run["dir"])
        assert cap.fingerprint["id"] == capture_run["fingerprint"]["id"]
        live = {
            rid: rec["tokens"]
            for rid, rec in capture_run["records"].items()
        }
        assert {r.rid: r.tokens for r in cap.records} == live
        for r in cap.records:
            assert r.digest == token_digest(r.tokens)
            assert r.seed == r.rid  # effective seed recorded

    @pytest.mark.parametrize(
        "overrides",
        [
            None,
            {"loop_steps": 8},
            {"prefix_cache": False},
            {"spec": True, "spec_min_accept": 0.0},
            {"kv_dtype": "int8-sim"},
            {"tp_devices": 2},
        ],
        ids=["same", "loop8", "prefix-off", "spec-untrained-draft",
             "int8-sim", "tp2"],
    )
    def test_replay_token_identical(
        self, capture_run, params, overrides
    ):
        cap = load_capture(capture_run["dir"])
        report = replay_capture(cap, params, overrides=overrides)
        assert report.ok, report.summary()
        assert report.n_verified == len(cap.records)
        for rec in cap.records:
            assert report.outcomes[rec.rid].tokens == rec.tokens

    def test_original_timing_replay(self, capture_run, params):
        cap = load_capture(capture_run["dir"])
        report = replay_capture(
            cap, params, timing="original", speed=1000.0
        )
        assert report.ok, report.summary()
        assert report.n_verified == len(cap.records)

    def test_truncated_record_verifies_by_prefix(
        self, capture_run, params, tmp_path
    ):
        """A pool-truncated completion's LENGTH is live pool
        pressure, not the serving function: replay (different
        pressure) may run past the captured cut. Either stream being
        a prefix of the other verifies; only a value divergence
        inside the common prefix is real."""
        src = load_capture(capture_run["dir"])
        lines = []
        chopped = False
        for path in src.files:
            for line in open(path):
                obj = json.loads(line)
                if (
                    not chopped and obj.get("kind") == "done"
                    and len(obj.get("tokens") or []) > 1
                ):
                    # Simulate a truncation the live run would have
                    # recorded: drop the tail, flag it.
                    obj["tokens"] = obj["tokens"][:-1]
                    obj["n_tokens"] = len(obj["tokens"])
                    obj["truncated"] = True
                    obj["reason"] = "pool_overflow"
                    chopped = True
                lines.append(json.dumps(obj))
        assert chopped
        edited = tmp_path / "capture-000001.jsonl"
        edited.write_text("\n".join(lines) + "\n")
        cap = load_capture(str(edited))
        report = replay_capture(cap, params)
        assert report.ok, report.summary()

    def test_unknown_override_rejected(self, capture_run):
        cap = load_capture(capture_run["dir"])
        with pytest.raises(ValueError, match="unknown override"):
            build_config(cap.fingerprint, {"not_a_knob": 1})


class TestDivergenceTriage:
    """An intentionally divergent replay (different weights) is
    localized to the correct first (request, token) and dumped as a
    readable flight bundle."""

    @pytest.fixture(scope="class")
    def divergent(self, capture_run, tmp_path_factory):
        params2 = DecoderLM(CFG).init_params(jax.random.PRNGKey(1))
        cap = load_capture(capture_run["dir"])
        report = replay_capture(cap, params2)
        flight_dir = str(tmp_path_factory.mktemp("flight"))
        verdict = triage_divergence(
            cap, report, params2, flight_dir=flight_dir
        )
        return cap, report, verdict

    def test_divergence_detected_in_arrival_order(self, divergent):
        cap, report, _ = divergent
        assert not report.ok
        arrival = [r.rid for r in cap.records]
        assert report.divergent == [
            rid for rid in arrival if rid in report.divergent
        ]
        assert report.divergent[0] == arrival[0]

    def test_first_divergent_token_is_exact(self, divergent):
        cap, report, verdict = divergent
        rid = report.divergent[0]
        rec = next(r for r in cap.records if r.rid == rid)
        out = report.outcomes[rid]
        idx = verdict["token_index"]
        assert idx == out.first_divergent_token
        # The index is the FIRST mismatch: everything before agrees.
        assert rec.tokens[:idx] == out.tokens[:idx]
        assert (
            idx >= min(len(rec.tokens), len(out.tokens))
            or rec.tokens[idx] != out.tokens[idx]
        )
        assert verdict["expected_token"] == rec.tokens[idx]
        assert verdict["got_token"] == out.tokens[idx]

    def test_classified_config_dependent(self, divergent):
        _, _, verdict = divergent
        # Different weights = a different function: solo re-run
        # cannot reproduce the capture either.
        assert verdict["classification"] == "config_dependent"

    def test_flight_bundle_is_readable(self, divergent, capture_run):
        _, _, verdict = divergent
        path = verdict["bundle_path"]
        assert path is not None and os.path.isfile(path)
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "replay_divergence"
        assert (
            bundle["capture_fingerprint"]["id"]
            == capture_run["fingerprint"]["id"]
        )
        # Both configs' fingerprints: the replay side's differs by
        # exactly the injected axis (the weights digest).
        assert (
            bundle["replay_fingerprint"]["weights_crc32"]
            != bundle["capture_fingerprint"]["weights_crc32"]
        )
        assert bundle["record"]["rid"] == verdict["rid"]
        assert bundle["record"]["captured_tokens"]
        assert bundle["verdict"]["classification"] == "config_dependent"
        assert "debug_state" in bundle

    def test_triage_none_on_clean_replay(self, capture_run, params):
        cap = load_capture(capture_run["dir"])
        report = replay_capture(cap, params)
        assert triage_divergence(cap, report, params) is None


class TestBatchDependentClassification:
    def test_solo_match_classifies_batch_dependent(
        self, capture_run, params, tmp_path
    ):
        """Force the 'violated engine invariant' arm without
        violating it: hand triage a report whose divergence is
        fabricated (the solo re-run under the TRUE config reproduces
        the capture, so triage must say batch_dependent)."""
        cap = load_capture(capture_run["dir"])
        report = replay_capture(cap, params)
        assert report.ok
        victim = cap.records[0]
        out = report.outcomes[victim.rid]
        out.match = False
        out.tokens = list(out.tokens)
        out.tokens[-1] = (out.tokens[-1] + 1) % CFG.vocab_size
        out.first_divergent_token = len(out.tokens) - 1
        report.divergent = [victim.rid]
        verdict = triage_divergence(
            cap, report, params, flight_dir=str(tmp_path)
        )
        assert verdict["classification"] == "batch_dependent"


class TestRouterCapture:
    def test_fleet_capture_records_routed_replica(
        self, params, tmp_path
    ):
        from walkai_nos_tpu.router.core import FleetRouter
        from walkai_nos_tpu.router.replica import EngineReplica

        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, prompt_bucket=8,
            chunk_steps=2,
        )
        router = FleetRouter(
            [EngineReplica(engine, name="r0")],
            capture=str(tmp_path),
        )
        rid = router.submit([1, 2, 3], max_new_tokens=3)
        results = router.run()
        assert router.capture_stats()["enabled"] is True
        cap = load_capture(str(tmp_path))
        assert cap.fingerprint.get("router", {}).get("replicas") == [
            "r0"
        ]
        rec = next(r for r in cap.records if r.rid == rid)
        assert rec.replica == "r0"
        assert rec.tokens == results[rid]
        assert rec.digest == token_digest(results[rid])


class TestRouterCaptureFailure:
    def test_failed_replica_request_not_recorded_as_clean(
        self, tmp_path
    ):
        """A replica failure (tokens None + error) must not read as
        a successful zero-token completion in the fleet capture:
        tokens/digest stay null and the error rides the record."""
        from walkai_nos_tpu.router.core import FleetRouter

        class _FailingReplica:
            name = "f0"
            draining = False
            saturation = None
            slo_ok = None
            queue_depth = 0
            slots = 1

            def __init__(self):
                self._pending = {}
                self._rid = 0

            def submit(self, prompt, **kwargs):
                rid = self._rid
                self._rid += 1
                self._pending[rid] = True
                return rid

            def step(self):
                return False

            @property
            def has_work(self):
                return bool(self._pending)

            def drain_done_records(self):
                done = {
                    rid: {
                        "tokens": None,
                        "error": "replica died mid-generate",
                        "ttft_s": None,
                        "wall_s": 0.01,
                        "truncated": False,
                        "trace_id": None,
                    }
                    for rid in self._pending
                }
                self._pending.clear()
                return done

            def drain(self):
                self.draining = True

            def prefix_stats(self):
                return {}

        router = FleetRouter(
            [_FailingReplica()], capture=str(tmp_path)
        )
        rid = router.submit([1, 2, 3], max_new_tokens=2)
        while router.has_work:
            router.step()
        router.drain_done_records()
        cap = load_capture(str(tmp_path))
        rec = next(r for r in cap.records if r.rid == rid)
        assert rec.tokens is None
        assert rec.digest is None
        assert rec.error == "replica died mid-generate"


class TestReplayCheckGate:
    def test_replay_check_is_green(self):
        """The `make replay-check` flow, in-process: record a
        deterministic run, replay it through cmd/replay.py (same
        config + loop override), expect rc 0."""
        import importlib.util
        import pathlib

        spec = importlib.util.spec_from_file_location(
            "replay_check",
            pathlib.Path(__file__).parent.parent
            / "hack" / "replay_check.py",
        )
        replay_check = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(replay_check)
        assert replay_check.main([]) == 0

    def test_cli_exit_codes(self, capture_run, tmp_path):
        from walkai_nos_tpu.cmd.replay import main as replay_main

        assert replay_main(
            [capture_run["dir"], "--init-seed", "0", "--json"]
        ) == 0
        # Different weights: nonzero + a bundle in --flight-dir.
        flight = tmp_path / "flt"
        assert replay_main(
            [capture_run["dir"], "--init-seed", "7",
             "--flight-dir", str(flight)]
        ) == 1
        assert any(
            n.startswith("flight-") for n in os.listdir(flight)
        )

    def test_cli_digest_warning_survives_engine_knob_override(
        self, capture_run, tmp_path, capsys
    ):
        """An engine-knob override (loop_steps) cannot change the
        weight tree, so it must NOT suppress the weights-digest
        mismatch note — the note is what stops a wrong --init-seed
        from being blamed on the overridden axis."""
        from walkai_nos_tpu.cmd.replay import main as replay_main

        rc = replay_main(
            [capture_run["dir"], "--init-seed", "7",
             "--override", "loop_steps=1",
             "--flight-dir", str(tmp_path)]
        )
        out = capsys.readouterr().out
        assert rc == 1
        assert "weights digest mismatch" in out

    def test_cli_override_parsing(self):
        from walkai_nos_tpu.cmd.replay import parse_override

        assert parse_override("loop_steps=8") == ("loop_steps", 8)
        assert parse_override("prefix_cache=false") == (
            "prefix_cache", False,
        )
        assert parse_override("kv_dtype=int8-sim") == (
            "kv_dtype", "int8-sim",
        )
        assert parse_override("spec_min_accept=0.5") == (
            "spec_min_accept", 0.5,
        )

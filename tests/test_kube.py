"""Fake kube client + runtime tests."""

import threading
import time

import pytest

from walkai_nos_tpu.kube import objects, predicates
from walkai_nos_tpu.kube.client import Conflict, NotFound
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Controller, Request, Result


def node(name, labels=None, annotations=None):
    return {
        "metadata": {
            "name": name,
            "labels": labels or {},
            "annotations": annotations or {},
        }
    }


class TestFakeCrud:
    def test_create_get(self):
        c = FakeKubeClient()
        c.create("Node", node("n1"))
        got = c.get("Node", "n1")
        assert objects.name(got) == "n1"
        assert got["metadata"]["resourceVersion"]

    def test_get_missing(self):
        with pytest.raises(NotFound):
            FakeKubeClient().get("Node", "nope")

    def test_create_duplicate(self):
        c = FakeKubeClient()
        c.create("Node", node("n1"))
        with pytest.raises(Conflict):
            c.create("Node", node("n1"))

    def test_namespacing(self):
        c = FakeKubeClient()
        c.create("Pod", {"metadata": {"name": "p", "namespace": "a"}})
        c.create("Pod", {"metadata": {"name": "p", "namespace": "b"}})
        assert len(c.list("Pod")) == 2
        assert len(c.list("Pod", namespace="a")) == 1
        c.delete("Pod", "p", "a")
        assert len(c.list("Pod")) == 1

    def test_label_selector(self):
        c = FakeKubeClient()
        c.create("Node", node("n1", labels={"x": "1"}))
        c.create("Node", node("n2", labels={"x": "2"}))
        assert [objects.name(n) for n in c.list("Node", label_selector={"x": "1"})] == [
            "n1"
        ]

    def test_merge_patch_annotations(self):
        c = FakeKubeClient()
        c.create("Node", node("n1", annotations={"a": "1", "b": "2"}))
        c.patch("Node", "n1", objects.annotation_patch({"a": None, "c": "3"}))
        ann = objects.annotations(c.get("Node", "n1"))
        assert ann == {"b": "2", "c": "3"}

    def test_update_conflict_on_stale_rv(self):
        c = FakeKubeClient()
        created = c.create("Node", node("n1"))
        c.patch("Node", "n1", {"metadata": {"labels": {"x": "1"}}})
        with pytest.raises(Conflict):
            c.update("Node", created)  # stale resourceVersion

    def test_field_selector(self):
        c = FakeKubeClient()
        c.create("Pod", {"metadata": {"name": "p1", "namespace": "d"},
                         "spec": {"nodeName": "n1"}})
        c.create("Pod", {"metadata": {"name": "p2", "namespace": "d"},
                         "spec": {"nodeName": "n2"}})
        got = c.list("Pod", field_selector={"spec.nodeName": "n1"})
        assert [objects.name(p) for p in got] == ["p1"]


class TestWatch:
    def test_backlog_and_live_events(self):
        c = FakeKubeClient()
        c.create("Node", node("n1"))
        events = []
        stop = threading.Event()

        def consume():
            for ev in c.watch("Node", stop=stop.is_set):
                events.append(ev)
                if len(events) >= 4:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.1)
        c.patch("Node", "n1", {"metadata": {"labels": {"x": "1"}}})
        c.delete("Node", "n1")
        t.join(timeout=2)
        stop.set()
        kinds = [e[0] for e in events]
        assert kinds == ["ADDED", "SYNCED", "MODIFIED", "DELETED"]


class TestPredicates:
    def test_matching_name(self):
        p = predicates.matching_name("n1")
        assert p("ADDED", node("n1"), None)
        assert not p("ADDED", node("n2"), None)

    def test_exclude_delete(self):
        p = predicates.exclude_delete()
        assert not p("DELETED", node("n1"), None)
        assert p("ADDED", node("n1"), None)

    def test_annotations_changed(self):
        p = predicates.annotations_changed()
        old = node("n1", annotations={"a": "1"})
        same = node("n1", annotations={"a": "1"})
        diff = node("n1", annotations={"a": "2"})
        assert not p("MODIFIED", same, old)
        assert p("MODIFIED", diff, old)
        assert p("ADDED", same, None)

    def test_node_resources_changed(self):
        p = predicates.node_resources_changed()
        old = {"metadata": {"name": "n"},
               "status": {"capacity": {"x": "1"}, "allocatable": {"x": "1"}}}
        cap_changed = {"metadata": {"name": "n"},
                       "status": {"capacity": {"x": "2"},
                                  "allocatable": {"x": "1"}}}
        both_changed = {"metadata": {"name": "n"},
                        "status": {"capacity": {"x": "2"},
                                   "allocatable": {"x": "2"}}}
        assert p("MODIFIED", cap_changed, old)
        assert not p("MODIFIED", both_changed, old)


class TestController:
    def test_reconciles_on_events_and_dedupes(self):
        c = FakeKubeClient()
        seen = []
        lock = threading.Lock()

        def reconcile(req: Request) -> Result:
            with lock:
                seen.append(req.name)
            return Result()

        ctrl = Controller("t", c, "Node", reconcile)
        ctrl.start()
        try:
            c.create("Node", node("n1"))
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline:
                with lock:
                    if "n1" in seen:
                        break
                time.sleep(0.01)
            with lock:
                assert "n1" in seen
        finally:
            ctrl.stop()

    def test_requeue_after(self):
        c = FakeKubeClient()
        c.create("Node", node("n1"))
        count = [0]

        def reconcile(req: Request) -> Result:
            count[0] += 1
            return Result(requeue_after=0.05)

        ctrl = Controller("t", c, "Node", reconcile)
        ctrl.start()
        try:
            time.sleep(0.5)
            assert count[0] >= 3
        finally:
            ctrl.stop()

    def test_watch_restart_prunes_deleted_objects(self):
        """Objects deleted while no watch stream is up must still produce a
        DELETED reconcile (with last-seen content, so label predicates
        match) when the watch is re-established — the cache must not retain
        them forever."""

        class _OneShotWatch(FakeKubeClient):
            def watch(self, kind, namespace=None, stop=None):
                # Stream dies after the initial snapshot: deletions in the
                # gap are only observable via the SYNCED-marker prune on
                # the next stream.
                for obj in self.list(kind, namespace):
                    yield ("ADDED", obj)
                yield ("SYNCED", {})
                time.sleep(0.05)

        c = _OneShotWatch()
        c.create("Node", node("n1", labels={"role": "tpu"}))
        deleted = threading.Event()

        def labeled(event, obj, old):
            return (objects.labels(obj)).get("role") == "tpu"

        def reconcile(req: Request) -> Result:
            try:
                c.get("Node", req.name)
            except NotFound:
                deleted.set()
            return Result()

        ctrl = Controller("t", c, "Node", reconcile, predicates=[labeled])
        ctrl.start()
        try:
            time.sleep(0.2)  # first stream consumed the backlog and died
            # Remove without a watch event reaching the controller.
            FakeKubeClient.delete(c, "Node", "n1")
            assert deleted.wait(timeout=3)
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and ctrl._cache:
                time.sleep(0.01)
            assert not ctrl._cache
        finally:
            ctrl.stop()

    def test_controller_restarts_after_stop(self):
        """Leader election stops and later restarts the manager; a
        stopped controller must come back to life (fresh work queue)."""
        c = FakeKubeClient()
        seen = []
        lock = threading.Lock()

        def reconcile(req: Request) -> Result:
            with lock:
                seen.append(req.name)
            return Result()

        ctrl = Controller("t", c, "Node", reconcile)
        ctrl.start()
        try:
            c.create("Node", node("n1"))
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and "n1" not in seen:
                time.sleep(0.01)
            assert "n1" in seen
            ctrl.stop()
            ctrl.start()  # lease re-acquired
            c.create("Node", node("n2"))
            deadline = time.monotonic() + 3
            while time.monotonic() < deadline and "n2" not in seen:
                time.sleep(0.01)
            with lock:
                assert "n2" in seen
        finally:
            ctrl.stop()

    def test_error_backoff_retries(self):
        c = FakeKubeClient()
        c.create("Node", node("n1"))
        attempts = [0]
        done = threading.Event()

        def reconcile(req: Request) -> Result:
            attempts[0] += 1
            if attempts[0] < 3:
                raise RuntimeError("boom")
            done.set()
            return Result()

        ctrl = Controller("t", c, "Node", reconcile)
        ctrl.start()
        try:
            assert done.wait(timeout=3)
        finally:
            ctrl.stop()

"""Batched multi-LoRA serving parity + contract tests (tier-1).

K fine-tuned adapters ride ONE paged continuous batcher
(`models/lora.py` + the engine's adapter plane): every request carries
an adapter id, each step applies the per-slot low-rank deltas as
batched gather-einsums, and adapter 0 is the base identity by
construction. The matrix pinned here:

- adapter-0 traffic on a LoRA-ARMED engine is token-identical to a
  LoRA-free engine across greedy/sampled × spec on/off × loop 1/8
  (the identity invariant — arming the engine must not perturb base
  serving by even one ulp);
- a MIXED-adapter ragged batch reproduces each adapter's solo
  streams exactly (batch composition never leaks across slots), and
  spec / the device-resident loop preserve tokens over any mix;
- adapters hot-load/unload at the dispatch sync seam, refused while
  in-flight requests reference the slot;
- the prefix trie never shares cached KV across adapters for the
  same prompt bytes;
- unknown ids reject through the bad_request taxonomy — never a
  silent base fallback;
- tensor-parallel serving applies the same deltas (A/B ride the
  existing psums): tp=2 armed == tp=1 armed.

Synthetic adapters at scale=0.5 for divergence assertions (the
default 0.02 perturbation is too small to flip a tiny model's
argmax); fp32 configs keep pinned streams stable."""

import dataclasses

import jax
import numpy as np
import pytest

from walkai_nos_tpu.models.checkpoint import (
    load_lora_adapter,
    save_lora_adapter,
)
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig, draft_config
from walkai_nos_tpu.models.lora import AdapterSet, adapter_tag
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512, dtype="float32",
)

# Mixed ragged prompts: one crossing the 128-row block boundary so
# multi-chunk prefill + a second pool block run under adapter deltas.
PROMPTS = [
    list(range(1, 8)),
    [(i % 60) + 1 for i in range(137)],
    [5, 9, 2],
]


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    dcfg = draft_config(CFG)
    return dcfg, DecoderLM(dcfg).init_params(jax.random.PRNGKey(1))


def _adapters(scale: float) -> AdapterSet:
    # Fresh set per engine: engines share one program, not one
    # registry (hot-load tests mutate theirs).
    return AdapterSet.synthetic(CFG, k=4, rank=4, seed=0, scale=scale)


def _serve(params, *, arm=None, ids=(0, 0, 0, 0), tp=1,
           spec_draft=None, **kw):
    """One engine + the shared 3-greedy + 1-sampled workload with
    per-request adapter ids. Returns (tokens per request, engine)."""
    cfg = dataclasses.replace(CFG, tp_devices=tp) if tp > 1 else CFG
    if arm is not None:
        kw["adapters"] = _adapters(0.5 if arm == "hot" else 0.02)
    if spec_draft is not None:
        dcfg, dparams = spec_draft
        kw.update(
            spec=True, spec_k=2, draft_cfg=dcfg, draft_params=dparams,
            spec_min_accept=0.0,
        )
    eng = ContinuousBatcher(
        cfg, params, slots=3, cache_len=384, chunk_steps=3,
        prefill_chunk=32, **kw,
    )
    rids = [
        eng.submit(p, max_new_tokens=12, adapter=a)
        for p, a in zip(PROMPTS, ids)
    ]
    rids.append(eng.submit(
        [2, 4, 6], max_new_tokens=10, temperature=0.9, seed=7,
        adapter=ids[3],
    ))
    out = eng.run()
    return [out[r] for r in rids], eng


# Memoized arms: every engine build costs a serving-program compile;
# several tests compare the same pair of runs.
_RUNS: dict = {}


def _serve_cached(params, *, arm=None, ids=(0, 0, 0, 0), tp=1,
                  spec_draft=None, **kw):
    key = (
        arm, ids, tp, spec_draft is not None,
        tuple(sorted(kw.items())),
    )
    if key not in _RUNS:
        _RUNS[key] = _serve(
            params, arm=arm, ids=ids, tp=tp, spec_draft=spec_draft,
            **kw,
        )
    return _RUNS[key]


MIXED = (1, 2, 0, 3)


class TestAdapterZeroIdentity:
    """Arming the engine must not move base traffic: all-adapter-0
    runs on a LoRA-armed engine (nonzero deltas resident in slots
    1..3) == the LoRA-free engine, token for token."""

    def test_plain(self, params):
        base, _ = _serve_cached(params)
        armed, eng = _serve_cached(params, arm="mild")
        assert armed == base
        assert eng.lora_stats()["enabled"]

    def test_loop8(self, params):
        base, _ = _serve_cached(params, loop_steps=8)
        armed, eng = _serve_cached(params, arm="mild", loop_steps=8)
        assert armed == base
        assert eng.loop_stats()["dispatches"] > 0

    def test_spec(self, params, draft):
        base, _ = _serve_cached(params, spec_draft=draft)
        armed, eng = _serve_cached(params, arm="mild", spec_draft=draft)
        assert armed == base
        assert eng.spec_stats()["verify_dispatches"] > 0

    def test_spec_loop8(self, params, draft):
        base, _ = _serve_cached(params, spec_draft=draft, loop_steps=8)
        armed, _ = _serve_cached(
            params, arm="mild", spec_draft=draft, loop_steps=8
        )
        assert armed == base


class TestMixedBatchParity:
    """A ragged batch mixing adapters 0/1/2/3 must reproduce each
    request's SOLO stream — slot composition never bleeds across the
    gather — and every execution mode preserves the mixed tokens."""

    def test_adapters_actually_diverge(self, params):
        base, _ = _serve_cached(params)
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        # Adapter-carrying requests move away from base; the one
        # adapter-0 request in the mix does not.
        assert mixed[0] != base[0]
        assert mixed[1] != base[1]
        assert mixed[2] == base[2]
        assert mixed[3] != base[3]

    def test_mixed_equals_solo_streams(self, params):
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        for idx, adapter in ((0, 1), (1, 2)):
            eng = ContinuousBatcher(
                CFG, params, slots=3, cache_len=384, chunk_steps=3,
                prefill_chunk=32, adapters=_adapters(0.5),
            )
            rid = eng.submit(
                PROMPTS[idx], max_new_tokens=12, adapter=adapter
            )
            assert eng.run()[rid] == mixed[idx]
        eng = ContinuousBatcher(
            CFG, params, slots=3, cache_len=384, chunk_steps=3,
            prefill_chunk=32, adapters=_adapters(0.5),
        )
        rid = eng.submit(
            [2, 4, 6], max_new_tokens=10, temperature=0.9, seed=7,
            adapter=3,
        )
        assert eng.run()[rid] == mixed[3]

    def test_spec_preserves_mixed_tokens(self, params, draft):
        """The base-model draft proposes, each slot's ADAPTER
        verifies — acceptance must leave every stream exactly the
        spec-off stream."""
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        spec, eng = _serve_cached(
            params, arm="hot", ids=MIXED, spec_draft=draft
        )
        assert spec == mixed
        assert eng.spec_stats()["verify_dispatches"] > 0

    def test_loop8_preserves_mixed_tokens(self, params):
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        loop, _ = _serve_cached(
            params, arm="hot", ids=MIXED, loop_steps=8
        )
        assert loop == mixed

    def test_prefix_off_preserves_mixed_tokens(self, params):
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        off, _ = _serve_cached(
            params, arm="hot", ids=MIXED, prefix_cache=False
        )
        assert off == mixed

    def test_tp2_preserves_mixed_tokens(self, params):
        """A/B shard per parallel/sharding.py and the delta rides the
        block's existing psum: tp=2 armed == tp=1 armed."""
        mixed, _ = _serve_cached(params, arm="hot", ids=MIXED)
        tp2, eng = _serve_cached(params, arm="hot", ids=MIXED, tp=2)
        assert tp2 == mixed
        assert eng.tp == 2


def _delta_tree(rng, *, rank=2, scale=0.5):
    """A partial adapter tree (missing blocks/projections stay
    identity) with seeded values big enough to flip argmax."""
    d = CFG.hidden_dim
    return {
        "block0": {
            "qkv": {
                "a": rng.standard_normal((d, rank)).astype(np.float32)
                / np.sqrt(d),
                "b": rng.standard_normal((rank, 3 * d)).astype(
                    np.float32
                ) * scale,
            }
        },
    }


class TestHotSwap:
    def test_hot_load_mid_traffic(self, params):
        """Swapping slot 1's weights between drains changes slot-1
        streams to exactly what an engine BUILT with those weights
        serves — and leaves the other residents untouched."""
        aset = _adapters(0.5)
        eng = ContinuousBatcher(
            CFG, params, slots=3, cache_len=384, chunk_steps=3,
            prefill_chunk=32, adapters=aset,
        )
        r1 = eng.submit(PROMPTS[0], max_new_tokens=12, adapter=1)
        before = eng.run()[r1]

        tree = _delta_tree(np.random.default_rng(42))
        eng.load_adapter(1, tree, name="swapped")
        r1b = eng.submit(PROMPTS[0], max_new_tokens=12, adapter=1)
        r2 = eng.submit(PROMPTS[2], max_new_tokens=12, adapter=2)
        out = eng.run()
        assert out[r1b] != before

        cold_set = _adapters(0.5)
        cold_set.load(1, _delta_tree(np.random.default_rng(42)),
                      name="swapped")
        cold = ContinuousBatcher(
            CFG, params, slots=3, cache_len=384, chunk_steps=3,
            prefill_chunk=32, adapters=cold_set,
        )
        c1 = cold.submit(PROMPTS[0], max_new_tokens=12, adapter=1)
        c2 = cold.submit(PROMPTS[2], max_new_tokens=12, adapter=2)
        cout = cold.run()
        assert out[r1b] == cout[c1]
        assert out[r2] == cout[c2]

    def test_swap_refused_while_in_flight(self, params):
        eng = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=3,
            prefill_chunk=32, adapters=_adapters(0.02),
        )
        eng.submit(PROMPTS[2], max_new_tokens=4, adapter=1)
        with pytest.raises(RuntimeError, match="in-flight"):
            eng.unload_adapter(1)
        with pytest.raises(RuntimeError, match="in-flight"):
            eng.load_adapter(
                1, _delta_tree(np.random.default_rng(7)), name="x"
            )
        eng.run()  # drain
        eng.unload_adapter(1)
        with pytest.raises(ValueError, match="not loaded"):
            eng.submit(PROMPTS[2], max_new_tokens=4, adapter=1)
        # The freed id reloads and serves again.
        eng.load_adapter(
            1, _delta_tree(np.random.default_rng(7)), name="back"
        )
        rid = eng.submit(PROMPTS[2], max_new_tokens=4, adapter=1)
        assert len(eng.run()[rid]) > 0


class TestRejection:
    def test_unarmed_engine_rejects_adapter_requests(self, params):
        """No adapter set -> adapter ids are bad_request, never a
        silent base fallback."""
        eng = ContinuousBatcher(CFG, params, slots=1, cache_len=128)
        with pytest.raises(ValueError, match="no adapter set"):
            eng.submit(PROMPTS[2], max_new_tokens=4, adapter=1)
        assert eng.obs.errors.value(
            labels={"reason": "bad_request"}
        ) == 1
        assert not eng.has_work
        assert eng.lora_stats() == {"enabled": False}

    def test_unknown_adapter_rejected(self, params):
        eng = ContinuousBatcher(
            CFG, params, slots=1, cache_len=128,
            adapters=_adapters(0.02),
        )
        with pytest.raises(ValueError, match="not loaded"):
            eng.submit(PROMPTS[2], max_new_tokens=4, adapter=9)
        assert eng.obs.errors.value(
            labels={"reason": "bad_request"}
        ) == 1

    def test_incompatible_set_rejected_at_build(self, params):
        other = LMConfig(
            vocab_size=64, hidden_dim=16, num_layers=2, num_heads=2,
            max_seq_len=512, dtype="float32",
        )
        with pytest.raises(ValueError, match="do not match"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128,
                adapters=AdapterSet.synthetic(other, k=2),
            )

    def test_dense_engine_rejected(self, params):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128, paged=False,
                adapters=_adapters(0.02),
            )


class TestTrieIsolation:
    def test_no_cross_adapter_prefix_sharing(self, params):
        """The same >=129-token prompt under two adapters must never
        share cached KV (adapter deltas rewrite every row); each
        adapter reuses its OWN parked blocks."""
        eng = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=3,
            prefill_chunk=32, adapters=_adapters(0.5),
        )
        p = [(i % 60) + 1 for i in range(300)]  # 2 shareable blocks
        r0 = eng.submit(p, max_new_tokens=8, adapter=0)
        base = eng.run()[r0]
        assert eng.prefix_stats()["block_hits"] == 0

        r1 = eng.submit(p, max_new_tokens=8, adapter=1)
        first = eng.run()[r1]
        # Adapter 1's lookup saw adapter 0's parked blocks and
        # matched NONE of them.
        assert eng.prefix_stats()["block_hits"] == 0
        assert first != base

        r1b = eng.submit(p, max_new_tokens=8, adapter=1)
        again = eng.run()[r1b]
        # ... while its own parked blocks DO hit, token-identically.
        assert eng.prefix_stats()["block_hits"] == 2
        assert again == first

    def test_adapter_tag_layout(self):
        assert adapter_tag(0) == b""
        assert adapter_tag(3) == np.int32(-3).tobytes()
        tags = {adapter_tag(k) for k in range(4)}
        assert len(tags) == 4


class TestCheckpointRoundTrip:
    def test_npz_round_trip_is_digest_exact(self, params, tmp_path):
        """save_lora_adapter/load_lora_adapter preserve the exact
        argument triple, so a reloaded adapter's effective slices are
        digest-identical to the original load."""
        tree = {
            "block0": _delta_tree(np.random.default_rng(5))["block0"],
            "block1": {
                "fc2": {
                    "a": np.random.default_rng(6).standard_normal(
                        (CFG.mlp_width, 2)
                    ).astype(np.float32),
                    "b": np.random.default_rng(7).standard_normal(
                        (2, CFG.hidden_dim)
                    ).astype(np.float32),
                }
            },
        }
        path = tmp_path / "adapter.npz"
        save_lora_adapter(path, tree, name="tuned", alpha=8.0)
        loaded_tree, name, alpha = load_lora_adapter(path)
        assert (name, alpha) == ("tuned", 8.0)

        direct, reloaded = AdapterSet(CFG), AdapterSet(CFG)
        direct.load(1, tree, name="tuned", alpha=8.0)
        reloaded.load(1, loaded_tree, name=name, alpha=alpha)
        assert direct.digests() == reloaded.digests()
        assert direct.resident() == reloaded.resident()


class TestStatsAndFingerprint:
    def test_lora_stats_contract(self, params):
        _, eng = _serve_cached(params, arm="hot", ids=MIXED)
        st = eng.lora_stats()
        assert st["enabled"] and st["capacity"] == 4 and st["rank"] == 4
        assert sorted(st["adapters"]) == ["0", "1", "2", "3"]
        # MIXED routes one request each to 1/2/0 and the sampled one
        # to 3.
        assert st["requests_total"] == {
            "0": 1, "1": 1, "2": 1, "3": 1,
        }
        assert st["gather_dispatches_total"] > 0

    def test_fingerprint_carries_lora_section(self, params):
        _, eng = _serve_cached(params, arm="hot", ids=MIXED)
        fp = eng.config_fingerprint()
        lora = fp["lora"]
        assert sorted(lora["digests"]) == ["1", "2", "3"]
        assert lora["recipe"]["kind"] == "synthetic"
        assert lora["recipe"]["scale"] == 0.5
        base_fp = _serve_cached(params)[1].config_fingerprint()
        assert "lora" not in base_fp

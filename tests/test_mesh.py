"""TpuMesh geometry-search tests (reference: `pkg/gpu/mig/gpu_test.go`, 596 LoC)."""

import pytest

from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpu.tiling.mesh import TpuMesh

V5E = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]


def mesh(used=None, free=None):
    return TpuMesh(model=V5E, mesh_index=0, used=used or {}, free=free or {})


class TestGeometry:
    def test_empty(self):
        assert mesh().geometry() == {}

    def test_used_plus_free(self):
        m = mesh(used={"2x2": 1}, free={"2x2": 1})
        assert m.geometry() == {"2x2": 2}


class TestCanApplyGeometry:
    def test_empty_mesh_accepts_all(self):
        assert mesh().can_apply_geometry({"2x4": 1})

    def test_never_drops_used(self):
        m = mesh(used={"2x2": 1})
        assert m.can_apply_geometry({"2x2": 2})
        assert m.can_apply_geometry({"2x2": 1, "1x2": 2})
        assert not m.can_apply_geometry({"2x4": 1})
        assert not m.can_apply_geometry({"1x1": 8})

    def test_apply_rejects_dropping_used(self):
        m = mesh(used={"2x2": 1})
        with pytest.raises(GenericError):
            m.apply_geometry({"2x4": 1})

    def test_apply_sets_free(self):
        m = mesh(used={"2x2": 1})
        m.apply_geometry({"2x2": 2})
        assert m.free == {"2x2": 1}
        assert m.used == {"2x2": 1}


class TestInitGeometry:
    def test_defaults_to_whole_host(self):
        m = mesh()
        assert m.init_geometry()
        assert m.geometry() == {"2x4": 1}
        assert m.free == {"2x4": 1}


class TestUpdateGeometryFor:
    def test_provides_wanted_profile(self):
        m = mesh()
        assert m.update_geometry_for({"2x2": 1})
        assert m.free_count("2x2") >= 1

    def test_prefers_most_provided(self):
        m = mesh()
        assert m.update_geometry_for({"1x1": 8})
        assert m.free_count("1x1") == 8

    def test_respects_used_slices(self):
        m = mesh(used={"2x2": 1})
        assert m.update_geometry_for({"1x1": 4})
        geom = m.geometry()
        assert geom.get("2x2", 0) >= 1  # used slice kept
        assert m.free_count("1x1") == 4

    def test_impossible_request_no_change(self):
        # All chips used: nothing can change.
        m = mesh(used={"1x1": 8})
        assert not m.update_geometry_for({"2x2": 1})
        assert m.geometry() == {"1x1": 8}

    def test_no_change_when_nothing_provided(self):
        m = mesh(free={"2x4": 1})
        # wanted profile unknown to this topology
        assert not m.update_geometry_for({"9x9": 1})

    def test_full_free_mesh_repartitions(self):
        m = mesh(free={"2x4": 1})
        assert m.update_geometry_for({"2x2": 2})
        assert m.free_count("2x2") == 2

    def test_deterministic(self):
        a, b = mesh(), mesh()
        a.update_geometry_for({"1x2": 1})
        b.update_geometry_for({"1x2": 1})
        assert a.geometry() == b.geometry()

    def test_distance_tie_break_prefers_similar_geometry(self):
        # Current: 2x2:2. Wanting one more 1x2-pair should pick a geometry
        # close to the current one rather than exploding everything.
        m = mesh(used={"2x2": 1}, free={"2x2": 1})
        assert m.update_geometry_for({"1x2": 2})
        assert m.geometry().get("2x2", 0) >= 1


class TestAddPod:
    def test_moves_free_to_used(self):
        m = mesh(free={"2x2": 2})
        m.add_pod("2x2")
        assert m.used == {"2x2": 1}
        assert m.free == {"2x2": 1}

    def test_no_free_raises(self):
        m = mesh(used={"2x2": 1})
        with pytest.raises(GenericError):
            m.add_pod("2x2")

    def test_clone_is_independent(self):
        m = mesh(free={"2x2": 2})
        c = m.clone()
        c.add_pod("2x2")
        assert m.used == {}
        assert c.used == {"2x2": 1}

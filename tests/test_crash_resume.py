"""Crash/resume suite — the externalized-state design proof (SURVEY §5.4:
all state lives in Node annotations + host-side slice records, so any
process can die and resume; reference `migagent.go:192-199` startup
cleanup + the spec/status diff protocol)."""

from __future__ import annotations

from tests.test_pod_controller import pending_slice_pod, tiling_node
from tests.test_actuator import (
    NODE,
    SPEC_2X2,
    FailingCreateTpudev,
    RecordingPlugin,
    advertise,
)
from walkai_nos_tpu.controllers.partitioner.pod_controller import PodController
from walkai_nos_tpu.controllers.tpuagent.actuator import Actuator
from walkai_nos_tpu.controllers.tpuagent.reporter import Reporter
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Request
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.annotations import (
    parse_node_annotations,
    spec_matches_status,
)
from walkai_nos_tpu.tpu.tiling.client import TilingClient


def agent_generation(kube, tpudev, resources):
    """One agent process lifetime: fresh SharedState/Reporter/Actuator
    (what a DaemonSet pod restart produces), same durable tpudev state."""
    shared = SharedState()
    client = TilingClient(resources, tpudev)
    plugin = RecordingPlugin()
    reporter = Reporter(kube, client, shared, NODE, refresh_interval=10.0)
    actuator = Actuator(kube, client, plugin, shared, NODE)
    return reporter, actuator, plugin


class TestAgentCrashResume:
    def test_restarted_agent_is_a_noop_on_converged_state(self):
        kube = FakeKubeClient()
        kube.create(
            "Node", {"metadata": {"name": NODE, "annotations": dict(SPEC_2X2)}}
        )
        tpudev = FailingCreateTpudev(fail_times=0)
        resources = FakeResourceClient()
        advertise(resources, tpudev)

        # Generation 1: report -> actuate -> advertise -> report.
        reporter, actuator, plugin = agent_generation(kube, tpudev, resources)
        reporter.reconcile(Request(name=NODE))
        actuator.reconcile(Request(name=NODE))
        advertise(resources, tpudev)  # device plugin restarted and re-advertised
        reporter.reconcile(Request(name=NODE))
        gen1_creates = tpudev.create_calls
        assert plugin.restarts == 1

        status, spec = parse_node_annotations(
            objects.annotations(kube.get("Node", NODE))
        )
        assert spec_matches_status(spec, status)

        # Generation 2 (crash + restart): all in-memory state is gone; the
        # node object and the durable slice store are the only truth.
        reporter2, actuator2, plugin2 = agent_generation(
            kube, tpudev, resources
        )
        reporter2.reconcile(Request(name=NODE))
        actuator2.reconcile(Request(name=NODE))
        assert tpudev.create_calls == gen1_creates  # nothing re-created
        assert plugin2.restarts == 0  # nothing changed, no restart

    def test_crash_mid_apply_converges_on_restart(self):
        """Crash AFTER slice creation but BEFORE the report: the restarted
        generation re-reports ground truth and the diff goes empty."""
        kube = FakeKubeClient()
        kube.create(
            "Node", {"metadata": {"name": NODE, "annotations": dict(SPEC_2X2)}}
        )
        tpudev = FailingCreateTpudev(fail_times=0)
        resources = FakeResourceClient()
        advertise(resources, tpudev)

        reporter, actuator, _ = agent_generation(kube, tpudev, resources)
        reporter.reconcile(Request(name=NODE))
        actuator.reconcile(Request(name=NODE))
        # CRASH here: the plugin re-advertised but the reporter never ran,
        # so node status still shows the pre-apply world.
        advertise(resources, tpudev)

        reporter2, actuator2, plugin2 = agent_generation(
            kube, tpudev, resources
        )
        reporter2.reconcile(Request(name=NODE))
        result = actuator2.reconcile(Request(name=NODE))
        assert result.requeue_after is None
        assert tpudev.create_calls == 1  # the one pre-crash apply
        assert plugin2.restarts == 0
        status, spec = parse_node_annotations(
            objects.annotations(kube.get("Node", NODE))
        )
        assert spec_matches_status(spec, status)


class TestPartitionerCrashResume:
    def test_restarted_partitioner_recomputes_identical_spec(self):
        """A partitioner restart mid-flight (spec written, not yet
        actuated) must re-derive the same geometry — idempotent planning
        from cluster state alone."""
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        kube.create("Pod", pending_slice_pod("p1", "2x2"))

        PodController(kube, plan_id_fn=lambda: "gen1").reconcile(
            Request(name="p1", namespace="default")
        )
        _, spec1 = parse_node_annotations(
            objects.annotations(kube.get("Node", "n1"))
        )

        # Restart: a brand-new controller sees the same pending pod again.
        PodController(kube, plan_id_fn=lambda: "gen2").reconcile(
            Request(name="p1", namespace="default")
        )
        annos = objects.annotations(kube.get("Node", "n1"))
        _, spec2 = parse_node_annotations(annos)
        # the restart really re-planned (gen2's plan id landed), with the
        # identical geometry
        assert annos[constants.ANNOTATION_PARTITIONING_PLAN] == "gen2"
        assert {(s.mesh_index, s.profile, s.quantity) for s in spec1} == {
            (s.mesh_index, s.profile, s.quantity) for s in spec2
        }


class TestChaosConvergence:
    def test_randomized_crash_interleavings_converge(self):
        """Seeded chaos sweep: interleave the partitioner/reporter/
        actuator reconciles, kubelet re-advertising, transient native
        failures, and agent-process restarts in random orders — then a
        bounded settle pass must always converge spec==status with the
        requested slice provided. The externalized-state claim, tested
        as a property."""
        import random

        from walkai_nos_tpu.tpu.errors import TpuError

        for seed in range(12):
            rng = random.Random(seed)
            kube = FakeKubeClient()
            kube.create("Node", tiling_node(NODE))
            tpudev = FailingCreateTpudev(fail_times=rng.choice([0, 1, 2]))
            resources = FakeResourceClient()
            ctrl = PodController(kube, plan_id_fn=lambda: "plan-chaos")
            kube.create("Pod", pending_slice_pod("j1", "2x2"))

            gen = {"agent": agent_generation(kube, tpudev, resources)}

            def pod_ctrl():
                ctrl.reconcile(Request(name="j1", namespace="default"))

            def report():
                gen["agent"][0].reconcile(Request(name=NODE))

            def actuate():
                gen["agent"][1].reconcile(Request(name=NODE))

            def readvertise():
                advertise(resources, tpudev)

            def crash_restart():
                gen["agent"] = agent_generation(kube, tpudev, resources)

            actions = [pod_ctrl, report, actuate, readvertise, crash_restart]
            for _ in range(rng.randrange(10, 40)):
                try:
                    rng.choice(actions)()
                except TpuError:
                    pass  # transient native failure, retried by requeue

            # Settle: the steady-state loop a live cluster would run.
            for _ in range(6):
                try:
                    report()
                    pod_ctrl()
                    actuate()
                    readvertise()
                except TpuError:
                    continue
            report()

            status, spec = parse_node_annotations(
                objects.annotations(kube.get("Node", NODE))
            )
            assert spec, f"seed {seed}: no spec written"
            assert spec_matches_status(spec, status), (
                f"seed {seed}: diverged: spec={spec} status={status}"
            )
            assert any(s.profile == "2x2" for s in spec), (
                f"seed {seed}: requested 2x2 never planned"
            )

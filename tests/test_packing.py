"""Mesh-packing tests — the placement engine replacing NVML permutations.

Reference analogue: the placement-order behavior exercised in
`pkg/gpu/nvml` (permutation creation) and `plan_test.go` recreate semantics.
"""

from walkai_nos_tpu.tpu.tiling import known_tilings, packing
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.tiling.packing import Placement, pack_geometry


class TestPackGeometry:
    def test_whole_host(self):
        placements = packing.pack_geometry((2, 4), {"2x4": 1}, pinned=[])
        assert placements is not None
        assert len(placements) == 1
        assert placements[0].profile == "2x4"
        assert sorted(placements[0].cells()) == [
            (r, c) for r in range(2) for c in range(4)
        ]

    def test_two_2x2(self):
        placements = packing.pack_geometry((2, 4), {"2x2": 2}, pinned=[])
        assert placements is not None
        cells = sorted(c for p in placements for c in p.cells())
        assert cells == [(r, c) for r in range(2) for c in range(4)]

    def test_mixed_geometry(self):
        placements = packing.pack_geometry((2, 4), {"2x2": 1, "1x2": 2}, pinned=[])
        assert placements is not None
        cells = [c for p in placements for c in p.cells()]
        assert len(cells) == len(set(cells)) == 8

    def test_partial_geometry_leaves_holes(self):
        placements = packing.pack_geometry((2, 4), {"2x2": 1}, pinned=[])
        assert placements is not None
        assert len(placements) == 1
        assert placements[0].chip_count == 4

    def test_infeasible_returns_none(self):
        # Five 1x2 slices need 10 chips; host has 8.
        assert packing.pack_geometry((2, 4), {"1x2": 5}, pinned=[]) is None

    def test_unplaceable_mix_returns_none(self):
        assert packing.pack_geometry((2, 4), {"1x4": 1, "2x2": 1}, pinned=[]) is None

    def test_deterministic(self):
        a = packing.pack_geometry((2, 4), {"2x2": 1, "1x1": 4}, pinned=[])
        b = packing.pack_geometry((2, 4), {"2x2": 1, "1x1": 4}, pinned=[])
        assert a == b

    def test_pinned_respected(self):
        pinned = [Placement("2x2", (0, 2), (2, 2))]
        placements = packing.pack_geometry((2, 4), {"2x2": 2}, pinned=pinned)
        assert placements is not None
        assert placements[0] == pinned[0]
        other = placements[1]
        assert set(other.cells()).isdisjoint(set(pinned[0].cells()))

    def test_pinned_not_in_geometry_is_infeasible(self):
        pinned = [Placement("2x2", (0, 0), (2, 2))]
        assert packing.pack_geometry((2, 4), {"1x1": 8}, pinned=pinned) is None

    def test_pinned_overlap_is_infeasible(self):
        pinned = [
            Placement("2x2", (0, 0), (2, 2)),
            Placement("2x2", (0, 1), (2, 2)),
        ]
        assert packing.pack_geometry((2, 4), {"2x2": 2}, pinned=pinned) is None

    def test_pinned_out_of_bounds_is_infeasible(self):
        pinned = [Placement("2x2", (0, 3), (2, 2))]
        assert packing.pack_geometry((2, 4), {"2x2": 2}, pinned=pinned) is None

    def test_awkward_pin_forces_backtracking(self):
        # Pin a 2x2 in the middle; 1x1s must fill around it.
        pinned = [Placement("2x2", (0, 1), (2, 2))]
        placements = packing.pack_geometry(
            (2, 4), {"2x2": 1, "1x1": 4}, pinned=pinned
        )
        assert placements is not None
        cells = [c for p in placements for c in p.cells()]
        assert len(cells) == len(set(cells)) == 8

    def test_orientation_permutation(self):
        # A canonical 1x2 must be placeable vertically in a 2x1 grid.
        placements = packing.pack_geometry((2, 1), {"1x2": 1}, pinned=[])
        assert placements is not None
        assert placements[0].orientation == (2, 1)

    def test_3d_host(self):
        placements = packing.pack_geometry(
            (2, 2, 1), {"1x1x2": 2}, pinned=[]
        )
        assert placements is not None
        cells = [c for p in placements for c in p.cells()]
        assert len(set(cells)) == 4

    def test_every_generated_tiling_is_placeable(self):
        for host in [(2, 4), (2, 2, 1), (2, 2)]:
            for gid in known_tilings.generate_tilings(host):
                geom = {}
                for part in gid.split("|"):
                    p, _, q = part.partition("=")
                    geom[p] = int(q)
                assert packing.pack_geometry(host, geom, pinned=[]) is not None, (
                    host,
                    geom,
                )

    def test_slice_ids_stable(self):
        placements = packing.pack_geometry((2, 4), {"2x2": 2}, pinned=[])
        ids = [p.slice_id() for p in placements]
        assert len(ids) == len(set(ids))
        assert all("@" in i for i in ids)


class TestReviewRegressions:
    def test_fragmented_pinned_packing(self):
        # Pinned 1x1 in the middle of a 1x4 strip fragments the mesh; the
        # packer must try the 1x1 (not only the largest 1x2) at the first
        # anchor to find 1x1@(0,0) + 1x2@(0,2).
        pinned = [Placement("1x1", (0, 1), (1, 1))]
        out = packing.pack_geometry((1, 4), {"1x2": 1, "1x1": 2}, pinned=pinned)
        assert out is not None
        cells = [c for p in out for c in p.cells()]
        assert len(cells) == len(set(cells)) == 4

    def test_fragmented_pinned_packing_2d(self):
        # Pin 1x1s at the corners of a 2x4 mesh; 2x2 can't be placed, but
        # 1x2s can fill the middle columns.
        pinned = [
            Placement("1x1", (0, 0), (1, 1)),
            Placement("1x1", (0, 3), (1, 1)),
        ]
        out = packing.pack_geometry(
            (2, 4), {"1x1": 2, "1x2": 3}, pinned=pinned
        )
        assert out is not None
        cells = [c for p in out for c in p.cells()]
        assert len(cells) == len(set(cells)) == 8


class TestPackGeometryProperty:
    """Seeded randomized property test: for random allowed geometries and
    random pinned subsets, every returned placement list is legal — in
    bounds, non-overlapping, matching the requested multiset, pinned kept
    in place (the invariant set of `pack_geometry`'s docstring)."""

    def _assert_legal(self, host_mesh, geometry, pinned, placements):
        # pinned come back first, unmoved
        assert placements[: len(pinned)] == pinned
        seen = set()
        counts = {}
        for p in placements:
            counts[p.profile] = counts.get(p.profile, 0) + 1
            for cell in p.cells():
                assert all(
                    0 <= c < d for c, d in zip(cell, host_mesh)
                ), (p, cell)
                assert cell not in seen, f"overlap at {cell}"
                seen.add(cell)
            # orientation must be a permutation of the canonical profile
            assert sorted(p.orientation) == sorted(
                int(x) for x in p.profile.split("x")
            )
        assert counts == {k: v for k, v in geometry.items() if v > 0}

    def test_random_geometries_with_random_pins(self):
        import random

        from walkai_nos_tpu.tpu.tiling.known_tilings import (
            get_allowed_geometries,
        )

        rng = random.Random(1234)
        for model_name in (
            "tpu-v5-lite-podslice",  # 2x4
            "tpu-v4-podslice",  # 2x2x1
        ):
            model = topology.KNOWN_MODELS[model_name]
            geometries = get_allowed_geometries(model)
            for _ in range(200):
                geometry = dict(rng.choice(geometries))
                # Build a pinned subset by first packing the full geometry,
                # then pinning a random sample of the result.
                full = pack_geometry(model.host_mesh, geometry, [])
                assert full is not None  # allowed => placeable
                k = rng.randrange(0, len(full) + 1)
                pinned = rng.sample(full, k)
                placements = pack_geometry(model.host_mesh, geometry, pinned)
                assert placements is not None, (
                    f"{model_name}: {geometry} unplaceable with "
                    f"{len(pinned)} pinned"
                )
                self._assert_legal(
                    model.host_mesh, geometry, pinned, placements
                )

    def test_random_partial_geometries(self):
        import random

        from walkai_nos_tpu.tpu.tiling.known_tilings import (
            get_allowed_geometries,
        )

        rng = random.Random(99)
        model = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]
        for _ in range(200):
            geometry = dict(rng.choice(get_allowed_geometries(model)))
            # Randomly drop quantities: partial geometries must still place
            # (holes allowed by design).
            geometry = {
                p: rng.randrange(0, q + 1) for p, q in geometry.items()
            }
            geometry = {p: q for p, q in geometry.items() if q > 0}
            if not geometry:
                continue
            placements = pack_geometry(model.host_mesh, geometry, [])
            assert placements is not None, geometry
            self._assert_legal(model.host_mesh, geometry, [], placements)

"""MoE layer: routing correctness, capacity, expert-parallel training."""

import jax
import jax.numpy as jnp
import numpy as np

from walkai_nos_tpu.models.lm import (
    LMConfig,
    init_lm_state,
    make_lm_train_step,
)
from walkai_nos_tpu.models.moe import MoEMlp, aux_loss_from_intermediates
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh
from walkai_nos_tpu.parallel.sharding import param_partition_spec


def _x(b=2, s=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)


class TestRouting:
    def test_single_expert_equals_dense_mlp(self):
        """With one expert and covering capacity every token routes to
        expert 0 with weight 1, so the MoE must equal the plain
        up/gelu/down computed from its own expert weights."""
        x = _x()
        moe = MoEMlp(
            hidden_dim=16, mlp_dim=32, num_experts=1, top_k=1,
            capacity_factor=1.0, dtype=jnp.float32,
        )
        params = moe.init(jax.random.PRNGKey(0), x)["params"]
        y = moe.apply({"params": params}, x)
        w_up = params["experts_up"][0]
        w_down = params["experts_down"][0]
        xt = x.reshape(-1, 16)
        expected = (jax.nn.gelu(xt @ w_up) @ w_down).reshape(x.shape)
        assert jnp.allclose(y, expected, atol=1e-5), (
            float(jnp.max(jnp.abs(y - expected)))
        )

    def test_capacity_overflow_tokens_fall_through(self):
        """With capacity 1 per expert, overflow tokens get zero MoE
        output (they survive via the block's residual connection)."""
        x = _x(b=1, s=16, d=16)
        moe = MoEMlp(
            hidden_dim=16, mlp_dim=32, num_experts=2, top_k=1,
            capacity_factor=1.0 / 8.0,  # capacity = ceil(16/2/8) = 1
            dtype=jnp.float32,
        )
        params = moe.init(jax.random.PRNGKey(0), x)["params"]
        y = moe.apply({"params": params}, x)
        zero_rows = int(jnp.sum(jnp.all(y.reshape(-1, 16) == 0.0, axis=-1)))
        # 16 tokens, 2 experts x capacity 1 -> at most 2 routed.
        assert zero_rows >= 14

    def test_top2_weights_normalized(self):
        """Routed gate mass is renormalized over the kept experts: make
        every expert identical, so the combine step computes
        (sum of kept weights) x dense(x) — which equals dense(x) exactly
        iff the weights were renormalized to sum to 1."""
        x = _x()
        moe = MoEMlp(
            hidden_dim=16, mlp_dim=32, num_experts=4, top_k=2,
            capacity_factor=4.0, dtype=jnp.float32,
        )
        params = moe.init(jax.random.PRNGKey(0), x)["params"]
        params = dict(
            params,
            experts_up=jnp.tile(params["experts_up"][:1], (4, 1, 1)),
            experts_down=jnp.tile(params["experts_down"][:1], (4, 1, 1)),
        )
        y = moe.apply({"params": params}, x)
        w_up, w_down = params["experts_up"][0], params["experts_down"][0]
        xt = x.reshape(-1, 16)
        dense = (jax.nn.gelu(xt @ w_up) @ w_down).reshape(x.shape)
        assert jnp.allclose(y, dense, atol=1e-5), (
            float(jnp.max(jnp.abs(y - dense)))
        )

    def test_aux_loss_sown(self):
        x = _x()
        moe = MoEMlp(
            hidden_dim=16, mlp_dim=32, num_experts=4, top_k=2,
            capacity_factor=2.0, dtype=jnp.float32,
        )
        variables = moe.init(jax.random.PRNGKey(0), x)
        _, state = moe.apply(variables, x, mutable=["intermediates"])
        aux = aux_loss_from_intermediates(state["intermediates"])
        # Perfectly balanced routing gives exactly 1.0; anything routed
        # gives a positive load-balance signal.
        assert float(aux) >= 1.0 - 1e-6


class TestExpertParallelTraining:
    def test_moe_lm_trains_on_expert_mesh(self):
        cfg = LMConfig(
            vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
            max_seq_len=32, num_experts=4, moe_every=2,
        )
        mesh = build_mesh(jax.devices(), axes=MeshAxes(data=2, expert=4))
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_lm_train_step(cfg, mesh)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)))
        state, loss0 = step(state, tokens)
        state, loss1 = step(state, tokens)
        assert bool(jnp.isfinite(loss0))
        assert float(loss1) < float(loss0)

    def test_expert_params_sharded_over_expert_axis(self):
        cfg = LMConfig(
            vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
            max_seq_len=32, num_experts=4, moe_every=2,
        )
        mesh = build_mesh(jax.devices(), axes=MeshAxes(data=2, expert=4))
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        up = state.params["block1"]["moe"]["experts_up"]
        assert "expert" in jax.tree_util.tree_leaves(
            [up.sharding.spec]
        )[0] or up.sharding.spec[0] == "expert"

    def test_sharding_rules_for_expert_stacks(self):
        assert param_partition_spec("block1/moe/experts_up")[0] == "expert"
        assert param_partition_spec("block1/moe/experts_down")[0] == "expert"

    def test_moe_layer_placement(self):
        """moe_every=2 puts MoE in odd blocks (1, 3, ...) only."""
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=4, num_heads=2,
            max_seq_len=16, num_experts=2, moe_every=2,
        )
        from walkai_nos_tpu.models.lm import DecoderLM

        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        assert "moe" in params["block1"] and "moe" in params["block3"]
        assert "fc1" in params["block0"] and "moe" not in params["block0"]


class TestMoEProperties:
    def test_covering_capacity_routes_all_mass(self):
        """Seeded sweep over (E, top_k): with covering capacity no token
        is dropped — every output row differs from zero and the layer is
        a convex combination of expert outputs (bounded by their max)."""
        import random

        rng = random.Random(11)
        for num_experts, top_k in ((2, 1), (4, 2), (8, 2), (4, 4)):
            x = _x(b=2, s=8, d=16, seed=rng.randrange(1 << 16))
            moe = MoEMlp(
                hidden_dim=16, mlp_dim=32, num_experts=num_experts,
                top_k=top_k, capacity_factor=float(num_experts),
                dtype=jnp.float32,
            )
            params = moe.init(jax.random.PRNGKey(top_k), x)["params"]
            y = moe.apply({"params": params}, x)
            assert bool(jnp.all(jnp.isfinite(y))), (num_experts, top_k)
            zero_rows = int(
                jnp.sum(jnp.all(y.reshape(-1, 16) == 0.0, axis=-1))
            )
            assert zero_rows == 0, (num_experts, top_k, zero_rows)

    def test_moe_gradients_flow_to_router_and_experts(self):
        x = _x()
        moe = MoEMlp(
            hidden_dim=16, mlp_dim=32, num_experts=4, top_k=2,
            capacity_factor=2.0, dtype=jnp.float32,
        )
        params = moe.init(jax.random.PRNGKey(0), x)["params"]

        def loss(p):
            return jnp.sum(moe.apply({"params": p}, x) ** 2)

        grads = jax.grad(loss)(params)
        for path in ("experts_up", "experts_down"):
            g = grads[path]
            assert float(jnp.max(jnp.abs(g))) > 0.0, path
        g_router = grads["router"]["kernel"]
        assert float(jnp.max(jnp.abs(g_router))) > 0.0

    def test_moe_composes_with_fsdp(self):
        """expert=2 x fsdp=2 x data=2: expert stacks shard over both the
        expert axis and (within each expert) the fsdp/model split."""
        cfg = LMConfig(
            vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
            max_seq_len=32, num_experts=2, moe_every=2,
        )
        mesh = build_mesh(
            jax.devices(), axes=MeshAxes(data=2, fsdp=2, expert=2)
        )
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_lm_train_step(cfg, mesh)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
        )
        state, loss0 = step(state, tokens)
        state, loss1 = step(state, tokens)
        assert float(loss1) < float(loss0)
        up = state.params["block1"]["moe"]["experts_up"]
        assert up.sharding.spec[0] == "expert"

"""`make metrics-lint` (hack/metrics_lint.py): the catalog/docs drift
gate must pass on the repo's own current files and fail on every
synthetic drift direction — a broken linter would wave undocumented
metrics through silently, so the logic itself is tier-1 (mirroring
tests/test_bench_check.py for the bench gate)."""

import importlib.util
import pathlib

_ROOT = pathlib.Path(__file__).resolve().parents[1]

spec = importlib.util.spec_from_file_location(
    "metrics_lint", _ROOT / "hack" / "metrics_lint.py"
)
metrics_lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(metrics_lint)


def _doc_text() -> str:
    return (_ROOT / "docs" / "observability.md").read_text()


class TestRepoIsClean:
    def test_lint_passes_on_repo(self):
        errors = metrics_lint.lint(
            _doc_text(), metrics_lint.registered_literals()
        )
        assert errors == [], errors

    def test_main_exit_zero(self):
        assert metrics_lint.main([]) == 0

    def test_every_catalog_metric_documented(self):
        from walkai_nos_tpu.obs.catalog import CATALOG

        documented = metrics_lint.documented_metrics(_doc_text())
        for spec_ in CATALOG:
            kind, labels = documented.get(spec_.name, (None, ()))
            assert kind == spec_.kind, spec_.name
            assert set(labels) == set(spec_.labels), spec_.name

    def test_federated_prefixes_documented(self):
        from walkai_nos_tpu.obs.federation import FEDERATED_PREFIXES

        documented = metrics_lint.documented_federated_prefixes(
            _doc_text()
        )
        assert documented == set(FEDERATED_PREFIXES)

    def test_makefile_has_target(self):
        assert "metrics-lint:" in (_ROOT / "Makefile").read_text()


class TestDriftDirections:
    def test_undocumented_catalog_metric_fails(self):
        # Remove one documented row: the catalog->docs direction.
        doc = _doc_text().replace("`cb_ttft_seconds`", "`renamed_away`")
        errors = metrics_lint.lint(doc)
        assert any(
            "cb_ttft_seconds" in e and "not documented" in e
            for e in errors
        )
        # ...and the stale row trips the docs->catalog direction.
        assert any("renamed_away" in e for e in errors)

    def test_documented_but_unregistered_fails(self):
        doc = _doc_text() + (
            "\n| `ghost_metric_total` | counter | — | not real |\n"
        )
        errors = metrics_lint.lint(doc)
        assert any(
            "ghost_metric_total" in e and "not in obs/catalog" in e
            for e in errors
        )

    def test_type_mismatch_fails(self):
        doc = _doc_text().replace(
            "| `cb_queue_depth` | gauge |",
            "| `cb_queue_depth` | counter |",
        )
        errors = metrics_lint.lint(doc)
        assert any(
            "cb_queue_depth" in e and "mismatch" in e for e in errors
        )

    def test_literal_registration_outside_catalog_fails(self):
        errors = metrics_lint.lint(
            _doc_text(),
            {"rogue_total": ["walkai_nos_tpu/somewhere.py"]},
        )
        assert any(
            "rogue_total" in e and "somewhere.py" in e for e in errors
        )

    def test_label_mismatch_fails(self):
        """The third table cell (labels) is linted in both directions
        too: a label dropped from the docs — or invented there —
        fails."""
        doc = _doc_text().replace(
            "| `router_replica_saturation` | gauge | `replica` |",
            "| `router_replica_saturation` | gauge | — |",
        )
        errors = metrics_lint.lint(doc)
        assert any(
            "router_replica_saturation" in e and "label" in e
            for e in errors
        )
        doc = _doc_text().replace(
            "| `cb_queue_depth` | gauge | — |",
            "| `cb_queue_depth` | gauge | `invented` |",
        )
        errors = metrics_lint.lint(doc)
        assert any(
            "cb_queue_depth" in e and "label" in e for e in errors
        )

    def test_undocumented_federated_prefix_fails(self):
        """The docs' 'Federated prefixes:' line is held to
        obs/federation.py in both directions."""
        doc = _doc_text().replace("Federated prefixes: `cb_*`", "")
        errors = metrics_lint.lint(doc)
        assert any(
            "cb_*" in e and "not documented" in e for e in errors
        )
        doc = _doc_text().replace(
            "Federated prefixes: `cb_*`",
            "Federated prefixes: `cb_*` `ghost_*`",
        )
        errors = metrics_lint.lint(doc)
        assert any("ghost_*" in e for e in errors)

    def test_code_scan_finds_known_literals(self):
        """The scan must actually see the kube/runtime.py and demo
        client registrations (a regex regression would quietly turn
        the third lint leg off)."""
        names = metrics_lint.registered_literals()
        assert "nos_reconcile_total" in names
        assert "inference_time_seconds_sum" in names

"""Pipeline parallelism: GPipe schedule correctness and the pipelined LM."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.lm import LMConfig, lm_loss
from walkai_nos_tpu.models.pipelined_lm import (
    _Embed,
    _Head,
    _block,
    init_pipelined_lm_state,
    make_pipelined_lm_train_step,
)
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh
from walkai_nos_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
    stack_stage_params,
)

D = 16


def _stages(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.standard_normal((D, D)) * 0.1, jnp.float32),
            "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32),
        }
        for _ in range(n)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


class TestPipelineApply:
    def test_matches_sequential(self):
        mesh = build_mesh(jax.devices(), axes=MeshAxes(pipe=4, data=2))
        stages = _stages(4)
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((16, D)), jnp.float32
        )
        xm = split_microbatches(x, 8)
        y = merge_microbatches(
            pipeline_apply(_stage_fn, stack_stage_params(stages), xm, mesh)
        )
        ref = x
        for p in stages:
            ref = _stage_fn(p, ref)
        assert jnp.allclose(y, ref, atol=1e-5)

    def test_differentiable(self):
        mesh = build_mesh(jax.devices(), axes=MeshAxes(pipe=4, data=2))
        stacked = stack_stage_params(_stages(4))
        xm = split_microbatches(
            jnp.ones((8, D), jnp.float32), 4
        )

        def loss(params):
            return jnp.sum(pipeline_apply(_stage_fn, params, xm, mesh) ** 2)

        grads = jax.grad(loss)(stacked)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert bool(jnp.all(jnp.isfinite(leaf)))
            assert float(jnp.max(jnp.abs(leaf))) > 0.0

    def test_underfilled_pipeline_rejected(self):
        mesh = build_mesh(jax.devices(), axes=MeshAxes(pipe=4, data=2))
        stacked = stack_stage_params(_stages(4))
        xm = split_microbatches(jnp.ones((4, D), jnp.float32), 2)
        with pytest.raises(ValueError, match="under-fill"):
            pipeline_apply(_stage_fn, stacked, xm, mesh)

    def test_indivisible_microbatches_rejected(self):
        with pytest.raises(ValueError, match="not divisible"):
            split_microbatches(jnp.ones((6, D)), 4)


class TestPipelinedLM:
    CFG = LMConfig(
        vocab_size=128, hidden_dim=64, num_layers=4, num_heads=4,
        max_seq_len=32,
    )

    def _mesh(self):
        return build_mesh(jax.devices(), axes=MeshAxes(pipe=4, data=2))

    def test_layers_must_split_over_stages(self):
        cfg = LMConfig(
            vocab_size=128, hidden_dim=64, num_layers=3, num_heads=4,
            max_seq_len=32,
        )
        with pytest.raises(ValueError, match="split over"):
            init_pipelined_lm_state(cfg, self._mesh(), jax.random.PRNGKey(0))

    def test_loss_matches_sequential_forward(self):
        """The pipelined step's reported loss must equal the loss of a
        plain sequential forward through the same parameters."""
        cfg, mesh = self.CFG, self._mesh()
        state = init_pipelined_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_pipelined_lm_train_step(cfg, mesh, n_microbatches=4)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
        )
        _, loss = step(state, tokens)

        params = jax.device_get(
            init_pipelined_lm_state(cfg, mesh, jax.random.PRNGKey(0)).params
        )
        x = _Embed(cfg).apply({"params": params["embed"]}, tokens)
        block = _block(cfg)
        n_stages, per_stage = 4, cfg.num_layers // 4
        for s in range(n_stages):
            for layer in range(per_stage):
                layer_params = jax.tree_util.tree_map(
                    lambda leaf: leaf[s][layer], params["blocks"]
                )
                x = block.apply({"params": layer_params}, x)
        logits = _Head(cfg).apply({"params": params["head"]}, x)
        expected = lm_loss(logits, tokens)
        assert abs(float(loss) - float(expected)) < 2e-2, (
            float(loss), float(expected),
        )

    def test_training_reduces_loss(self):
        cfg, mesh = self.CFG, self._mesh()
        state = init_pipelined_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_pipelined_lm_train_step(cfg, mesh, n_microbatches=4)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 32))
        )
        state, loss0 = step(state, tokens)
        state, loss1 = step(state, tokens)
        assert float(loss1) < float(loss0)

    def test_stage_params_sharded_over_pipe(self):
        cfg, mesh = self.CFG, self._mesh()
        state = init_pipelined_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        leaf = jax.tree_util.tree_leaves(state.params["blocks"])[0]
        assert leaf.sharding.spec[0] == "pipe"


class TestPipelineProperties:
    def test_random_configs_match_sequential(self):
        """Seeded property sweep: every (stage count, microbatch count)
        the 8-device mesh supports must reproduce the sequential
        composition exactly."""
        import random

        rng = random.Random(7)
        for pipe, data in ((2, 4), (4, 2), (8, 1)):
            mesh = build_mesh(
                jax.devices(), axes=MeshAxes(pipe=pipe, data=data)
            )
            stages = _stages(pipe, seed=rng.randrange(1 << 16))
            for n_micro in (pipe, 2 * pipe):
                batch = n_micro * max(data, 1)
                x = jnp.asarray(
                    np.random.default_rng(
                        rng.randrange(1 << 16)
                    ).standard_normal((batch, D)),
                    jnp.float32,
                )
                y = merge_microbatches(
                    pipeline_apply(
                        _stage_fn,
                        stack_stage_params(stages),
                        split_microbatches(x, n_micro),
                        mesh,
                    )
                )
                ref = x
                for p in stages:
                    ref = _stage_fn(p, ref)
                assert jnp.allclose(y, ref, atol=1e-5), (pipe, n_micro)

"""KV block transfer + live request migration (`models/serve.py`).

Tier-1 surface for the disaggregated-serving engine seams: a block
exported by content hash and imported into a peer's pool must be
indistinguishable from a locally-prefilled block — matchable (serving
an identical prompt over the imported prefix is token-identical to a
cold engine), refcounted (a local reader pins it exactly like a local
sharer), evictable (it parks at refcount 0 and LRU-evicts under
pressure), and an import must NEVER overflow the pool — with the free
list dry it competes through the same evict-under-pressure seam as
admission. Live migration must preserve the stream bit-for-bit: a
request exported mid-decode and re-imported elsewhere (greedy AND
sampled — the per-slot PRNG key rides along) finishes with the exact
tokens an uninterrupted engine emits, and a partial export (`only=`)
leaves the other residents decoding untouched. Deliberately NOT in
conftest's `_SLOW_FILES`: tiny 2-layer config, few-token budgets.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.block_key import chain_hashes
from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


def _expected(params, prompt, max_new):
    gen = make_generate_fn(CFG)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


def _engine(params, **kw):
    base = dict(
        slots=2, cache_len=384, chunk_steps=3, prefill_chunk=32,
        prefill_lanes=2,
    )
    base.update(kw)
    return ContinuousBatcher(CFG, params, **base)


class TestBlockExportImport:
    def test_imported_prefix_parity_and_divergent_tail(self, params):
        """Blocks shipped by hash from a warm engine land matchable:
        the importer serves the SAME prompt with 2 block hits and the
        exact cold-engine tokens; a prompt diverging AFTER the
        imported prefix is also token-identical to cold (imported
        prefix + local suffix — the fleet-cache correctness claim);
        re-importing the same payload is a per-block dup reject."""
        src = _engine(params)
        p = _prompt(300, seed=31)
        want = _expected(params, p, 10)
        r0 = src.submit(p, max_new_tokens=10)
        assert src.run()[r0] == want
        hashes = chain_hashes(p)
        assert len(hashes) == 2  # 300 tokens -> 2 full shareable blocks
        payload = src.export_blocks(hashes)
        # Trie-side path hashes ARE the prompt-side chain hashes: the
        # router can name another engine's blocks from tokens alone.
        assert [b["hash"] for b in payload["blocks"]] == hashes

        dst = _engine(params)
        assert dst.import_blocks(payload) == {
            "imported": 2, "rejected": {},
        }
        r1 = dst.submit(p, max_new_tokens=10)
        assert dst.run()[r1] == want, "imported-prefix decode mismatch"
        assert dst.prefix_stats()["block_hits"] == 2
        # Divergent tail over the imported prefix.
        p2 = np.concatenate([p[:256], _prompt(30, seed=77)])
        r2 = dst.submit(p2, max_new_tokens=8)
        assert dst.run()[r2] == _expected(params, p2, 8)
        assert dst.import_blocks(src.export_blocks(hashes)) == {
            "imported": 0, "rejected": {"dup": 2},
        }

    def test_import_with_free_list_dry_evicts_lru(self, params):
        """With every allocatable block parked, an import competes
        through evict-under-pressure: the LRU parked prefix is
        evicted (never a pool overflow), the payload lands whole,
        and the newer local prefix survives."""
        dst = _engine(params, slots=1)  # pool: 3 allocatable blocks
        p_old = _prompt(130, seed=101)
        p_new = _prompt(130, seed=102)
        for p in (p_old, p_new):
            dst.submit(p, max_new_tokens=2)
            dst.run()
        assert dst._prefix.parked_blocks == 2

        src = _engine(params)
        p3 = _prompt(300, seed=55)
        src.submit(p3, max_new_tokens=2)
        src.run()
        res = dst.import_blocks(src.export_blocks(chain_hashes(p3)))
        assert res["imported"] == 2, res
        assert int(dst.obs.prefix_evictions.value()) >= 1
        assert dst._prefix.match(p_old) == []  # LRU victim gone
        # The import is fully resident and serves.
        rid = dst.submit(p3, max_new_tokens=4)
        assert dst.run()[rid] == _expected(params, p3, 4)

    def test_refcount_shared_by_local_reader_of_import(self, params):
        """A local request matching an IMPORTED block pins it exactly
        like a local sharer: refcount 1 while the reader decodes (the
        block is not freeable), 0 + parked after it finishes — then
        it is evictable like any cached prefix."""
        src = _engine(params)
        p = _prompt(200, seed=9)  # 1 shareable block
        src.submit(p, max_new_tokens=2)
        src.run()
        dst = _engine(params)
        assert dst.import_blocks(
            src.export_blocks(chain_hashes(p))
        )["imported"] == 1
        node = dst._prefix.match(p)[0]
        assert node.ready and node.refcount == 0
        assert dst._prefix.parked_blocks == 1
        rid = dst.submit(p, max_new_tokens=24)
        records = {}
        while dst.has_work and node.refcount == 0:
            dst.step()
        assert node.refcount == 1  # live local reader of the import
        assert node.block not in dst._free_blocks
        while dst.has_work:
            dst.step()
            records.update(dst.drain_done_records())
        assert records[rid]["tokens"] == _expected(params, p, 24)
        assert node.refcount == 0
        assert dst._prefix.parked_blocks == 1
        assert dst._prefix.evict_lru() == node.block

    def test_incompatible_header_rejects_whole(self, params):
        """A payload whose compatibility header disagrees (here:
        kv_dtype) rejects WHOLE — nothing lands, and the rejection
        reason names the first mismatching field."""
        src = _engine(params)
        p = _prompt(200, seed=3)
        src.submit(p, max_new_tokens=2)
        src.run()
        payload = src.export_blocks(chain_hashes(p))
        payload["kv_dtype"] = "int4"
        dst = _engine(params)
        res = dst.import_blocks(payload)
        assert res == {"imported": 0, "rejected": {"kv_dtype": 1}}
        assert dst._prefix.match(p) == []


class TestLiveMigration:
    @pytest.mark.parametrize("knobs", [
        {},
        {"temperature": 0.9, "top_k": 16, "top_p": 0.95, "seed": 123},
    ], ids=["greedy", "sampled"])
    def test_midstream_migration_is_token_exact(self, params, knobs):
        """A request exported a few tokens into decode and imported
        into a peer finishes with EXACTLY the tokens an uninterrupted
        engine emits — greedy and sampled (the slot's per-step PRNG
        key migrates with the stream, so sampling resumes on the same
        draw sequence)."""
        src = _engine(params)
        q = _prompt(140, seed=7)
        rc = src.submit(q, max_new_tokens=40, **knobs)
        while not src._requests[rc].tokens:
            src.step()
        for _ in range(3):
            src.step()
        payload = src.export_resident()
        assert not src.has_work  # evacuated, not copied
        assert len(payload["migrate"]) == 1 and not payload["resubmit"]
        dst = _engine(params)
        out = dst.import_resident(payload)
        assert out[0]["migrated"] is True
        got = dst.run()[out[0]["rid"]]
        if knobs:
            ref_engine = _engine(params)
            rr = ref_engine.submit(q, max_new_tokens=40, **knobs)
            ref = ref_engine.run()[rr]
        else:
            ref = _expected(params, q, 40)
        assert got == ref

    def test_queued_and_prefilling_export_as_resubmits(self, params):
        """Work without a committed token (queued, mid-prefill) has no
        KV worth shipping: it exports as resubmit state and replays
        from scratch on the importer, token-identical."""
        src = _engine(params, slots=1)
        q1, q2 = _prompt(300, seed=11), _prompt(20, seed=12)
        src.submit(q1, max_new_tokens=6)
        src.submit(q2, max_new_tokens=5)
        src.step()  # q1 mid-prefill, q2 still queued
        payload = src.export_resident()
        assert not src.has_work
        assert len(payload["resubmit"]) == 2 and not payload["migrate"]
        dst = _engine(params)
        out = dst.import_resident(payload)
        res = dst.run()
        got = sorted(tuple(res[o["rid"]]) for o in out)
        assert got == sorted(map(tuple, [
            _expected(params, q1, 6), _expected(params, q2, 5),
        ]))

    def test_partial_export_leaves_other_streams_serving(self, params):
        """`only=[rid]` ships ONE live stream (the two-stage decode
        handoff); the other resident keeps decoding on the source and
        BOTH finish token-identical to uninterrupted runs."""
        src = _engine(params)
        qa, qb = _prompt(140, seed=21), _prompt(150, seed=22)
        ra = src.submit(qa, max_new_tokens=20)
        rb = src.submit(qb, max_new_tokens=20)
        while not (src._requests[ra].tokens and src._requests[rb].tokens):
            src.step()
        payload = src.export_resident(only=[ra])
        assert len(payload["migrate"]) == 1 and not payload["resubmit"]
        assert list(payload["migrate"][0]["prompt"]) == list(qa)
        assert src.has_work  # rb still resident and decoding
        dst = _engine(params)
        out = dst.import_resident(payload)
        moved = dst.run()[out[0]["rid"]]
        stayed = None
        while src.has_work:
            src.step()
            stayed = {**(stayed or {}), **src.drain_done_records()}
        assert moved == _expected(params, qa, 20)
        assert stayed[rb]["tokens"] == _expected(params, qb, 20)

    def test_drain_stats_counts_down_to_empty(self, params):
        """`drain_stats()` (the /healthz drain block) reports the
        evacuation's progress: resident slots + blocks remaining
        while work is live, zeros once the export empties the
        engine."""
        engine = _engine(params)
        rid = engine.submit(_prompt(140, seed=5), max_new_tokens=30)
        while not engine._requests[rid].tokens:
            engine.step()
        engine.drain()
        st = engine.drain_stats()
        assert st["draining"] is True
        assert st["resident_slots"] == 1
        assert st["blocks_remaining"] >= 1
        engine.export_resident()
        st = engine.drain_stats()
        assert st["resident_slots"] == 0
        assert st["queued"] == 0
        assert st["blocks_remaining"] == 0

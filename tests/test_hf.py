"""HF GPT-2 import: config mapping and exact logit/generation parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from walkai_nos_tpu.models.decode import make_generate_fn  # noqa: E402
from walkai_nos_tpu.models.hf import (  # noqa: E402
    config_from_gpt2,
    load_gpt2,
)
from walkai_nos_tpu.models.lm import DecoderLM  # noqa: E402


def _hf_model(seed=0):
    torch.manual_seed(seed)
    cfg = transformers.GPT2Config(
        n_embd=32, n_layer=2, n_head=2, n_positions=32, vocab_size=64,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    return transformers.GPT2LMHeadModel(cfg).eval()


class TestConfigMapping:
    def test_fields(self):
        hf = _hf_model()
        cfg = config_from_gpt2(hf.config)
        assert cfg.vocab_size == 64
        assert cfg.hidden_dim == 32
        assert cfg.num_layers == 2
        assert cfg.num_heads == 2
        assert cfg.mlp_ratio == 4
        assert cfg.max_seq_len == 32
        assert cfg.layer_norm_eps == hf.config.layer_norm_epsilon

    def test_non_gelu_variant_rejected(self):
        hf = _hf_model()
        hf.config.activation_function = "relu"
        with pytest.raises(ValueError, match="gelu_new"):
            config_from_gpt2(hf.config)


class TestLogitParity:
    def test_forward_matches_torch(self):
        hf = _hf_model()
        cfg, params = load_gpt2(hf)
        tokens = np.random.default_rng(0).integers(0, 64, (2, 16))
        with torch.no_grad():
            expected = hf(torch.tensor(tokens)).logits.numpy()
        ours = np.asarray(
            DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
        )
        assert np.max(np.abs(ours - expected)) < 5e-4

    def test_greedy_generation_matches_torch(self):
        """The imported weights must decode the same continuation HF's
        own greedy search produces — logits, cache, and sampling all in
        agreement."""
        hf = _hf_model(seed=1)
        cfg, params = load_gpt2(hf)
        prompt = np.random.default_rng(1).integers(0, 64, (1, 4))
        with torch.no_grad():
            expected = hf.generate(
                torch.tensor(prompt), max_new_tokens=6, do_sample=False,
                pad_token_id=0,
            ).numpy()[:, 4:]
        ours = np.asarray(
            make_generate_fn(cfg)(
                params, jnp.asarray(prompt), max_new_tokens=6
            )
        )
        assert np.array_equal(ours, expected), (ours, expected)


class TestConfigGuards:
    def test_indivisible_n_inner_rejected(self):
        hf = _hf_model()
        hf.config.n_inner = 100  # not a multiple of n_embd=32
        with pytest.raises(ValueError, match="multiple of n_embd"):
            config_from_gpt2(hf.config)


class TestExport:
    def test_trained_model_round_trips_through_torch(self):
        """The feature's actual use case: import, TRAIN (untying the
        head from the embedding), export — the torch forward of the
        exported model must match our jax forward of the trained one."""
        import jax.numpy as jnp

        from walkai_nos_tpu.models.hf import (
            load_gpt2,
            state_dict_from_params,
        )
        from walkai_nos_tpu.models.lm import make_lm_train_step
        from walkai_nos_tpu.parallel.mesh import build_mesh

        hf = _hf_model(seed=2)
        cfg, params = load_gpt2(hf)
        mesh = build_mesh(jax.devices()[:1])
        from walkai_nos_tpu.models.train import TrainState, make_optimizer

        tx = make_optimizer(1e-3)
        state = TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
        step = make_lm_train_step(cfg, mesh)
        tokens_np = np.random.default_rng(2).integers(0, 64, (2, 16))
        state, _ = step(state, jnp.asarray(tokens_np))
        trained = jax.device_get(state.params)
        # The head really diverged from the embedding (untied training).
        assert not np.allclose(
            np.asarray(trained["head"]["kernel"]),
            np.asarray(trained["embed"]["embedding"]).T,
            atol=1e-6,
        )

        from walkai_nos_tpu.models.hf import export_gpt2

        hf_config, sd = export_gpt2(trained, cfg)
        assert hf_config.tie_word_embeddings is False
        clone = transformers.GPT2LMHeadModel(hf_config).eval()
        missing, unexpected = clone.load_state_dict(sd, strict=False)
        assert not unexpected, unexpected

        # The low-level path without acknowledgement refuses the
        # untied head (loading it tied would corrupt the embedding).
        with pytest.raises(ValueError, match="untied"):
            state_dict_from_params(trained, cfg)
        eval_tokens = np.random.default_rng(3).integers(0, 64, (2, 12))
        with torch.no_grad():
            theirs = clone(torch.tensor(eval_tokens)).logits.numpy()
        ours = np.asarray(
            DecoderLM(cfg).apply(
                {"params": trained}, jnp.asarray(eval_tokens)
            )
        )
        assert np.max(np.abs(ours - theirs)) < 5e-4

    def test_moe_layout_rejected(self):
        from dataclasses import replace

        from walkai_nos_tpu.models.hf import (
            load_gpt2,
            state_dict_from_params,
        )

        hf = _hf_model()
        cfg, params = load_gpt2(hf)
        with pytest.raises(ValueError, match="MoE"):
            state_dict_from_params(params, replace(cfg, num_experts=2))

    def test_head_bias_rejected(self):
        import jax.numpy as jnp

        from walkai_nos_tpu.models.hf import (
            load_gpt2,
            state_dict_from_params,
        )

        hf = _hf_model()
        cfg, params = load_gpt2(hf)
        params = dict(params, head={
            "kernel": params["head"]["kernel"],
            "bias": jnp.ones((cfg.vocab_size,), jnp.float32),
        })
        with pytest.raises(ValueError, match="head_bias"):
            state_dict_from_params(params, cfg)


def _hf_llama(seed=0, kv_heads=2):
    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=kv_heads,
        intermediate_size=48, max_position_embeddings=64,
        rms_norm_eps=1e-6, attention_dropout=0.0,
        tie_word_embeddings=False,
    )
    return transformers.LlamaForCausalLM(cfg).eval()


class TestLlamaImport:
    def test_config_mapping(self):
        from walkai_nos_tpu.models.hf import config_from_llama

        hf = _hf_llama()
        cfg = config_from_llama(hf.config)
        assert cfg.norm == "rmsnorm"
        assert cfg.mlp == "swiglu"
        assert cfg.rope and not cfg.use_bias and not cfg.head_bias
        assert cfg.num_kv_heads == 2
        assert cfg.mlp_dim == 48
        assert cfg.layer_norm_eps == hf.config.rms_norm_eps

    @pytest.mark.parametrize("kv_heads", [1, 2, 4])
    def test_forward_matches_torch(self, kv_heads):
        """Exact logit parity incl. GQA/MQA variants: RMSNorm, RoPE,
        SwiGLU, grouped heads all in agreement with transformers."""
        from walkai_nos_tpu.models.hf import load_llama

        hf = _hf_llama(kv_heads=kv_heads)
        cfg, params = load_llama(hf)
        tokens = np.random.default_rng(0).integers(0, 64, (2, 16))
        with torch.no_grad():
            expected = hf(torch.tensor(tokens)).logits.numpy()
        ours = np.asarray(
            DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
        )
        assert np.max(np.abs(ours - expected)) < 5e-4

    def test_greedy_generation_matches_torch(self):
        """KV-cache decode (RoPE offsets, grouped cache) must produce
        HF's own greedy continuation."""
        from walkai_nos_tpu.models.hf import load_llama

        hf = _hf_llama(seed=1)
        cfg, params = load_llama(hf)
        prompt = np.random.default_rng(1).integers(0, 64, (1, 4))
        with torch.no_grad():
            expected = hf.generate(
                torch.tensor(prompt), max_new_tokens=6, do_sample=False,
                pad_token_id=0,
            ).numpy()[:, 4:]
        ours = np.asarray(
            make_generate_fn(cfg)(
                params, jnp.asarray(prompt), max_new_tokens=6
            )
        )
        assert np.array_equal(ours, expected), (ours, expected)

    def test_continuous_batching_serves_imported_checkpoint(self):
        """The interop x serving bridge: an imported HF llama served
        through the continuous-batching slot pool (ragged decode,
        staggered admission, co-tenant requests) must emit exactly
        HF's own greedy continuation for every request — the same
        guarantee a reference user migrating their checkpoint to the
        TPU serving engine relies on."""
        from walkai_nos_tpu.models.hf import load_llama
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        # seed 3: no near-argmax ties between torch-f32 and jax-f32
        # on any of the four prompts (random tiny models have close
        # logits; a tie flip is numerics, not a serving bug — the
        # engine==generate assertion below holds for ANY seed).
        hf = _hf_llama(seed=3)
        cfg, params = load_llama(hf)
        engine = ContinuousBatcher(
            cfg, params, slots=2, cache_len=32,
            prompt_bucket=8, chunk_steps=2,
        )
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, n) for n in (4, 6, 3, 5)]
        rids = {}
        # Staggered admission: two requests join, the batch advances,
        # two more join mid-flight (slots > queue forces re-admission
        # into freed slots as earlier requests finish).
        for p in prompts[:2]:
            rids[engine.submit(p, max_new_tokens=6)] = p
        engine.step()
        for p in prompts[2:]:
            rids[engine.submit(p, max_new_tokens=6)] = p
        out = engine.run()
        gen = make_generate_fn(cfg)
        for rid, p in rids.items():
            with torch.no_grad():
                expected = hf.generate(
                    torch.tensor(p[None]), max_new_tokens=6,
                    do_sample=False, pad_token_id=0,
                ).numpy()[0, len(p):]
            got = np.asarray(out[rid])
            assert np.array_equal(got, expected), rid
            # The engine's own exactness invariant, seed-independent:
            # slot-pool output == standalone greedy generate.
            standalone = np.asarray(
                gen(params, jnp.asarray(p[None]), max_new_tokens=6)
            )[0]
            assert np.array_equal(got, standalone), rid

    def test_rope_scaling_rejected(self):
        from walkai_nos_tpu.models.hf import config_from_llama

        hf = _hf_llama()
        hf.config.rope_scaling = {"rope_type": "linear", "factor": 2.0}
        with pytest.raises(ValueError, match="rope_scaling"):
            config_from_llama(hf.config)

    def test_export_round_trips(self):
        """import -> export -> torch forward equals our forward."""
        from walkai_nos_tpu.models.hf import export_llama, load_llama

        hf = _hf_llama(seed=2)
        cfg, params = load_llama(hf)
        hf_config, sd = export_llama(params, cfg)
        clone = transformers.LlamaForCausalLM(hf_config).eval()
        missing, unexpected = clone.load_state_dict(sd, strict=False)
        assert not unexpected, unexpected
        tokens = np.random.default_rng(3).integers(0, 64, (2, 8))
        with torch.no_grad():
            theirs = clone(torch.tensor(tokens)).logits.numpy()
        ours = np.asarray(
            DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
        )
        assert np.max(np.abs(ours - theirs)) < 5e-4

    def test_mlp_bias_rejected(self):
        from walkai_nos_tpu.models.hf import config_from_llama

        hf = _hf_llama()
        hf.config.mlp_bias = True
        with pytest.raises(ValueError, match="mlp_bias"):
            config_from_llama(hf.config)

    def test_export_rejects_gpt2_family_config(self):
        from walkai_nos_tpu.models.hf import export_llama

        hf_gpt2 = _hf_model()
        cfg, params = load_gpt2(hf_gpt2)
        with pytest.raises(ValueError, match="llama-family"):
            export_llama(params, cfg)

"""HF GPT-2 import: config mapping and exact logit/generation parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from walkai_nos_tpu.models.decode import make_generate_fn  # noqa: E402
from walkai_nos_tpu.models.hf import (  # noqa: E402
    config_from_gpt2,
    load_gpt2,
)
from walkai_nos_tpu.models.lm import DecoderLM  # noqa: E402


def _hf_model(seed=0):
    torch.manual_seed(seed)
    cfg = transformers.GPT2Config(
        n_embd=32, n_layer=2, n_head=2, n_positions=32, vocab_size=64,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    return transformers.GPT2LMHeadModel(cfg).eval()


class TestConfigMapping:
    def test_fields(self):
        hf = _hf_model()
        cfg = config_from_gpt2(hf.config)
        assert cfg.vocab_size == 64
        assert cfg.hidden_dim == 32
        assert cfg.num_layers == 2
        assert cfg.num_heads == 2
        assert cfg.mlp_ratio == 4
        assert cfg.max_seq_len == 32
        assert cfg.layer_norm_eps == hf.config.layer_norm_epsilon

    def test_non_gelu_variant_rejected(self):
        hf = _hf_model()
        hf.config.activation_function = "relu"
        with pytest.raises(ValueError, match="gelu_new"):
            config_from_gpt2(hf.config)


class TestLogitParity:
    def test_forward_matches_torch(self):
        hf = _hf_model()
        cfg, params = load_gpt2(hf)
        tokens = np.random.default_rng(0).integers(0, 64, (2, 16))
        with torch.no_grad():
            expected = hf(torch.tensor(tokens)).logits.numpy()
        ours = np.asarray(
            DecoderLM(cfg).apply({"params": params}, jnp.asarray(tokens))
        )
        assert np.max(np.abs(ours - expected)) < 5e-4

    def test_greedy_generation_matches_torch(self):
        """The imported weights must decode the same continuation HF's
        own greedy search produces — logits, cache, and sampling all in
        agreement."""
        hf = _hf_model(seed=1)
        cfg, params = load_gpt2(hf)
        prompt = np.random.default_rng(1).integers(0, 64, (1, 4))
        with torch.no_grad():
            expected = hf.generate(
                torch.tensor(prompt), max_new_tokens=6, do_sample=False,
                pad_token_id=0,
            ).numpy()[:, 4:]
        ours = np.asarray(
            make_generate_fn(cfg)(
                params, jnp.asarray(prompt), max_new_tokens=6
            )
        )
        assert np.array_equal(ours, expected), (ours, expected)


class TestConfigGuards:
    def test_indivisible_n_inner_rejected(self):
        hf = _hf_model()
        hf.config.n_inner = 100  # not a multiple of n_embd=32
        with pytest.raises(ValueError, match="multiple of n_embd"):
            config_from_gpt2(hf.config)


class TestExport:
    def test_round_trip_through_torch(self):
        """import -> export -> torch forward must equal the original
        torch forward exactly (the TPU-trained weights land back in the
        torch ecosystem unchanged)."""
        from walkai_nos_tpu.models.hf import (
            load_gpt2,
            state_dict_from_params,
        )

        hf = _hf_model(seed=2)
        cfg, params = load_gpt2(hf)
        sd = state_dict_from_params(params, cfg)
        clone = _hf_model(seed=3)  # different random init
        clone.load_state_dict(sd, strict=False)
        tokens = torch.tensor(
            np.random.default_rng(2).integers(0, 64, (2, 12))
        )
        with torch.no_grad():
            a = hf(tokens).logits.numpy()
            b = clone(tokens).logits.numpy()
        assert np.max(np.abs(a - b)) < 1e-5

    def test_untied_head_rejected(self):
        from walkai_nos_tpu.models.hf import (
            load_gpt2,
            state_dict_from_params,
        )
        import jax.numpy as jnp

        hf = _hf_model()
        cfg, params = load_gpt2(hf)
        params = dict(params, head={
            "kernel": jnp.asarray(params["head"]["kernel"]) + 1.0,
            "bias": params["head"]["bias"],
        })
        with pytest.raises(ValueError, match="tied"):
            state_dict_from_params(params, cfg)

"""Unit tests for the tensor-parallel sharding rules
(`walkai_nos_tpu/parallel/sharding.py`): the Megatron column/row
kernel split, the QuantDense `scale` leaves riding their kernel's
output-dim sharding (the int8 tree from `quantize_lm_params` used to
fall through to the replicated catch-all), the decode-cache specs
(paged pools kv-head-split, indexes replicated), and the per-shard
byte accounting the TP-aware roofline cost model runs on."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from walkai_nos_tpu.models.lm import (
    DecoderLM,
    LMConfig,
    quantize_lm_params,
)
from walkai_nos_tpu.parallel import sharding as shardlib
from walkai_nos_tpu.parallel.mesh import (
    AXIS_FSDP,
    AXIS_MODEL,
    serving_mesh,
)

QCFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=1, num_heads=4,
    num_kv_heads=2, max_seq_len=32, dtype="float32",
    mlp="swiglu", mlp_dim=64, use_bias=True, w_dtype="int8",
)


def _quantized_params():
    raw = DecoderLM(QCFG).init_params(jax.random.PRNGKey(0))
    return quantize_lm_params(raw, QCFG)


class TestQuantDenseScaleRules:
    def test_column_parallel_scales_follow_model_axis(self):
        # qkv / gate / fc1 kernels are column-split (output features
        # on `model`); their per-output-channel scale rows must split
        # the same way or a sharded QuantDense dequantizes with a
        # misplaced scale.
        for path in (
            "block0/attn/qkv/scale",
            "block0/gate/scale",
            "block0/fc1/scale",
        ):
            assert shardlib.param_partition_spec(path) == P(AXIS_MODEL)

    def test_row_parallel_scales_follow_fsdp_axis(self):
        # out_proj / fc2 kernels are row-split P(model, fsdp): their
        # OUTPUT dim shards over fsdp, so the scale row does too.
        for path in ("block0/attn/out_proj/scale", "block0/fc2/scale"):
            assert shardlib.param_partition_spec(path) == P(AXIS_FSDP)

    def test_norm_scales_stay_replicated(self):
        # RMSNorm/LayerNorm params are also named `scale`; only the
        # quantized Dense scopes' scale rows shard.
        for path in ("block0/norm1/scale", "norm/scale"):
            assert shardlib.param_partition_spec(path) == P()

    def test_quantized_tree_specs_cover_every_scale_leaf(self):
        """End to end: quantize a real LM tree, ask for fitted specs
        on a tp=2 mesh, and check every QuantDense scope got a
        sharded scale spec matching its kernel's output split."""
        params = _quantized_params()
        mesh = serving_mesh(2)
        specs = shardlib.param_specs(params, mesh)
        attn = specs["block0"]["attn"]
        assert attn["qkv"]["kernel"] == P(AXIS_FSDP, AXIS_MODEL)
        assert attn["qkv"]["scale"] == P(AXIS_MODEL)
        assert attn["qkv"]["bias"] == P(AXIS_MODEL)
        assert attn["out_proj"]["kernel"] == P(AXIS_MODEL, AXIS_FSDP)
        # fsdp has size 1 on the serving mesh, so the row-parallel
        # scale fits trivially and keeps its rule spec.
        assert attn["out_proj"]["scale"] == P(AXIS_FSDP)
        assert specs["block0"]["gate"]["scale"] == P(AXIS_MODEL)
        assert specs["block0"]["fc1"]["scale"] == P(AXIS_MODEL)
        assert specs["block0"]["fc2"]["scale"] == P(AXIS_FSDP)
        # Norm scales replicate even in a quantized tree.
        assert specs["block0"]["norm1"]["scale"] == P()

    def test_sharded_quant_dense_matmul_matches_unsharded(self):
        """Placement proof: the int8 tree device_puts onto the mesh
        under the fitted specs, the scale row lands sharded beside
        its kernel columns, and the sharded apply reproduces the
        single-device output."""
        params = _quantized_params()
        mesh = serving_mesh(2)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 8)), jnp.int32
        )
        model = DecoderLM(QCFG)

        # One jitted program (eager apply on sharded leaves would
        # compile a distributed mini-program per op).
        @jax.jit
        def fwd(p):
            return model.apply({"params": p}, tokens)

        want = np.asarray(fwd(params))
        sharded = shardlib.shard_params(params, mesh)
        qkv = sharded["block0"]["attn"]["qkv"]
        assert qkv["scale"].sharding.shard_shape(
            qkv["scale"].shape
        )[0] == qkv["scale"].shape[0] // 2
        got = np.asarray(fwd(sharded))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_non_dividing_scale_replicates(self):
        # _fit_spec drops sharded axes the leaf's dim doesn't divide:
        # a 6-wide scale on a 4-way model axis replicates instead of
        # erroring.
        mesh = serving_mesh(4)
        spec = shardlib._fit_spec(P(AXIS_MODEL), (6,), mesh)
        assert spec == P()


class TestCacheSpecs:
    def test_pool_leaves_split_kv_heads_indexes_replicate(self):
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=4,
            num_kv_heads=2, max_seq_len=256, dtype="float32",
            ragged_decode=True, paged_decode=True, paged_blocks=5,
            cache_len=256, kv_dtype="int8-sim",
        )
        cache = DecoderLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
            decode=True,
        )["cache"]
        specs = shardlib.cache_specs(cache, serving_mesh(2))
        attn = specs["block0"]["attn"]
        assert attn["cached_key"] == P(None, AXIS_MODEL)
        assert attn["cached_value"] == P(None, AXIS_MODEL)
        assert attn["cached_key_scale"] == P(None, AXIS_MODEL)
        assert attn["cached_value_scale"] == P(None, AXIS_MODEL)
        assert attn["cache_index"] == P()

    def test_shard_cache_places_pool_slices(self):
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=4,
            num_kv_heads=2, max_seq_len=256, dtype="float32",
            ragged_decode=True, paged_decode=True, paged_blocks=5,
            cache_len=256,
        )
        cache = DecoderLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
            decode=True,
        )["cache"]
        mesh = serving_mesh(2)
        placed = shardlib.shard_cache(cache, mesh)
        pool = placed["block0"]["attn"]["cached_key"]
        # Each shard physically backs one kv head's slice of every
        # block: same block ids, half the bytes per chip.
        assert pool.sharding.shard_shape(pool.shape) == (
            pool.shape[0], 1, pool.shape[2], pool.shape[3]
        )


class TestParamsShardBytes:
    def test_sharded_tree_reports_per_device_bytes(self):
        params = _quantized_params()
        full = shardlib.params_shard_bytes(params)
        mesh = serving_mesh(2)
        sharded = shardlib.shard_params(params, mesh)
        per_shard = shardlib.params_shard_bytes(sharded)
        # Projection/MLP kernels split 2-way; embeddings/norms/head
        # bias replicate, so the per-shard sum sits strictly between
        # half and all of the full tree.
        assert full / 2 < per_shard < full
        # The sharded leaves' global nbytes are unchanged — only the
        # per-device accounting moves.
        assert shardlib.params_shard_bytes(
            jax.tree_util.tree_map(np.asarray, params)
        ) == full

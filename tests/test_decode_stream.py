"""Streamed decode kernel + amortized-dispatch generate loop (PR 1).

Tier-1 surface for the decode hot path: the streamed Pallas kernel
(`ops/decode_attention.py`) runs here in interpreter mode on CPU (no
hardware in tests — SURVEY.md §4), and the chunked generate loop
(`models/decode.py`) is pinned token-identical across every
`tokens_per_dispatch`, including EOS landing mid-chunk. This file is
deliberately NOT in conftest's `_SLOW_FILES`: the fast control-plane
loop must exercise the serving hot path's correctness surface, so the
shapes here stay small; microbenchmark-scale shapes carry an explicit
`slow` mark instead.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.ops import decode_attention as da

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2, max_seq_len=64
)


def _qkv(b=2, h=4, kvh=2, s=256, d=64, steps=None, seed=0,
         dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    qshape = (b, h, d) if steps is None else (b, h, steps, d)
    q = jnp.asarray(rng.standard_normal(qshape), dtype)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
    return q, k, v


def _prompt(b=2, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (b, n)), jnp.int32)


class TestStreamedKernelParity:
    """The streamed kernel (blocked cache iteration, logsumexp-combined
    partial softmax, skipped tail blocks) vs the XLA reference."""

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    @pytest.mark.parametrize("index", [0, 127, 128, 255])
    def test_gqa_shapes_and_bucket_boundaries(self, kvh, index):
        """kv_heads ∈ {1, 2, 4} across cache-block boundary indices
        (127/128: the skip decision flips exactly here)."""
        q, k, v = _qkv(kvh=kvh)
        out = da.decode_attention(q, k, v, jnp.int32(index), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(index))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_uneven_per_slot_cache_lengths(self):
        """Ragged decoding: each row at its own position, spanning
        different visible block counts within one grid block."""
        q, k, v = _qkv(b=4, kvh=2, s=384)
        idx = jnp.asarray([0, 17, 129, 383], jnp.int32)
        out = da.decode_attention(q, k, v, idx, interpret=True)
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_skipped_tail_blocks_never_leak(self):
        """Cache rows in blocks wholly past the index must not affect
        the output — they are skipped, not read-and-masked, so poison
        there must be invisible."""
        q, k, v = _qkv(s=384, seed=1)
        pk = k.at[:, :, 128:].set(jnp.inf)  # blocks 1 and 2 poisoned
        pv = v.at[:, :, 128:].set(jnp.inf)
        out = da.decode_attention(q, pk, pv, jnp.int32(99), interpret=True)
        clean = da.decode_attention(q, k, v, jnp.int32(99), interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(clean))

    @pytest.mark.parametrize("steps", [2, 7])
    def test_multi_step_queries(self, steps):
        """steps query positions per head (the speculative verify
        shape): row r at position index + r sees cache rows
        <= index + r."""
        q, k, v = _qkv(steps=steps)
        out = da.decode_attention(q, k, v, jnp.int32(120), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(120))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_multi_step_crosses_block_boundary(self):
        """Queries whose positions straddle a 128-row block edge keep
        the boundary block visible for the later rows only."""
        q, k, v = _qkv(steps=4, seed=2)
        out = da.decode_attention(q, k, v, jnp.int32(126), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(126))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_multi_step_ragged(self):
        q, k, v = _qkv(b=4, kvh=2, steps=3, seed=3)
        idx = jnp.asarray([0, 100, 126, 250], jnp.int32)
        out = da.decode_attention(q, k, v, idx, interpret=True)
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_bf16_inputs_f32_accumulation(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=4)
        out = da.decode_attention(q, k, v, jnp.int32(200), interpret=True)
        ref = da.decode_attention_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), jnp.int32(200),
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
        )

    def test_untiled_cache_falls_back(self):
        q, k, v = _qkv(s=100)
        out = da.decode_attention(q, k, v, jnp.int32(50))
        ref = da.decode_attention_reference(q, k, v, jnp.int32(50))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    @pytest.mark.slow
    def test_serving_scale_shape(self):
        """Microbenchmark-scale parity (the bench's b=128, kv=2 serving
        point, interpreted): slow — the interpreter walks 256 grid
        steps of 16-cell blocks."""
        q, k, v = _qkv(b=128, h=8, kvh=2, s=256, seed=5)
        out = da.decode_attention(q, k, v, jnp.int32(160), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(160))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )


def _paged_pool(k, v, nlog, extra_blocks=4, seed=0):
    """Scatter a dense [b, kvh, nlog*128, d] cache into a SHUFFLED
    block pool + per-slot table (physical order deliberately unlike
    logical order, plus unreferenced garbage blocks)."""
    b, kvh, s, d = k.shape
    assert s == nlog * 128
    rng = np.random.default_rng(seed)
    nb = 1 + b * nlog + extra_blocks  # block 0 reserved, like serve.py
    k_pool = rng.standard_normal((nb, kvh, 128, d))
    v_pool = rng.standard_normal((nb, kvh, 128, d))
    table = np.zeros((b, nlog), np.int32)
    perm = rng.permutation(np.arange(1, nb))[: b * nlog]
    for bi in range(b):
        for j in range(nlog):
            p = perm[bi * nlog + j]
            table[bi, j] = p
            k_pool[p] = np.asarray(k, np.float64)[bi, :, j*128:(j+1)*128]
            v_pool[p] = np.asarray(v, np.float64)[bi, :, j*128:(j+1)*128]
    return (
        jnp.asarray(k_pool, k.dtype), jnp.asarray(v_pool, v.dtype),
        jnp.asarray(table),
    )


class TestPagedKernelParity:
    """The table-indexed (gather-grid) variant of the streamed kernel
    vs the dense XLA reference: block indirection must change WHERE a
    block is read from, never what the softmax sees."""

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    def test_shuffled_pool_matches_dense_reference(self, kvh):
        q, k, v = _qkv(b=3, kvh=kvh, s=384)
        idx = jnp.asarray([0, 129, 383], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        out = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_unreferenced_pool_blocks_never_leak(self):
        """Pool blocks no table entry references — and referenced
        blocks wholly past a slot's index — must not affect output:
        poison there must be invisible (tail blocks are skipped via
        the clamped table lookup, not read-and-masked)."""
        q, k, v = _qkv(b=2, kvh=2, s=384, seed=1)
        idx = jnp.asarray([64, 130], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        referenced = set(np.asarray(table).ravel().tolist())
        tbl = np.asarray(table)
        poison_k = np.array(k_pool)
        poison_v = np.array(v_pool)
        for p in range(k_pool.shape[0]):
            if p not in referenced:
                poison_k[p] = poison_v[p] = np.inf
        # Slot 0 at index 64 sees only its logical block 0: poison its
        # blocks 1 and 2 as well.
        poison_k[tbl[0, 1]] = poison_k[tbl[0, 2]] = np.inf
        poison_v[tbl[0, 1]] = poison_v[tbl[0, 2]] = np.inf
        out = da.paged_decode_attention(
            q, jnp.asarray(poison_k, k.dtype), jnp.asarray(poison_v, v.dtype),
            table, idx, interpret=True,
        )
        clean = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(clean))

    @pytest.mark.parametrize("steps", [3, 7])
    def test_multi_step_crosses_block_boundary(self, steps):
        q, k, v = _qkv(b=2, kvh=2, s=256, steps=steps, seed=2)
        idx = jnp.asarray([126, 40], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=2)
        out = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_index_past_logical_capacity_clamps(self):
        """Freed serving slots keep stepping with index past their
        logical capacity (models/serve.py parks them on the scratch
        block): the visible-block count must clamp to the table width
        instead of reading out of bounds."""
        q, k, v = _qkv(b=2, kvh=2, s=256, seed=3)
        idx = jnp.asarray([255, 1000], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=2)
        out = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        # Past-capacity index sees the whole gathered view — same as
        # the reference at a full-cache index.
        ref = da.decode_attention_reference(
            q, k, v, jnp.asarray([255, 255], jnp.int32)
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_multi_step_heterogeneous_positions(self):
        """The speculative-serving verify shape: every slot at its OWN
        write head with k+1 query steps each — heads at the cache
        start, mid-block, straddling the 128-row edge, and deep in
        block 3 must each see exactly rows <= head + step."""
        q, k, v = _qkv(b=4, kvh=2, s=384, steps=4, seed=6)
        idx = jnp.asarray([0, 100, 126, 290], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        out = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_rejected_speculative_rows_invisible(self):
        """The serving engine's rollback invariant: rows a rejected
        verify window wrote past the committed head need no device
        rewind BECAUSE a later round's queries cannot see them —
        overwrite every pool row strictly past each slot's last
        visible position (head + steps - 1) with garbage and the
        multi-step output must be bit-identical. (Finite garbage, not
        inf: rows inside a partially visible block are read and
        score-masked, so the test asserts zero INFLUENCE, which is
        the serving invariant.)"""
        q, k, v = _qkv(b=2, kvh=2, s=384, steps=3, seed=7)
        idx = np.asarray([126, 40], np.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        tbl = np.asarray(table)
        poison_k = np.array(k_pool)
        poison_v = np.array(v_pool)
        for r in range(2):
            first_hidden = int(idx[r]) + 3  # steps = 3
            for j in range(3):
                lo = max(0, first_hidden - j * 128)
                if lo < 128:
                    poison_k[tbl[r, j], :, lo:] = 1e4
                    poison_v[tbl[r, j], :, lo:] = -1e4
        out = da.paged_decode_attention(
            q, jnp.asarray(poison_k, k.dtype),
            jnp.asarray(poison_v, v.dtype), table,
            jnp.asarray(idx), interpret=True,
        )
        clean = da.paged_decode_attention(
            q, k_pool, v_pool, table, jnp.asarray(idx), interpret=True
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(clean))

    def test_bf16_pool_f32_accumulation(self):
        q, k, v = _qkv(b=2, kvh=2, s=256, dtype=jnp.bfloat16, seed=4)
        idx = jnp.asarray([200, 77], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=2)
        out = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        ref = da.decode_attention_reference(
            q.astype(jnp.float32),
            da.gather_paged_cache(k_pool, table).astype(jnp.float32),
            da.gather_paged_cache(v_pool, table).astype(jnp.float32),
            idx,
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
        )


def _quantize_pools(k_pool, v_pool, quant="int8"):
    """Quantize fp pools per row (the emit rule, applied offline):
    pools keep their [nb, kvh, 128, d] shape, scales parallel them at
    [nb, kvh, 128]."""
    kq, ks = da.quantize_kv_rows(k_pool, quant)
    vq, vs = da.quantize_kv_rows(v_pool, quant)
    return (
        kq if quant == "int8" else jnp.asarray(kq, k_pool.dtype),
        vq if quant == "int8" else jnp.asarray(vq, v_pool.dtype),
        ks, vs,
    )


class TestQuantizedKernelParity:
    """Dtype matrix for the quantized paged kernels: int8 pools with
    per-row scale tiles must match the dequantized-gather reference
    through the table-indexed grid (shuffled physical order, ragged
    indices, block-edge crossings), and the "sim" arm must be
    bit-identical to the unquantized kernel — the lossless-plumbing
    property the serving parity suite builds on."""

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    def test_int8_pool_matches_dequant_reference(self, kvh):
        q, k, v = _qkv(b=3, kvh=kvh, s=384)
        idx = jnp.asarray([0, 129, 383], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        kq, vq, ks, vs = _quantize_pools(k_pool, v_pool)
        out = da.paged_decode_attention(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs,
            interpret=True,
        )
        ref = da.paged_decode_attention_reference(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("steps", [3, 7])
    def test_int8_multi_step_crosses_block_edge(self, steps):
        """The speculative verify shape over a quantized pool: per-
        slot heads mid-block and straddling the 128-row edge."""
        q, k, v = _qkv(b=2, kvh=2, s=256, steps=steps, seed=2)
        idx = jnp.asarray([126, 40], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=2)
        kq, vq, ks, vs = _quantize_pools(k_pool, v_pool)
        out = da.paged_decode_attention(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs,
            interpret=True,
        )
        ref = da.paged_decode_attention_reference(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_int8_bf16_queries(self):
        """bf16 q over an int8 pool — the serving dtype pairing: the
        int8->bf16 tile convert is lossless, folds accumulate f32."""
        q, k, v = _qkv(b=2, kvh=2, s=256, dtype=jnp.bfloat16, seed=4)
        idx = jnp.asarray([200, 77], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=2)
        kq, vq, ks, vs = _quantize_pools(k_pool, v_pool)
        out = da.paged_decode_attention(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs,
            interpret=True,
        )
        ref = da.paged_decode_attention_reference(
            q, kq, vq, table, idx, k_scales=ks, v_scales=vs
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32),
            np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2,
        )

    def test_sim_mode_bit_identical_to_unquantized(self):
        """quant="sim" stores the same values with unit scales: the
        kernel's scale plumbing runs, the output must not move a
        bit vs the unquantized kernel."""
        q, k, v = _qkv(b=2, kvh=2, s=384, seed=5)
        idx = jnp.asarray([100, 290], jnp.int32)
        k_pool, v_pool, table = _paged_pool(k, v, nlog=3)
        ksim, vsim, ks, vs = _quantize_pools(k_pool, v_pool, "sim")
        out = da.paged_decode_attention(
            q, ksim, vsim, table, idx, k_scales=ks, v_scales=vs,
            interpret=True,
        )
        plain = da.paged_decode_attention(
            q, k_pool, v_pool, table, idx, interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(plain)
        )

    def test_scatter_quantizes_at_emit(self):
        """`scatter_paged_rows` with quant: fresh rows round-trip
        within int8 resolution, their scales land at the same
        (block, row) indices, and rows past the table's logical
        capacity DROP — data and scales alike."""
        rng = np.random.default_rng(0)
        nb, kvh, hd, steps = 6, 2, 16, 3
        kp = jnp.zeros((nb, kvh, da.PAGE_ROWS, hd), jnp.int8)
        vp = jnp.zeros((nb, kvh, da.PAGE_ROWS, hd), jnp.int8)
        ksp = jnp.zeros((nb, kvh, da.PAGE_ROWS), jnp.float32)
        vsp = jnp.zeros((nb, kvh, da.PAGE_ROWS), jnp.float32)
        table = jnp.asarray([[3, 1], [4, 2]], jnp.int32)
        k = jnp.asarray(rng.standard_normal((2, kvh, steps, hd)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, kvh, steps, hd)), jnp.float32)
        # Slot 0 crosses the block edge (127 -> 129); slot 1's window
        # runs off the table's logical capacity (254, 255, 256-drop).
        idx = jnp.asarray([127, 254], jnp.int32)
        kp2, vp2, ksp2, vsp2 = da.scatter_paged_rows(
            kp, vp, k, v, table, idx,
            k_scale_pool=ksp, v_scale_pool=vsp, quant="int8",
        )
        kq = np.asarray(kp2, np.float64)
        ks = np.asarray(ksp2, np.float64)
        # Slot 0: row 127 of block 3, rows 0-1 of block 1.
        for t, (blk, row) in enumerate([(3, 127), (1, 0), (1, 1)]):
            deq = kq[blk, :, row, :] * ks[blk, :, row, None]
            want = np.asarray(k)[0, :, t, :]
            tol = np.abs(want).max(axis=-1, keepdims=True) / 127 + 1e-6
            assert (np.abs(deq - want) <= tol).all(), (t, blk, row)
            assert (ks[blk, :, row] > 0).all()
        # Slot 1: rows 254, 255 land in block 2; position 256 DROPS.
        assert (ks[2, :, 126:128] > 0).all()
        written = np.zeros_like(ks, bool)
        written[3, :, 127] = written[1, :, 0:2] = True
        written[2, :, 126:128] = True
        assert (ks[~written] == 0).all(), "dropped row leaked a scale"
        assert (np.asarray(vsp2)[~written] == 0).all()

    def test_fused_int8_weight_and_pool(self):
        """The fused kernel's full quantized configuration: int8
        weight + per-channel scale row dequantized before the MXU,
        int8 pools + scale tiles dequantized in the fold, rope on,
        vs the dequant-composition reference."""
        rng = np.random.default_rng(3)
        kvh, h, hd, steps, b = 2, 4, 16, 4, 3
        dm = h * hd
        dout = dm + 2 * kvh * hd
        x = jnp.asarray(rng.standard_normal((b, steps, dm)), jnp.float32)
        w = rng.standard_normal((dm, dout)) * 0.1
        w_scale = jnp.asarray(
            np.maximum(np.abs(w).max(axis=0) / 127, 1e-12), jnp.float32
        )
        wq = jnp.asarray(
            np.clip(np.round(w / np.asarray(w_scale)), -127, 127),
            jnp.int8,
        )
        bias = jnp.asarray(rng.standard_normal(dout) * 0.1, jnp.float32)
        kp = jnp.asarray(
            rng.standard_normal((12, kvh, da.PAGE_ROWS, hd)), jnp.float32
        )
        vp = jnp.asarray(
            rng.standard_normal((12, kvh, da.PAGE_ROWS, hd)), jnp.float32
        )
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        table = jnp.asarray(
            rng.permutation(np.arange(1, 12))[:9].reshape(3, 3),
            jnp.int32,
        )
        index = jnp.asarray([0, 126, 200], jnp.int32)
        out = da.fused_qkv_paged_attention(
            x, wq, bias, kq, vq, table, index,
            num_heads=h, rope_theta=10000.0, w_scale=w_scale,
            k_scales=ks, v_scales=vs, interpret=True,
        )
        ref = da.fused_qkv_paged_reference(
            x, wq, bias, kq, vq, table, index,
            num_heads=h, rope_theta=10000.0, w_scale=w_scale,
            k_scales=ks, v_scales=vs,
        )
        for name, a, bb in zip(("o", "k_new", "v_new"), out, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(bb), atol=1e-4, rtol=1e-4,
                err_msg=name,
            )

    def test_fused_fresh_rows_stay_full_precision(self):
        """Injected fresh rows must bypass the pool's scales entirely
        (their scale column pins to 1.0 in-kernel): poisoning the
        scale pools at every write position must not move the
        output."""
        rng = np.random.default_rng(6)
        kvh, h, hd, steps, b = 2, 4, 16, 4, 2
        dm = h * hd
        dout = dm + 2 * kvh * hd
        x = jnp.asarray(rng.standard_normal((b, steps, dm)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((dm, dout)) * 0.1, jnp.float32)
        kp = jnp.asarray(
            rng.standard_normal((9, kvh, da.PAGE_ROWS, hd)), jnp.float32
        )
        vp = jnp.asarray(
            rng.standard_normal((9, kvh, da.PAGE_ROWS, hd)), jnp.float32
        )
        kq, vq, ks, vs = _quantize_pools(kp, vp)
        table = jnp.asarray(
            np.arange(1, 9).reshape(2, 4), jnp.int32
        )
        index = jnp.asarray([126, 40], jnp.int32)
        poison_ks, poison_vs = np.asarray(ks).copy(), np.asarray(vs).copy()
        for s in range(b):
            for t in range(steps):
                pos = int(index[s]) + t
                blk = int(table[s, pos // da.PAGE_ROWS])
                poison_ks[blk, :, pos % da.PAGE_ROWS] = 1e6
                poison_vs[blk, :, pos % da.PAGE_ROWS] = 1e6
        clean = da.fused_qkv_paged_attention(
            x, w, None, kq, vq, table, index, num_heads=h,
            k_scales=ks, v_scales=vs, interpret=True,
        )
        poisoned = da.fused_qkv_paged_attention(
            x, w, None, kq, vq, table, index, num_heads=h,
            k_scales=jnp.asarray(poison_ks),
            v_scales=jnp.asarray(poison_vs), interpret=True,
        )
        for a, bb in zip(clean, poisoned):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))


class TestAmortizedDispatch:
    """`tokens_per_dispatch` changes WHEN the host syncs, never the
    tokens: every chunk size must be bit-identical to the single-step
    path."""

    @pytest.fixture(scope="class")
    def params(self):
        return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))

    @pytest.mark.parametrize("tpd", [1, 4, 8])
    def test_greedy_token_identical_across_dispatch_sizes(
        self, params, tpd
    ):
        base = make_generate_fn(CFG, tokens_per_dispatch=1)(
            params, _prompt(), max_new_tokens=11
        )
        out = make_generate_fn(CFG, tokens_per_dispatch=tpd)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(base, out), (tpd, base, out)

    def test_one_shot_default_matches_chunked(self, params):
        """tokens_per_dispatch=None (whole generation per dispatch,
        the bench's shape) emits the same tokens as chunked."""
        one_shot = make_generate_fn(CFG)(
            params, _prompt(), max_new_tokens=11
        )
        chunked = make_generate_fn(CFG, tokens_per_dispatch=4)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(one_shot, chunked)

    @pytest.mark.parametrize("tpd", [1, 4, 8])
    def test_eos_mid_chunk_token_identical(self, params, tpd):
        """EOS landing mid-chunk: finished rows pad deterministically
        with eos_id, so every dispatch size agrees — including the
        early-exit host path (all rows done before the budget)."""
        full = make_generate_fn(CFG)(params, _prompt(), max_new_tokens=11)
        eos = int(full[0, 5])  # row 0 finishes mid-generation
        base = make_generate_fn(CFG, tokens_per_dispatch=1, eos_id=eos)(
            params, _prompt(), max_new_tokens=11
        )
        out = make_generate_fn(CFG, tokens_per_dispatch=tpd, eos_id=eos)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(base, out), (tpd, base, out)
        # Post-EOS suffix is all-eos in every row that hit it.
        arr = np.asarray(out)
        for row in arr:
            hits = np.where(row == eos)[0]
            if len(hits):
                assert (row[hits[0]:] == eos).all(), row

    def test_sampling_deterministic_across_dispatch_sizes(self, params):
        a = make_generate_fn(CFG, temperature=1.0, tokens_per_dispatch=3)(
            params, _prompt(), max_new_tokens=9,
            rng=jax.random.PRNGKey(7),
        )
        b = make_generate_fn(CFG, temperature=1.0, tokens_per_dispatch=1)(
            params, _prompt(), max_new_tokens=9,
            rng=jax.random.PRNGKey(7),
        )
        assert jnp.array_equal(a, b)
        assert bool(jnp.all((0 <= a) & (a < CFG.vocab_size)))

    def test_generator_is_reusable(self, params):
        """The donated carry is engine-internal: back-to-back calls on
        one generator (fresh prefill each) must agree — donation must
        never consume the params or leak state across calls."""
        gen = make_generate_fn(CFG, tokens_per_dispatch=4)
        a = gen(params, _prompt(), max_new_tokens=7)
        b = gen(params, _prompt(), max_new_tokens=7)
        assert jnp.array_equal(a, b)

    def test_bad_tokens_per_dispatch_rejected(self):
        with pytest.raises(ValueError, match="tokens_per_dispatch"):
            make_generate_fn(CFG, tokens_per_dispatch=0)


class TestKernelThroughModel:
    """End-to-end greedy decode THROUGH the streamed kernel (interpret
    mode forced via WALKAI_DECODE_INTERPRET — the CPU seam): the kernel
    path must emit exactly the tokens the XLA reference path does."""

    def test_gqa_generate_matches_reference_path(self, monkeypatch):
        cfg = dataclasses.replace(CFG, num_kv_heads=1, max_seq_len=256)
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        ref = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        monkeypatch.setenv("WALKAI_DECODE_INTERPRET", "1")
        out = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        assert jnp.array_equal(ref, out), (ref, out)


class TestFusedQkvKernel:
    """Fused QKV projection + rotary + paged attention
    (`ops/decode_attention.fused_qkv_paged_attention`): interpret-mode
    CI pins the fusion against the unfused composition
    (`fused_qkv_paged_reference` — projection, split, rope, pool
    scatter, gather-reference attention), across storage dtypes,
    kv-head counts, and rope on/off — the dtype-parity seam for a
    kernel whose TPU lowering CI cannot run."""

    def _case(self, kvh, dtype, rope, *, steps=4, b=3, hd=16, seed=0):
        rng = np.random.default_rng(seed)
        h = 4
        dm = h * hd
        nlog, nb = 3, 12
        x = jnp.asarray(rng.standard_normal((b, steps, dm)), dtype)
        w = jnp.asarray(
            rng.standard_normal((dm, dm + 2 * kvh * hd)) * 0.1, dtype
        )
        bias = jnp.asarray(
            rng.standard_normal(dm + 2 * kvh * hd) * 0.1, dtype
        )
        kp = jnp.asarray(
            rng.standard_normal((nb, kvh, da.PAGE_ROWS, hd)), dtype
        )
        vp = jnp.asarray(
            rng.standard_normal((nb, kvh, da.PAGE_ROWS, hd)), dtype
        )
        # Shuffled table (physical != logical) + ragged indices, some
        # mid-block, some crossing a block edge inside the window.
        table = jnp.asarray(
            rng.permutation(np.arange(1, nb))[:b * nlog].reshape(
                b, nlog
            ),
            jnp.int32,
        )
        index = jnp.asarray([0, 126, 200][:b], jnp.int32)
        theta = 10000.0 if rope else None
        return (x, w, bias, kp, vp, table, index), theta

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    @pytest.mark.parametrize("rope", [False, True])
    def test_fp32_parity(self, kvh, rope):
        args, theta = self._case(kvh, jnp.float32, rope)
        out = da.fused_qkv_paged_attention(
            *args, num_heads=4, rope_theta=theta, interpret=True
        )
        ref = da.fused_qkv_paged_reference(
            *args, num_heads=4, rope_theta=theta
        )
        for name, a, b in zip(("o", "k_new", "v_new"), out, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5,
                err_msg=name,
            )

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    @pytest.mark.parametrize("rope", [False, True])
    def test_bf16_parity(self, kvh, rope):
        """bf16 storage: kernel folds accumulate f32 and the rope math
        runs f32 either way, so the paths agree within bf16 rounding."""
        args, theta = self._case(kvh, jnp.bfloat16, rope)
        out = da.fused_qkv_paged_attention(
            *args, num_heads=4, rope_theta=theta, interpret=True
        )
        ref = da.fused_qkv_paged_reference(
            *args, num_heads=4, rope_theta=theta
        )
        for name, a, b in zip(("o", "k_new", "v_new"), out, ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=3e-2, rtol=3e-2, err_msg=name,
            )

    def test_bias_free_and_single_step(self):
        """use_bias=False models pass b_qkv=None; steps=1 is the
        serving decode step."""
        (x, w, _, kp, vp, table, index), _ = self._case(
            2, jnp.float32, True, steps=1
        )
        out = da.fused_qkv_paged_attention(
            x, w, None, kp, vp, table, index,
            num_heads=4, rope_theta=10000.0, interpret=True,
        )
        ref = da.fused_qkv_paged_reference(
            x, w, None, kp, vp, table, index,
            num_heads=4, rope_theta=10000.0,
        )
        for a, b in zip(out, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
            )

    def test_fresh_rows_visible_to_fold(self):
        """The kernel must attend to the rows it just projected
        WITHOUT a prior pool update (in-VMEM injection): poison the
        pool at the write positions — the output must match the
        reference, which scatters before attending, not the poison."""
        (x, w, bias, kp, vp, table, index), _ = self._case(
            2, jnp.float32, False
        )
        steps = x.shape[1]
        poison = kp
        for s in range(x.shape[0]):
            base = int(index[s])
            for t in range(steps):
                blk = int(table[s, (base + t) // da.PAGE_ROWS])
                row = (base + t) % da.PAGE_ROWS
                poison = poison.at[blk, :, row, :].set(1e4)
        out_o, _, _ = da.fused_qkv_paged_attention(
            x, w, bias, poison, vp, table, index,
            num_heads=4, rope_theta=None, interpret=True,
        )
        ref_o, _, _ = da.fused_qkv_paged_reference(
            x, w, bias, poison, vp, table, index,
            num_heads=4, rope_theta=None,
        )
        np.testing.assert_allclose(
            np.asarray(out_o), np.asarray(ref_o), atol=2e-5, rtol=2e-5
        )

    def test_model_routing_parity(self, monkeypatch):
        """`LMConfig.fused_qkv` routing through DecoderLM (the
        WALKAI_FUSED_QKV interpret seam): fused and unfused paged
        decode must agree on logits AND the whole cache tree — pools,
        write heads — for a rope+GQA llama-family config."""
        monkeypatch.setenv("WALKAI_FUSED_QKV", "1")
        monkeypatch.setenv("WALKAI_DECODE_INTERPRET", "1")
        cfg = dataclasses.replace(
            CFG, num_heads=4, num_kv_heads=2, rope=True,
            norm="rmsnorm", mlp="swiglu", use_bias=False,
            ragged_decode=True, cache_len=256, max_seq_len=512,
            paged_decode=True, paged_blocks=9,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        table = jnp.asarray(
            np.arange(1, 9).reshape(2, 4), jnp.int32
        )
        tok = jnp.asarray([[3, 5], [7, 9]], jnp.int32)
        outs = {}
        for fused in (True, False):
            model = DecoderLM(
                dataclasses.replace(cfg, fused_qkv=fused)
            )
            cache = model.init(
                jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32),
                decode=True,
            )["cache"]
            logits, vs = model.apply(
                {"params": params, "cache": cache}, tok, decode=True,
                block_table=table, mutable=["cache"],
            )
            outs[fused] = (logits, vs["cache"])
        np.testing.assert_allclose(
            np.asarray(outs[True][0]), np.asarray(outs[False][0]),
            atol=2e-4, rtol=2e-4,
        )
        flat_f = jax.tree_util.tree_leaves(outs[True][1])
        flat_u = jax.tree_util.tree_leaves(outs[False][1])
        for a, b in zip(flat_f, flat_u):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=2e-4, rtol=2e-4,
            )

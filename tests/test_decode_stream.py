"""Streamed decode kernel + amortized-dispatch generate loop (PR 1).

Tier-1 surface for the decode hot path: the streamed Pallas kernel
(`ops/decode_attention.py`) runs here in interpreter mode on CPU (no
hardware in tests — SURVEY.md §4), and the chunked generate loop
(`models/decode.py`) is pinned token-identical across every
`tokens_per_dispatch`, including EOS landing mid-chunk. This file is
deliberately NOT in conftest's `_SLOW_FILES`: the fast control-plane
loop must exercise the serving hot path's correctness surface, so the
shapes here stay small; microbenchmark-scale shapes carry an explicit
`slow` mark instead.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.ops import decode_attention as da

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2, max_seq_len=64
)


def _qkv(b=2, h=4, kvh=2, s=256, d=64, steps=None, seed=0,
         dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    qshape = (b, h, d) if steps is None else (b, h, steps, d)
    q = jnp.asarray(rng.standard_normal(qshape), dtype)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
    return q, k, v


def _prompt(b=2, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (b, n)), jnp.int32)


class TestStreamedKernelParity:
    """The streamed kernel (blocked cache iteration, logsumexp-combined
    partial softmax, skipped tail blocks) vs the XLA reference."""

    @pytest.mark.parametrize("kvh", [1, 2, 4])
    @pytest.mark.parametrize("index", [0, 127, 128, 255])
    def test_gqa_shapes_and_bucket_boundaries(self, kvh, index):
        """kv_heads ∈ {1, 2, 4} across cache-block boundary indices
        (127/128: the skip decision flips exactly here)."""
        q, k, v = _qkv(kvh=kvh)
        out = da.decode_attention(q, k, v, jnp.int32(index), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(index))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_uneven_per_slot_cache_lengths(self):
        """Ragged decoding: each row at its own position, spanning
        different visible block counts within one grid block."""
        q, k, v = _qkv(b=4, kvh=2, s=384)
        idx = jnp.asarray([0, 17, 129, 383], jnp.int32)
        out = da.decode_attention(q, k, v, idx, interpret=True)
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_skipped_tail_blocks_never_leak(self):
        """Cache rows in blocks wholly past the index must not affect
        the output — they are skipped, not read-and-masked, so poison
        there must be invisible."""
        q, k, v = _qkv(s=384, seed=1)
        pk = k.at[:, :, 128:].set(jnp.inf)  # blocks 1 and 2 poisoned
        pv = v.at[:, :, 128:].set(jnp.inf)
        out = da.decode_attention(q, pk, pv, jnp.int32(99), interpret=True)
        clean = da.decode_attention(q, k, v, jnp.int32(99), interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(clean))

    @pytest.mark.parametrize("steps", [2, 7])
    def test_multi_step_queries(self, steps):
        """steps query positions per head (the speculative verify
        shape): row r at position index + r sees cache rows
        <= index + r."""
        q, k, v = _qkv(steps=steps)
        out = da.decode_attention(q, k, v, jnp.int32(120), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(120))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_multi_step_crosses_block_boundary(self):
        """Queries whose positions straddle a 128-row block edge keep
        the boundary block visible for the later rows only."""
        q, k, v = _qkv(steps=4, seed=2)
        out = da.decode_attention(q, k, v, jnp.int32(126), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(126))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_multi_step_ragged(self):
        q, k, v = _qkv(b=4, kvh=2, steps=3, seed=3)
        idx = jnp.asarray([0, 100, 126, 250], jnp.int32)
        out = da.decode_attention(q, k, v, idx, interpret=True)
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_bf16_inputs_f32_accumulation(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=4)
        out = da.decode_attention(q, k, v, jnp.int32(200), interpret=True)
        ref = da.decode_attention_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), jnp.int32(200),
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
        )

    def test_untiled_cache_falls_back(self):
        q, k, v = _qkv(s=100)
        out = da.decode_attention(q, k, v, jnp.int32(50))
        ref = da.decode_attention_reference(q, k, v, jnp.int32(50))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    @pytest.mark.slow
    def test_serving_scale_shape(self):
        """Microbenchmark-scale parity (the bench's b=128, kv=2 serving
        point, interpreted): slow — the interpreter walks 256 grid
        steps of 16-cell blocks."""
        q, k, v = _qkv(b=128, h=8, kvh=2, s=256, seed=5)
        out = da.decode_attention(q, k, v, jnp.int32(160), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(160))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )


class TestAmortizedDispatch:
    """`tokens_per_dispatch` changes WHEN the host syncs, never the
    tokens: every chunk size must be bit-identical to the single-step
    path."""

    @pytest.fixture(scope="class")
    def params(self):
        return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))

    @pytest.mark.parametrize("tpd", [1, 4, 8])
    def test_greedy_token_identical_across_dispatch_sizes(
        self, params, tpd
    ):
        base = make_generate_fn(CFG, tokens_per_dispatch=1)(
            params, _prompt(), max_new_tokens=11
        )
        out = make_generate_fn(CFG, tokens_per_dispatch=tpd)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(base, out), (tpd, base, out)

    def test_one_shot_default_matches_chunked(self, params):
        """tokens_per_dispatch=None (whole generation per dispatch,
        the bench's shape) emits the same tokens as chunked."""
        one_shot = make_generate_fn(CFG)(
            params, _prompt(), max_new_tokens=11
        )
        chunked = make_generate_fn(CFG, tokens_per_dispatch=4)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(one_shot, chunked)

    @pytest.mark.parametrize("tpd", [1, 4, 8])
    def test_eos_mid_chunk_token_identical(self, params, tpd):
        """EOS landing mid-chunk: finished rows pad deterministically
        with eos_id, so every dispatch size agrees — including the
        early-exit host path (all rows done before the budget)."""
        full = make_generate_fn(CFG)(params, _prompt(), max_new_tokens=11)
        eos = int(full[0, 5])  # row 0 finishes mid-generation
        base = make_generate_fn(CFG, tokens_per_dispatch=1, eos_id=eos)(
            params, _prompt(), max_new_tokens=11
        )
        out = make_generate_fn(CFG, tokens_per_dispatch=tpd, eos_id=eos)(
            params, _prompt(), max_new_tokens=11
        )
        assert jnp.array_equal(base, out), (tpd, base, out)
        # Post-EOS suffix is all-eos in every row that hit it.
        arr = np.asarray(out)
        for row in arr:
            hits = np.where(row == eos)[0]
            if len(hits):
                assert (row[hits[0]:] == eos).all(), row

    def test_sampling_deterministic_across_dispatch_sizes(self, params):
        a = make_generate_fn(CFG, temperature=1.0, tokens_per_dispatch=3)(
            params, _prompt(), max_new_tokens=9,
            rng=jax.random.PRNGKey(7),
        )
        b = make_generate_fn(CFG, temperature=1.0, tokens_per_dispatch=1)(
            params, _prompt(), max_new_tokens=9,
            rng=jax.random.PRNGKey(7),
        )
        assert jnp.array_equal(a, b)
        assert bool(jnp.all((0 <= a) & (a < CFG.vocab_size)))

    def test_generator_is_reusable(self, params):
        """The donated carry is engine-internal: back-to-back calls on
        one generator (fresh prefill each) must agree — donation must
        never consume the params or leak state across calls."""
        gen = make_generate_fn(CFG, tokens_per_dispatch=4)
        a = gen(params, _prompt(), max_new_tokens=7)
        b = gen(params, _prompt(), max_new_tokens=7)
        assert jnp.array_equal(a, b)

    def test_bad_tokens_per_dispatch_rejected(self):
        with pytest.raises(ValueError, match="tokens_per_dispatch"):
            make_generate_fn(CFG, tokens_per_dispatch=0)


class TestKernelThroughModel:
    """End-to-end greedy decode THROUGH the streamed kernel (interpret
    mode forced via WALKAI_DECODE_INTERPRET — the CPU seam): the kernel
    path must emit exactly the tokens the XLA reference path does."""

    def test_gqa_generate_matches_reference_path(self, monkeypatch):
        cfg = dataclasses.replace(CFG, num_kv_heads=1, max_seq_len=256)
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        ref = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        monkeypatch.setenv("WALKAI_DECODE_INTERPRET", "1")
        out = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        assert jnp.array_equal(ref, out), (ref, out)

"""k8s Quantity parser tests."""

import pytest

from walkai_nos_tpu.utils.quantity import parse_quantity


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("1", 1), ("2k", 2000), ("1Ki", 1024), ("3Mi", 3 * 2**20),
        ("2000m", 2), (4, 4), (2.0, 2), ("0", 0), ("-1", -1),
    ],
)
def test_valid(raw, expected):
    assert parse_quantity(raw) == expected


@pytest.mark.parametrize("raw", ["1.5", "", "zz", "1500m", 1.5, "1e"])
def test_invalid(raw):
    with pytest.raises(ValueError):
        parse_quantity(raw)

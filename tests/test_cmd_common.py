"""cmd/_common wiring tests: namespace resolution, health/metrics server
split (the kube-rbac-proxy topology), shutdown signal latch."""

from __future__ import annotations

import os
import signal
import urllib.error
import urllib.request

from walkai_nos_tpu.cmd import _common


def _get(url: str) -> tuple[int, str]:
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestCurrentNamespace:
    def test_env_wins(self, monkeypatch):
        monkeypatch.setenv("POD_NAMESPACE", "walkai-nos")
        assert _common.current_namespace() == "walkai-nos"

    def test_default_without_env_or_sa_file(self, monkeypatch):
        monkeypatch.delenv("POD_NAMESPACE", raising=False)
        assert _common.current_namespace(default="fallback") == "fallback"


class TestStartHealth:
    def test_single_address_serves_probes_and_metrics(self):
        servers = _common.start_health("127.0.0.1:0")
        try:
            port = servers._health.port
            servers.mark_ready()
            assert _get(f"http://127.0.0.1:{port}/healthz")[0] == 200
            assert _get(f"http://127.0.0.1:{port}/readyz")[0] == 200
            servers.metrics.counter_add("test_metric_total", 1, {})
            body = _get(f"http://127.0.0.1:{port}/metrics")[1]
            assert "test_metric_total" in body
        finally:
            servers.stop()

    def test_split_metrics_address(self):
        # The rbac-proxy topology: probes on one port, /metrics on its own
        # (proxied) port; the probe port must NOT expose metrics.
        # Port 0 twice would compare equal as strings; the split is keyed
        # on the *address string* differing, as it does in real deploys.
        servers = _common.start_health("127.0.0.1:0", "localhost:0")
        try:
            probe_port = servers._health.port
            metrics_port = servers._metrics_server.port
            assert probe_port != metrics_port
            servers.metrics.counter_add("split_metric_total", 1, {})
            body = _get(f"http://127.0.0.1:{metrics_port}/metrics")[1]
            assert "split_metric_total" in body
            try:
                status, _ = _get(f"http://127.0.0.1:{probe_port}/metrics")
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 404
        finally:
            servers.stop()


class TestMetricsRender:
    def test_label_values_escaped(self):
        from walkai_nos_tpu.health import Metrics

        m = Metrics()
        m.counter_add("x_total", 1, {"result": 'bad "quote"\nline'})
        out = m.render()
        # One bad label value must not corrupt the whole exposition.
        assert 'result="bad \\"quote\\"\\nline"' in out


class TestWaitForShutdown:
    def test_sigterm_sets_latch(self):
        old_term = signal.getsignal(signal.SIGTERM)
        old_int = signal.getsignal(signal.SIGINT)
        try:
            stop = _common.wait_for_shutdown()
            assert not stop.is_set()
            os.kill(os.getpid(), signal.SIGTERM)
            assert stop.wait(timeout=5)
        finally:
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)

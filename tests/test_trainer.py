"""Input pipeline and training loop: batching, prefetch, fit, resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.data import prefetch_to_device, token_batches
from walkai_nos_tpu.models.lm import (
    LMConfig,
    init_lm_state,
    make_lm_train_step,
)
from walkai_nos_tpu.models.trainer import fit
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh
from walkai_nos_tpu.parallel.sharding import batch_sharding

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2, max_seq_len=16
)


def _corpus(n=4096, seed=0):
    return np.random.default_rng(seed).integers(
        0, CFG.vocab_size, n, dtype=np.int32
    )


class TestTokenBatches:
    def test_shapes_and_dtype(self):
        it = token_batches(
            _corpus(), batch_size=4, seq_len=16, epochs=1
        )
        batches = list(it)
        assert batches, "no batches yielded"
        for b in batches:
            assert b.shape == (4, 16) and b.dtype == np.int32

    def test_deterministic_in_seed(self):
        a = list(token_batches(
            _corpus(), batch_size=4, seq_len=16, seed=3, epochs=1
        ))
        b = list(token_batches(
            _corpus(), batch_size=4, seq_len=16, seed=3, epochs=1
        ))
        assert all(np.array_equal(x, y) for x, y in zip(a, b))

    def test_epoch_reshuffles(self):
        it = token_batches(_corpus(), batch_size=4, seq_len=16, epochs=2)
        per_epoch = (4096 // 16) // 4
        batches = list(it)
        assert len(batches) == 2 * per_epoch
        assert not all(
            np.array_equal(x, y)
            for x, y in zip(batches[:per_epoch], batches[per_epoch:])
        )

    def test_windows_partition_the_corpus(self):
        corpus = np.arange(256, dtype=np.int32)
        batches = list(token_batches(
            corpus, batch_size=2, seq_len=16, shuffle=False, epochs=1
        ))
        seen = np.sort(np.concatenate([b.ravel() for b in batches]))
        assert np.array_equal(seen, corpus)

    def test_too_small_corpus_rejected_eagerly(self):
        # At the call site, not deferred to the first next().
        with pytest.raises(ValueError, match="at least batch_size"):
            token_batches(_corpus(32), batch_size=4, seq_len=16)


class TestPrefetch:
    def test_prefetch_preserves_order_and_shards(self):
        mesh = build_mesh(jax.devices(), axes=MeshAxes(data=8))
        sharding = batch_sharding(mesh)
        host = [
            np.full((8, 4), i, dtype=np.int32) for i in range(5)
        ]
        out = list(prefetch_to_device(iter(host), sharding=sharding))
        assert len(out) == 5
        for i, batch in enumerate(out):
            assert isinstance(batch, jax.Array)
            assert batch.sharding == sharding
            assert int(batch[0, 0]) == i

    def test_bad_size_rejected_eagerly(self):
        with pytest.raises(ValueError, match="size"):
            prefetch_to_device(iter([np.zeros(2)]), size=0)


class TestFit:
    def _pipeline(self, mesh, epochs=None):
        return prefetch_to_device(
            token_batches(
                _corpus(), batch_size=8, seq_len=CFG.max_seq_len,
                epochs=epochs,
            ),
            sharding=batch_sharding(mesh),
        )

    def test_loss_decreases(self):
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        result = fit(
            state, make_lm_train_step(CFG, mesh), self._pipeline(mesh),
            num_steps=12, log_every=4,
        )
        assert result.steps_run == 12
        assert int(result.state.step) == 12
        assert result.losses[-1] < result.losses[0]

    def test_exhausted_iterator_stops_early(self):
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        result = fit(
            state, make_lm_train_step(CFG, mesh),
            self._pipeline(mesh, epochs=1), num_steps=10_000,
        )
        assert 0 < result.steps_run < 10_000

    def test_final_save_on_interval_boundary(self, tmp_path):
        """num_steps a multiple of checkpoint_every: the interval save
        already wrote the final step — the forced final save must not
        crash with orbax StepAlreadyExists."""
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        result = fit(
            state, make_lm_train_step(CFG, mesh), self._pipeline(mesh),
            num_steps=4, checkpoint_dir=str(tmp_path), checkpoint_every=2,
        )
        assert int(result.state.step) == 4

        # Resumed run that makes zero steps: same guard applies.
        fresh = init_lm_state(CFG, mesh, jax.random.PRNGKey(1))
        second = fit(
            fresh, make_lm_train_step(CFG, mesh), iter(()),
            num_steps=5, checkpoint_dir=str(tmp_path),
        )
        assert second.resumed_from == 4 and second.steps_run == 0

    def test_checkpoint_resume_continues_counting(self, tmp_path):
        mesh = build_mesh(jax.devices())
        step_fn = make_lm_train_step(CFG, mesh)
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        first = fit(
            state, step_fn, self._pipeline(mesh),
            num_steps=5, checkpoint_dir=str(tmp_path), checkpoint_every=2,
        )
        assert int(first.state.step) == 5

        fresh = init_lm_state(CFG, mesh, jax.random.PRNGKey(1))
        second = fit(
            fresh, step_fn, self._pipeline(mesh),
            num_steps=3, checkpoint_dir=str(tmp_path),
        )
        assert second.resumed_from == 5
        assert int(second.state.step) == 8
        assert second.steps_run == 3


class TestProfiling:
    def test_profile_window_produces_a_trace(self, tmp_path):
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        result = fit(
            state, make_lm_train_step(CFG, mesh), self._pipeline(mesh),
            num_steps=8, profile_dir=str(tmp_path / "trace"),
            profile_steps=(2, 4),
        )
        assert result.steps_run == 8
        produced = list((tmp_path / "trace").rglob("*"))
        assert any(p.is_file() for p in produced), produced

    _pipeline = TestFit._pipeline

    def test_degenerate_window_rejected(self, tmp_path):
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="stop > start"):
            fit(
                state, make_lm_train_step(CFG, mesh),
                self._pipeline(mesh), num_steps=4,
                profile_dir=str(tmp_path), profile_steps=(3, 3),
            )

    def test_window_past_end_still_closes(self, tmp_path):
        """Stop ordinal beyond the run: the finally block fences and
        closes the trace instead of leaving the profiler dangling."""
        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        result = fit(
            state, make_lm_train_step(CFG, mesh), self._pipeline(mesh),
            num_steps=3, profile_dir=str(tmp_path / "t"),
            profile_steps=(1, 99),
        )
        assert result.steps_run == 3
        assert any(p.is_file() for p in (tmp_path / "t").rglob("*"))


class TestEvaluate:
    def test_mean_loss_over_batches(self):
        from walkai_nos_tpu.models.lm import DecoderLM, lm_loss
        from walkai_nos_tpu.models.trainer import evaluate

        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        model = DecoderLM(CFG, mesh)

        @jax.jit
        def loss_fn(params, tokens):
            return lm_loss(model.apply({"params": params}, tokens), tokens)

        pipeline = TestFit._pipeline(None, mesh, epochs=1)
        loss = evaluate(state, loss_fn, pipeline, max_batches=4)
        assert 0.0 < loss < 20.0

    def test_empty_iterator_rejected(self):
        from walkai_nos_tpu.models.trainer import evaluate

        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="no batches"):
            evaluate(state, lambda p, b: jnp.zeros(()), iter(()))


class TestOptimizerKnobs:
    def test_clip_and_schedule_train(self):
        from walkai_nos_tpu.models.lm import DecoderLM, lm_loss
        from walkai_nos_tpu.models.train import (
            TrainState,
            make_optimizer,
        )
        import optax

        mesh = build_mesh(jax.devices())
        model = DecoderLM(CFG, mesh)
        tx = make_optimizer(
            1e-3, clip_norm=1.0, warmup_steps=2, decay_steps=10
        )
        params = model.init_params(jax.random.PRNGKey(0))
        state = TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, CFG.vocab_size, (4, 16))
        )

        @jax.jit
        def step(state, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(model.apply({"params": p}, tokens), tokens)
            )(state.params)
            updates, opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            return TrainState(
                optax.apply_updates(state.params, updates),
                opt_state, state.step + 1,
            ), loss

        losses = []
        for _ in range(6):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_warmup_alone_holds_peak_rate(self):
        """warmup_steps without decay_steps must ramp to the peak and
        HOLD it — a zero-length cosine tail would silently freeze the
        rate at 0 one step past warmup."""
        import optax

        from walkai_nos_tpu.models.train import make_optimizer

        tx = make_optimizer(1e-3, warmup_steps=5)
        params = {"w": jnp.ones((3,))}
        state = tx.state = tx.init(params)
        grads = {"w": jnp.ones((3,))}
        for _ in range(8):
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        # Step 8 is past warmup: updates must still be nonzero.
        assert float(jnp.max(jnp.abs(updates["w"]))) > 0.0


class TestEvalDuringFit:
    def test_eval_fn_runs_on_interval(self):
        from walkai_nos_tpu.models.lm import DecoderLM, lm_loss
        from walkai_nos_tpu.models.trainer import evaluate

        mesh = build_mesh(jax.devices())
        state = init_lm_state(CFG, mesh, jax.random.PRNGKey(0))
        model = DecoderLM(CFG, mesh)

        @jax.jit
        def loss_fn(params, tokens):
            return lm_loss(model.apply({"params": params}, tokens), tokens)

        def eval_fn(state):
            val = TestFit._pipeline(None, mesh, epochs=1)
            return evaluate(state, loss_fn, val, max_batches=2)

        result = fit(
            state, make_lm_train_step(CFG, mesh),
            TestFit._pipeline(None, mesh),
            num_steps=6, eval_fn=eval_fn, eval_every=3, log_every=0,
        )
        assert [step for step, _ in result.eval_losses] == [3, 6]
        assert all(v > 0 for _, v in result.eval_losses)

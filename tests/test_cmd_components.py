"""Config loading, health/metrics server, leader election, clusterinfo
collector, sharing client, metricsexporter payload."""

import json
import urllib.request
from datetime import datetime, timezone

import pytest

from walkai_nos_tpu import config as configlib
from walkai_nos_tpu.clusterinfo import Collector
from walkai_nos_tpu.cmd.metricsexporter import build_metrics
from walkai_nos_tpu.health import HealthServer
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.leader import LeaderElector
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus
from walkai_nos_tpu.tpu.sharing.client import SharingClient


class TestConfig:
    def test_partitioner_config_roundtrip(self, tmp_path):
        path = tmp_path / "cfg.yaml"
        path.write_text(
            """
apiVersion: config.nos.walkai.io/v1alpha1
kind: TpuPartitionerConfig
health:
  healthProbeBindAddress: ":9001"
leaderElection:
  leaderElect: true
  resourceName: part-leader
devicePluginDelaySeconds: 2
podRetryIntervalSeconds: 3
"""
        )
        cfg = configlib.load_config(path, "TpuPartitionerConfig")
        assert cfg.manager.health_probe_addr == ":9001"
        assert cfg.manager.leader_elect is True
        assert cfg.manager.leader_election_id == "part-leader"
        assert cfg.device_plugin_delay_s == 2.0
        assert cfg.pod_retry_interval_s == 3.0

    def test_wrong_kind_rejected(self, tmp_path):
        path = tmp_path / "cfg.yaml"
        path.write_text("kind: SomethingElse\n")
        with pytest.raises(ValueError, match="expected kind"):
            configlib.load_config(path, "TpuAgentConfig")

    def test_agent_config_validates_interval(self, tmp_path):
        path = tmp_path / "cfg.yaml"
        path.write_text(
            "kind: TpuAgentConfig\nreportConfigIntervalSeconds: 0\n"
        )
        with pytest.raises(ValueError, match="report_interval_s"):
            configlib.load_config(path, "TpuAgentConfig")

    def test_known_geometries_file(self, tmp_path):
        path = tmp_path / "geom.yaml"
        path.write_text(
            """
- models: [tpu-v5-lite-podslice]
  allowedGeometries:
    - "2x4": 1
    - "2x2": 2
"""
        )
        from walkai_nos_tpu.tpu import topology
        from walkai_nos_tpu.tpu.tiling import known_tilings

        table = configlib.load_known_geometries_file(path)
        assert "tpu-v5-lite-podslice" in table
        model = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]
        geoms = known_tilings.get_allowed_geometries(model)
        assert len(geoms) == 2


class TestHealthServer:
    def test_probes_and_metrics(self):
        server = HealthServer("127.0.0.1:0")
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            assert urllib.request.urlopen(f"{base}/healthz").status == 200
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/readyz")
            assert e.value.code == 503
            server.mark_ready()
            assert urllib.request.urlopen(f"{base}/readyz").status == 200
            server.metrics.counter_add(
                "nos_reconcile_total", 2, {"controller": "partitioner"}
            )
            server.metrics.gauge_set("nos_free_slices", 3)
            body = urllib.request.urlopen(f"{base}/metrics").read().decode()
            # Integral values render bare (the unified obs registry's
            # Go-client-style formatting; "2.0" was the old adapter's).
            assert 'nos_reconcile_total{controller="partitioner"} 2' in body
            assert "nos_free_slices 3" in body
        finally:
            server.stop()


class TestLeaderElection:
    def test_single_leader_and_failover(self):
        kube = FakeKubeClient()
        a = LeaderElector(
            kube, "test-lease", identity="a",
            lease_duration=0.4, renew_interval=0.05,
        )
        b = LeaderElector(
            kube, "test-lease", identity="b",
            lease_duration=0.4, renew_interval=0.05,
        )
        a.start()
        assert a.wait_for_leadership(2.0)
        b.start()
        assert not b.wait_for_leadership(0.3)  # a holds the lease
        a.stop()
        assert b.wait_for_leadership(3.0)  # lease expires, b takes over
        b.stop()

    def test_renew_time_without_fractional_seconds_respected(self):
        """A renewTime serialized without '.%f' (another client's lease)
        must not parse as 'expired' and get stolen."""
        kube = FakeKubeClient()
        kube.create(
            "Lease",
            {
                "metadata": {"name": "foreign-lease", "namespace": "walkai-nos"},
                "spec": {
                    "holderIdentity": "someone-else",
                    "leaseDurationSeconds": 3600,
                    "renewTime": datetime.now(timezone.utc).strftime(
                        "%Y-%m-%dT%H:%M:%SZ"
                    ),
                },
            },
            "walkai-nos",
        )
        thief = LeaderElector(
            kube, "foreign-lease", identity="thief",
            lease_duration=0.4, renew_interval=0.05,
        )
        assert thief._try_acquire_or_renew() is False


def _node(name, accelerator="tpu-v5-lite-podslice", annotations=None,
          capacity=None):
    return {
        "metadata": {
            "name": name,
            "labels": {
                "cloud.google.com/gke-tpu-accelerator": accelerator,
                "cloud.google.com/gke-tpu-topology": "2x4",
            },
            "annotations": annotations or {},
        },
        "status": {"capacity": capacity or {}},
    }


class TestClusterInfoCollector:
    def test_annotations_path(self):
        kube = FakeKubeClient()
        kube.create(
            "Node",
            _node(
                "n1",
                annotations={
                    "nos.walkai.io/status-tpu-0-2x2-used": "1",
                    "nos.walkai.io/status-tpu-0-2x2-free": "1",
                    "nos.walkai.io/status-tpu-0-1x1-free": "4",
                },
            ),
        )
        snap = Collector(kube).collect()
        by_name = {t.tpu: t for t in snap.tpus}
        assert by_name["n1: tpu-v5-lite-podslice 2x2"].allocated == 1
        assert by_name["n1: tpu-v5-lite-podslice 2x2"].available == 1
        assert by_name["n1: tpu-v5-lite-podslice 1x1"].available == 4

    def test_capacity_fallback_path(self):
        """Unmanaged node: capacity minus pod requests
        (`collector_test.go:33-133` capacity-fallback case)."""
        kube = FakeKubeClient()
        kube.create(
            "Node", _node("n2", capacity={"walkai.io/tpu-2x2": "2"})
        )
        kube.create(
            "Pod",
            {
                "metadata": {"name": "p1", "namespace": "default"},
                "spec": {
                    "nodeName": "n2",
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"walkai.io/tpu-2x2": "1"}
                            },
                        }
                    ],
                },
                "status": {"phase": "Running"},
            },
        )
        snap = Collector(kube).collect()
        inv = next(t for t in snap.tpus if "2x2" in t.tpu)
        assert inv.allocated == 1 and inv.available == 1

    def test_multi_host_pool_reported_whole(self):
        """A multi-host pool is never partitioned but its capacity must not
        vanish from the inventory: it is reported as one whole slice."""
        kube = FakeKubeClient()
        node = _node("mh1", accelerator="tpu-v5p-slice",
                     capacity={"google.com/tpu": "4"})
        node["metadata"]["labels"]["cloud.google.com/gke-tpu-topology"] = "2x2x2"
        kube.create("Node", node)
        kube.create(
            "Pod",
            {
                "metadata": {"name": "whole", "namespace": "default"},
                "spec": {
                    "nodeName": "mh1",
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"google.com/tpu": "4"}
                            },
                        }
                    ],
                },
                "status": {"phase": "Running"},
            },
        )
        snap = Collector(kube).collect()
        inv = next(t for t in snap.tpus if t.tpu.startswith("mh1"))
        # Units are CHIPS of this host (one host of the pool), and the
        # label says so — capacity 4 is 4 chips, not 4 pools.
        assert "2x2x2-pool chips" in inv.tpu
        assert inv.allocated == 4 and inv.available == 0

    def test_managed_pool_member_reports_from_annotations(self):
        """A pool member MANAGED by pool-level partitioning carries
        status annotations (its pool share or host-local slices); those
        are the inventory truth, not the whole-host capacity fallback."""
        kube = FakeKubeClient()
        node = _node(
            "mh3", accelerator="tpu-v5p-slice",
            capacity={"google.com/tpu": "4"},
            annotations={
                "nos.walkai.io/status-tpu-0-2x2x2-used": "1"
            },
        )
        node["metadata"]["labels"]["cloud.google.com/gke-tpu-topology"] = "2x2x2"
        kube.create("Node", node)
        snap = Collector(kube).collect()
        inv = next(t for t in snap.tpus if t.tpu.startswith("mh3"))
        assert "2x2x2" in inv.tpu
        assert inv.allocated == 1 and inv.available == 0

    def test_idle_multi_host_pool_reports_chip_units(self):
        kube = FakeKubeClient()
        node = _node("mh2", accelerator="tpu-v5p-slice",
                     capacity={"google.com/tpu": "4"})
        node["metadata"]["labels"]["cloud.google.com/gke-tpu-topology"] = "2x2x2"
        kube.create("Node", node)
        snap = Collector(kube).collect()
        inv = next(t for t in snap.tpus if t.tpu.startswith("mh2"))
        assert inv.allocated == 0 and inv.available == 4  # 4 chips, not pools

    def test_pod_summaries(self):
        kube = FakeKubeClient()
        kube.create(
            "Pod",
            {
                "metadata": {"name": "train", "namespace": "ml"},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"walkai.io/tpu-2x2": "2"}
                            },
                        }
                    ]
                },
                "status": {
                    "phase": "Failed",
                    "startTime": "2026-07-29T10:00:00Z",
                    "containerStatuses": [
                        {
                            "state": {
                                "terminated": {
                                    "reason": "OOMKilled",
                                    "finishedAt": "2026-07-29T11:00:00Z",
                                }
                            }
                        }
                    ],
                },
            },
        )
        snap = Collector(kube).collect()
        assert len(snap.pods) == 1
        p = snap.pods[0]
        assert p.status == "OOMKilled"
        assert p.tpu == "2x2 x2"
        assert p.start_time == "2026-07-29T10:00:00Z"
        assert p.finish_time == "2026-07-29T11:00:00Z"

    def test_snapshot_is_json_serializable(self):
        kube = FakeKubeClient()
        kube.create(
            "Node",
            _node(
                "n1",
                annotations={"nos.walkai.io/status-tpu-0-2x4-free": "1"},
            ),
        )
        json.dumps(Collector(kube).collect().to_dict())


class TestSharingClient:
    def test_replica_suffix_identity(self):
        resources = FakeResourceClient()
        resources.set_allocatable(
            [
                Device("walkai.io/tpu-shared-2c", "shared-0::0", DeviceStatus.UNKNOWN),
                Device("walkai.io/tpu-shared-2c", "shared-0::1", DeviceStatus.UNKNOWN),
                Device("walkai.io/tpu-shared-2c", "shared-1::0", DeviceStatus.UNKNOWN),
            ]
        )
        resources.mark_used("shared-0::0")
        devices = SharingClient(resources).get_tpu_devices()
        used = [d.device_id for d in devices.get_used()]
        free = [d.device_id for d in devices.get_free()]
        assert used == ["shared-0::0"]
        # shared-0::1 is a replica of a used device -> not free
        assert free == ["shared-1::0"]


class TestMetricsExporter:
    def test_build_metrics_enriches_nodes(self):
        kube = FakeKubeClient()
        kube.create("Node", _node("n1", capacity={"google.com/tpu": "8"}))
        m = build_metrics(
            {"installationUUID": "u1", "chartValues": {"a": 1}}, kube
        )
        assert m["installation_uuid"] == "u1"
        assert m["nodes"][0]["name"] == "n1"
        assert m["nodes"][0]["capacity"] == {"google.com/tpu": "8"}


def test_pyproject_console_scripts_resolve():
    """Every [project.scripts] entry must point at an importable
    callable — packaging metadata can silently rot otherwise."""
    import importlib
    from pathlib import Path

    tomllib = pytest.importorskip(
        "tomllib", reason="stdlib tomllib needs Python >= 3.11"
    )

    pyproject = Path(__file__).resolve().parents[1] / "pyproject.toml"
    scripts = tomllib.loads(pyproject.read_text())["project"]["scripts"]
    assert len(scripts) == 6
    for name, target in scripts.items():
        module, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(module), attr)
        assert callable(fn), (name, target)

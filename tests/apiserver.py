"""In-process Kubernetes API server over real HTTP — the envtest analogue.

The reference's integration suites boot a real kube-apiserver+etcd via
envtest (`internal/controllers/migagent/suite_int_test.go:33-163`); those
binaries aren't shippable here, so this stdlib HTTP server emulates the
REST surface the controllers use — CRUD, JSON merge patch (+/status
subresource), pods/binding, label/field selectors, resourceVersion
conflicts, and streaming watch with per-collection filtering — so the
REAL `RestKubeClient` wire path (watch framing, cluster-wide collection
routes, merge-patch semantics) is what e2e tests exercise.

Supported route shapes:
  /api/v1/<plural>[...]                          core kinds
  /apis/<group>/<version>/<plural>[...]          CRDs, coordination.k8s.io
  .../namespaces/<ns>/<plural>/<name>[/status|/binding|/eviction]
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

# The PRODUCT's RFC 7386 implementation (`kube/objects.py`) — the test
# server must agree with the client on patch semantics, not re-derive them.
from walkai_nos_tpu.kube.objects import merge_patch


def _matches_labels(obj: dict, sel: dict) -> bool:
    labels = (obj.get("metadata") or {}).get("labels") or {}
    return all(labels.get(k) == v for k, v in sel.items())


def _get_path(obj: dict, dotted: str):
    cur = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


class MiniApiServer:
    """Thread-safe in-memory object store behind a real HTTP listener."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rv = 0
        # (plural, ns, name) -> obj;  ns == "" for cluster-scoped use
        self._objects: dict[tuple, dict] = {}
        self._events: list[tuple[int, str, str, str, dict]] = []
        self._httpd = None
        self._thread = None

    # ------------------------------------------------------------------ state

    def _bump(self, plural: str, ns: str, etype: str, obj: dict) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        self._events.append(
            (self._rv, plural, ns, etype, json.loads(json.dumps(obj)))
        )
        self._cond.notify_all()

    # ---------------------------------------------------------------- serving

    def start(self) -> str:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _parse(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                if parts[0] == "api":
                    rest = parts[2:]  # ["api","v1",...]
                elif parts[0] == "apis":
                    rest = parts[3:]  # ["apis",group,version,...]
                else:
                    raise ValueError(self.path)
                ns = ""
                if rest and rest[0] == "namespaces" and len(rest) > 2:
                    ns = rest[1]
                    rest = rest[2:]
                plural = rest[0]
                name = rest[1] if len(rest) > 1 else None
                sub = rest[2] if len(rest) > 2 else None
                return plural, ns, name, sub, parse_qs(u.query)

            def _send(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def _find(self, plural, ns, name):
                """Single-object lookup; tolerates a namespace-less path
                for namespaced objects (cluster-scoped kinds store ns='')."""
                obj = outer._objects.get((plural, ns, name))
                if obj is None and not ns:
                    for (p, _ns, n), o in outer._objects.items():
                        if p == plural and n == name:
                            return (p, _ns, n), o
                    return None, None
                return ((plural, ns, name), obj) if obj else (None, None)

            def do_GET(self):
                plural, ns, name, _sub, query = self._parse()
                if not name and query.get("watch"):
                    rv = int(query.get("resourceVersion", ["0"])[0])
                    self._watch(plural, ns, rv)
                    return
                with outer._lock:
                    if name:
                        _key, obj = self._find(plural, ns, name)
                        if obj is None:
                            self._send(404, {"message": "not found"})
                        else:
                            self._send(200, obj)
                        return
                    sel = {}
                    for pair in query.get("labelSelector", [""])[0].split(","):
                        if "=" in pair:
                            k, v = pair.split("=", 1)
                            sel[k] = v
                    fields = {}
                    for pair in query.get("fieldSelector", [""])[0].split(","):
                        if "=" in pair:
                            k, v = pair.split("=", 1)
                            fields[k] = v
                    items = [
                        o
                        for (p, n2, _), o in sorted(outer._objects.items())
                        if p == plural
                        and (not ns or n2 == ns)
                        and _matches_labels(o, sel)
                        and all(
                            str(_get_path(o, k) or "") == v
                            for k, v in fields.items()
                        )
                    ]
                    self._send(
                        200,
                        {
                            "items": items,
                            "metadata": {"resourceVersion": str(outer._rv)},
                        },
                    )

            def _watch(self, plural, ns, rv):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                deadline = time.monotonic() + 5.0
                sent = rv
                while time.monotonic() < deadline:
                    with outer._cond:
                        events = [
                            (v, t, o)
                            for v, p, ens, t, o in outer._events
                            if v > sent
                            and p == plural
                            and (not ns or ens == ns)
                        ]
                        if not events:
                            last = outer._events[-1][0] if outer._events else sent
                            sent = max(sent, last)
                            outer._cond.wait(0.05)
                            continue
                    for v, etype, obj in events:
                        line = (
                            json.dumps({"type": etype, "object": obj}) + "\n"
                        ).encode()
                        try:
                            self.wfile.write(
                                f"{len(line):x}\r\n".encode() + line + b"\r\n"
                            )
                            self.wfile.flush()
                        except (BrokenPipeError, ConnectionResetError):
                            return
                        sent = v
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                plural, ns, name, sub, _ = self._parse()
                body = self._read_body()
                with outer._lock:
                    if sub == "binding":
                        key, obj = self._find(plural, ns, name)
                        if obj is None:
                            self._send(404, {"message": "not found"})
                            return
                        node = ((body.get("target") or {}).get("name")) or ""
                        obj.setdefault("spec", {})["nodeName"] = node
                        conds = obj.setdefault("status", {}).setdefault(
                            "conditions", []
                        )
                        conds[:] = [
                            c for c in conds if c.get("type") != "PodScheduled"
                        ]
                        conds.append(
                            {"type": "PodScheduled", "status": "True"}
                        )
                        outer._bump(plural, key[1], "MODIFIED", obj)
                        self._send(201, {})
                        return
                    if sub == "eviction":
                        # pods/eviction: PDB-enforced graceful delete —
                        # 429 when the budget is spent, like the real
                        # subresource handler (kube/disruption.py).
                        from walkai_nos_tpu.kube.disruption import (
                            eviction_allowed,
                        )

                        key, obj = self._find(plural, ns, name)
                        if obj is None:
                            self._send(404, {"message": "not found"})
                            return
                        pdbs = [
                            o
                            for (p, ens, _), o in outer._objects.items()
                            if p == "poddisruptionbudgets" and ens == ns
                        ]
                        pods = [
                            o
                            for (p, ens, _), o in outer._objects.items()
                            if p == "pods" and ens == ns
                        ]
                        allowed, reason = eviction_allowed(obj, pdbs, pods)
                        if not allowed:
                            self._send(429, {"message": reason})
                            return
                        del outer._objects[key]
                        outer._bump(plural, key[1], "DELETED", obj)
                        self._send(201, {})
                        return
                    name = body["metadata"]["name"]
                    ns = ns or body["metadata"].get("namespace", "")
                    key = (plural, ns, name)
                    if key in outer._objects:
                        self._send(409, {"message": "exists"})
                        return
                    outer._objects[key] = body
                    outer._bump(plural, ns, "ADDED", body)
                    self._send(201, body)

            def do_PATCH(self):
                plural, ns, name, sub, _ = self._parse()
                patch = self._read_body()
                with outer._lock:
                    key, obj = self._find(plural, ns, name)
                    if obj is None:
                        self._send(404, {"message": "not found"})
                        return
                    if sub == "status":
                        # The /status subresource only touches status —
                        # real API servers drop everything else.
                        patch = {"status": patch.get("status") or {}}
                    elif "status" in patch:
                        # ...and a main-resource write silently drops
                        # status changes (kube/client.py documents this
                        # exact trap; the fake must reproduce it).
                        patch = {
                            k: v for k, v in patch.items() if k != "status"
                        }
                    if (
                        plural == "pods"
                        and sub is None
                        and (patch.get("spec") or {}).get("nodeName")
                        and (obj.get("spec") or {}).get("nodeName")
                        != patch["spec"]["nodeName"]
                    ):
                        # spec.nodeName is immutable; schedulers must use
                        # the pods/binding subresource.
                        self._send(
                            422, {"message": "spec.nodeName is immutable"}
                        )
                        return
                    obj = merge_patch(obj, patch)
                    outer._objects[key] = obj
                    outer._bump(plural, key[1], "MODIFIED", obj)
                    self._send(200, obj)

            def do_PUT(self):
                plural, ns, name, _sub, _ = self._parse()
                body = self._read_body()
                with outer._lock:
                    key, obj = self._find(plural, ns, name)
                    if obj is not None:
                        stale = (body.get("metadata") or {}).get(
                            "resourceVersion"
                        )
                        current = (obj.get("metadata") or {}).get(
                            "resourceVersion"
                        )
                        if stale is not None and stale != current:
                            self._send(409, {"message": "conflict"})
                            return
                    key = key or (plural, ns, name)
                    outer._objects[key] = body
                    outer._bump(
                        plural, key[1],
                        "MODIFIED" if obj is not None else "ADDED", body,
                    )
                    self._send(200, body)

            def do_DELETE(self):
                plural, ns, name, _sub, _ = self._parse()
                with outer._lock:
                    key, obj = self._find(plural, ns, name)
                    if obj is None:
                        self._send(404, {"message": "not found"})
                        return
                    outer._objects.pop(key, None)
                    outer._bump(plural, key[1], "DELETED", obj)
                    self._send(200, {})

            def log_message(self, *a):
                pass

        class Server(ThreadingHTTPServer):
            request_queue_size = 64
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

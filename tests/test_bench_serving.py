"""End-to-end smoke of `bench.py`'s serving phase on the tiny CPU model.

The headline bench is the round artifact; a crash in `serving_benchmark`
records 0% utilization for the round, so the whole phase — pipelined
throughput window, sequential latency probe, interleaved fair/noisy QoS
segments, and the stats math over all of them — must execute in CI, not
only on the real chip. (A variable-shadowing bug in the QoS pooling loop
once broke the throughput-sample unpack only at the very end of the
phase; this test exists so that class of failure fails in CI first.)
"""

import importlib

import pytest


@pytest.fixture()
def bench_mod(monkeypatch):
    # Bench knobs are read at import time; set them, then (re)load.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("WALKAI_DEMO_MODEL", "tiny")
    monkeypatch.setenv("WALKAI_CALIB_WINDOW_S", "0.2")
    monkeypatch.setenv("WALKAI_BENCH_WARMUP_S", "1")
    monkeypatch.setenv("WALKAI_BENCH_SECONDS", "2")
    monkeypatch.setenv("WALKAI_BENCH_PROBE_SECONDS", "1")
    monkeypatch.setenv("WALKAI_BENCH_QOS_SECONDS", "3")
    monkeypatch.setenv("WALKAI_BENCH_QOS_REPEATS", "3")
    monkeypatch.setenv("WALKAI_BENCH_SWEEP_SECONDS", "0.5")
    monkeypatch.setenv("WALKAI_BENCH_PIPELINE", "2")
    monkeypatch.setenv("WALKAI_BENCH_REQUEST_BATCH", "4")
    monkeypatch.setenv("WALKAI_BENCH_MAX_BATCH", "8")
    monkeypatch.setenv("WALKAI_BENCH_WINDOW_MS", "5.0")
    import bench

    bench = importlib.reload(bench)
    yield bench
    # Leave a clean module for any later importer. monkeypatch's own
    # teardown runs AFTER this fixture's (reverse setup order), so undo
    # the env explicitly first — reloading before the undo would re-bake
    # the tiny test knobs into the module for the rest of the session.
    monkeypatch.undo()
    importlib.reload(bench)


def test_cb_serving_benchmark_runs_end_to_end(monkeypatch):
    """The round-5 CB serving phase (Poisson arrivals over HTTP
    /generate, TTFT/goodput/occupancy math) must execute in CI on the
    tiny CPU models — a crash here would erase the whole cb block from
    the round artifact."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from bench_lm import measure_cb_serving

    r = measure_cb_serving(
        slots=2, lm_max_new=8, prompt_bucket=8, vocab=64,
        capacity_seconds=1.0, measure_seconds=3.0, load_fraction=0.5,
        server_env={
            "WALKAI_LM_MODEL": "tiny",
            "WALKAI_CALIB_WINDOW_S": "0.2",
        },
        startup_timeout_s=300.0,
    )
    assert r["cb_requests_completed"] > 0
    assert r["cb_request_errors"] == 0
    assert r["cb_ttft_p50"] > 0
    assert r["cb_goodput_tokens_per_s"] > 0
    assert r["cb_slot_occupancy"] is not None
    assert r["cb_serving_request_p90_s"] >= r["cb_serving_request_p50_s"]
    # The paged-pool rework's first-class fields: admission stall per
    # measured second and KV HBM bytes per resident token — both must
    # be emitted (and the engine must be running the paged pool).
    assert r["cb_admission_stall_ms"] >= 0
    assert r["cb_kv_hbm_bytes_per_resident_token"] > 0
    assert r["cb_kv_paged"] is True
    # Observability acceptance: the TTFT p99 read from the server's
    # /metrics histogram (bucket delta over the window) agrees with
    # the record-derived p99 within one log-bucket width.
    from walkai_nos_tpu.obs.catalog import CATALOG

    bounds = next(
        s.buckets for s in CATALOG if s.name == "cb_ttft_seconds"
    )
    got = r["cb_ttft_p99_from_metrics"]
    assert got is not None
    expect_idx = next(
        (i for i, b in enumerate(bounds) if b >= r["cb_ttft_p99"]),
        len(bounds) - 1,
    )
    assert got in bounds
    assert abs(bounds.index(got) - expect_idx) <= 1, (
        got, r["cb_ttft_p99"]
    )
    # And they are headline keys in bench.py's emitted line (they
    # must survive driver-side tail truncation).
    import inspect

    import bench

    src = inspect.getsource(bench.main)
    assert "cb_admission_stall_ms" in src
    assert "cb_kv_hbm_bytes_per_resident_token" in src
    assert "cb_serving_capacity_tokens_per_s" in src


def test_cb_prefix_reuse_benchmark_runs_end_to_end(monkeypatch):
    """The templated-prefix serving workload
    (`bench_lm.measure_cb_prefix_reuse`) must execute on the tiny CPU
    model and emit its two headline keys with the deterministic
    cold/warm split: 2 templates fill cold (1 shareable block each),
    the remaining 6 requests hit — hit rate exactly 6/8."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from bench_lm import measure_cb_prefix_reuse

    r = measure_cb_prefix_reuse(
        n_requests=8, n_templates=2, prefix_tokens=160, suffix_max=8,
        max_new=8, slots=2, vocab=64, concurrency=2,
        server_env={
            "WALKAI_LM_MODEL": "tiny",
            "WALKAI_LM_SEQ": "512",
            "WALKAI_CALIB_WINDOW_S": "0.2",
        },
        startup_timeout_s=300.0,
    )
    assert r["cb_prefix_cache_enabled"] is True
    assert r["cb_prefix_request_errors"] == 0
    assert r["cb_prefix_hit_rate"] == 0.75
    assert r["cb_prefill_tokens_saved_frac"] > 0.4
    assert r["cb_prefix_evictions"] == 0
    # Both keys are headline keys in bench.py's emitted line.
    import inspect

    import bench

    src = inspect.getsource(bench.main)
    assert "cb_prefix_hit_rate" in src
    assert "cb_prefill_tokens_saved_frac" in src


def test_decode_bench_emits_roofline_fields(monkeypatch):
    """The decode phase's new first-class fields — the roofline
    attainment of the measured attention chain and the dispatch
    amortization operating point — must be emitted by
    `measure_decode`, not derived by hand from the step breakdown.
    Runs the tiny CPU model with a stubbed HBM bandwidth (the CPU
    device kind has none published); the VALUES are meaningless here —
    the field contract is what CI pins."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import bench_lm
    from walkai_nos_tpu.models.lm import LM_TINY

    monkeypatch.setattr(
        "walkai_nos_tpu.utils.flops.hbm_bytes_per_s", lambda kind: 1e12
    )
    r = bench_lm.measure_decode(
        cfg=LM_TINY, batch=2, prompt_len=4, new_tokens=8,
        pipeline=1, compare_batch=None, tokens_per_dispatch=4,
    )
    assert r["decode_tokens_per_dispatch"] == 4
    assert 0 < r["decode_gqa_roofline_fraction"] <= 1.0
    bd = r["decode_gqa_step_breakdown"]
    assert set(bd) >= {
        "attention_ms", "non_attention_ms", "host_dispatch_ms",
        "attention_hbm_ideal_ms", "device_step_ms",
    }
    # The fraction is the breakdown's own ratio, rounded.
    assert r["decode_gqa_roofline_fraction"] == pytest.approx(
        bd["attention_hbm_ideal_ms"] / bd["attention_ms"], abs=2e-3
    )
    # And both new fields are headline keys in bench.py's emitted
    # line (they must survive driver-side tail truncation).
    import inspect

    import bench

    src = inspect.getsource(bench.main)
    assert "decode_gqa_roofline_fraction" in src
    assert "decode_tokens_per_dispatch" in src


def test_obs_overhead_measure_runs_end_to_end(monkeypatch):
    """The telemetry-overhead A/B (`bench_lm.measure_obs_overhead`,
    the obs_overhead_pct headline key gated < 2% by bench-check) must
    execute on the tiny CPU model — the VALUES are machine noise here;
    the field contract and the enabled/disabled engine paths are what
    CI pins."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from bench_lm import measure_obs_overhead
    from walkai_nos_tpu.models.lm import LM_TINY

    r = measure_obs_overhead(
        slots=2, n_requests=4, prompt_len=4, new_tokens=6,
        chunk_steps=2, repeats=1, cfg=LM_TINY,
    )
    assert set(r) >= {
        "obs_overhead_pct", "obs_on_tokens_per_s",
        "obs_off_tokens_per_s", "obs_overhead_repeats",
    }
    assert r["obs_on_tokens_per_s"] > 0
    assert r["obs_off_tokens_per_s"] > 0


def test_serving_benchmark_runs_end_to_end(bench_mod):
    r = bench_mod.serving_benchmark()
    # The phase completed: throughput, probe, and QoS sections all
    # produced real numbers (a crash anywhere raises instead).
    assert r["throughput_images_per_s"] > 0
    assert r["latency_mean_request_s"] > 0
    assert r["latency_probe_p50_s"] > 0
    assert r["client_errors"] == 0
    assert len(r["qos_p99_per_stream_s"]) == bench_mod.N_STREAMS
    assert len(r["qos_noisy_victim_p99_s"]) == bench_mod.N_STREAMS - 1
    assert all(p > 0 for p in r["qos_p99_per_stream_s"])
    assert r["noisy_neighbor_degradation_pct"] is not None
    # Powered QoS verdict: per-repeat mean and 95% interval present.
    assert r["noisy_neighbor_repeats"] >= 3
    lo, hi = r["noisy_neighbor_degradation_ci95_pct"]
    assert lo <= r["noisy_neighbor_degradation_mean_pct"] <= hi
    # The claim rides the p95-tail interval: its fields must exist,
    # cohere, and agree with the claim expression.
    lo95, hi95 = r["noisy_neighbor_degradation_p95_ci95_pct"]
    assert lo95 <= r["noisy_neighbor_degradation_p95_mean_pct"] <= hi95
    assert r["noisy_neighbor_no_degradation"] == (
        r["noisy_neighbor_skipped_repeats"] == 0 and hi95 < 10.0
    )
    # Co-tenancy sweep covers the four widths with real samples.
    assert [row["streams"] for row in r["cotenancy_sweep"]] == [1, 2, 4, 8]
    assert all(row["requests"] > 0 for row in r["cotenancy_sweep"])
    # Gap decomposition stays one consistent story.
    assert r["utilization_gap_pct"] == pytest.approx(
        100.0 - r["utilization_pct"], abs=0.02
    )

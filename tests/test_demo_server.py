"""Demo inference server: micro-batching + fence-based completion.

Boots the real server process (tiny model, CPU) and exercises the
product serving path the bench measures: concurrent requests coalesce
into one forward, responses come only after a device fence, and /stats
counts only fenced work (`demos/tpu-sharing-comparison/app/main.py`).
"""

import threading

import pytest

from walkai_nos_tpu.utils.httpbench import (
    get_json,
    kill_server,
    post_infer,
    spawn_server,
)


@pytest.fixture(scope="module")
def server():
    proc, base = spawn_server(
        {
            "JAX_PLATFORMS": "cpu",
            "WALKAI_DEMO_MODEL": "tiny",
            "WALKAI_MAX_BATCH": "8",
            "WALKAI_BATCH_WINDOW_MS": "20",
            "WALKAI_WARM_BUCKETS": "1,8",
            # CPU CI doesn't read the ceiling; don't spend seconds
            # calibrating it (startup raced the fixture timeout under
            # parallel machine load).
            "WALKAI_CALIB_WINDOW_S": "0.2",
        },
        startup_timeout_s=240.0,
        poll_s=0.25,
    )
    yield base
    kill_server(proc)


class TestDemoServer:
    def test_single_request_roundtrip(self, server):
        out = post_infer(server, 1, timeout=60)
        assert out["inference_time_seconds"] > 0
        assert out["batched_with"] >= 1

    def test_concurrent_requests_are_batched(self, server):
        results = []
        lock = threading.Lock()

        def hit():
            r = post_infer(server, 1, timeout=60)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=hit) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 6
        # The 20ms window must have coalesced at least some requests.
        assert max(r["batched_with"] for r in results) > 1

    def test_stats_count_only_fenced_work(self, server):
        s0 = get_json(f"{server}/stats")
        post_infer(server, 4, timeout=60)
        s1 = get_json(f"{server}/stats")
        assert s1["images"] - s0["images"] >= 4
        assert s1["requests"] - s0["requests"] >= 1
        assert s1["flops"] > s0["flops"]
        assert s1["model_ceiling_images_per_s"] > 0
        assert s1["fence_rtt_s"] >= 0
        assert s1["flops_per_image"] > 0

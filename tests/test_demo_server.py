"""Demo inference server: micro-batching + fence-based completion.

Boots the real server process (tiny model, CPU) and exercises the
product serving path the bench measures: concurrent requests coalesce
into one forward, responses come only after a device fence, and /stats
counts only fenced work (`demos/tpu-sharing-comparison/app/main.py`).
"""

import threading

import pytest

from walkai_nos_tpu.utils.httpbench import (
    get_json,
    kill_server,
    post_infer,
    spawn_server,
)


@pytest.fixture(scope="module")
def server():
    proc, base = spawn_server(
        {
            "JAX_PLATFORMS": "cpu",
            "WALKAI_DEMO_MODEL": "tiny",
            "WALKAI_MAX_BATCH": "8",
            "WALKAI_BATCH_WINDOW_MS": "20",
            "WALKAI_WARM_BUCKETS": "1,8",
            # CPU CI doesn't read the ceiling; don't spend seconds
            # calibrating it (startup raced the fixture timeout under
            # parallel machine load).
            "WALKAI_CALIB_WINDOW_S": "0.2",
        },
        startup_timeout_s=240.0,
        poll_s=0.25,
    )
    yield base
    kill_server(proc)


class TestDemoServer:
    def test_single_request_roundtrip(self, server):
        out = post_infer(server, 1, timeout=60)
        assert out["inference_time_seconds"] > 0
        assert out["batched_with"] >= 1

    def test_concurrent_requests_are_batched(self, server):
        results = []
        lock = threading.Lock()

        def hit():
            r = post_infer(server, 1, timeout=60)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=hit) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 6
        # The 20ms window must have coalesced at least some requests.
        assert max(r["batched_with"] for r in results) > 1

    def test_stats_count_only_fenced_work(self, server):
        s0 = get_json(f"{server}/stats")
        post_infer(server, 4, timeout=60)
        s1 = get_json(f"{server}/stats")
        assert s1["images"] - s0["images"] >= 4
        assert s1["requests"] - s0["requests"] >= 1
        assert s1["flops"] > s0["flops"]
        assert s1["model_ceiling_images_per_s"] > 0
        assert s1["fence_rtt_s"] >= 0
        assert s1["flops_per_image"] > 0

    def test_healthz_without_engine(self, server):
        # Vision-only server: readiness payload present, engine null.
        h = get_json(f"{server}/healthz")
        assert h["ok"] is True
        assert h["engine"] is None

    def test_debug_state_without_engine(self, server):
        # The snapshot endpoint exists on every server; engine null
        # when continuous batching is off (same shape as /healthz).
        assert get_json(f"{server}/debug/state") == {"engine": None}
        assert get_json(f"{server}/debug/slo") == {"engine": None}


class TestGenerateEndpoint:
    @pytest.fixture(scope="class")
    def lm_server(self):
        proc, base = spawn_server(
            {
                "JAX_PLATFORMS": "cpu",
                "WALKAI_DEMO_MODEL": "tiny",
                "WALKAI_DEMO_LM": "1",
                "WALKAI_LM_MAX_NEW": "8",
                "WALKAI_MAX_BATCH": "8",
                "WALKAI_WARM_BUCKETS": "1",
                "WALKAI_CALIB_WINDOW_S": "0.2",
            },
            startup_timeout_s=300.0,
            poll_s=0.25,
        )
        yield base
        kill_server(proc)

    def _post(self, base, payload):
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, {}

    def test_generates_tokens(self, lm_server):
        status, out = self._post(lm_server, {"prompt": [1, 2, 3, 4]})
        assert status == 200
        assert len(out["tokens"]) == 8
        assert out["tokens_per_second"] > 0

    def test_bad_prompt_rejected(self, lm_server):
        assert self._post(lm_server, {"prompt": []})[0] == 400
        assert self._post(lm_server, {"prompt": [999999]})[0] == 400
        assert self._post(lm_server, {"prompt": list(range(125))})[0] == 400

    def test_generate_disabled_by_default(self, server):
        status, _ = self._post(server, {"prompt": [1, 2]})
        assert status == 404

    def test_speculative_requires_opt_in(self, lm_server):
        status, _ = self._post(
            lm_server, {"prompt": [1, 2], "speculative": True}
        )
        assert status == 404


class TestSpeculativeEndpoint:
    @pytest.fixture(scope="class")
    def spec_server(self):
        proc, base = spawn_server(
            {
                "JAX_PLATFORMS": "cpu",
                "WALKAI_DEMO_MODEL": "tiny",
                "WALKAI_DEMO_LM": "1",
                "WALKAI_DEMO_SPEC": "1",
                "WALKAI_SPEC_K": "3",
                "WALKAI_LM_MAX_NEW": "8",
                "WALKAI_MAX_BATCH": "8",
                "WALKAI_WARM_BUCKETS": "1",
                "WALKAI_CALIB_WINDOW_S": "0.2",
            },
            startup_timeout_s=300.0,
            poll_s=0.25,
        )
        yield base
        kill_server(proc)

    def test_speculative_generates_target_greedy(self, spec_server):
        """The speculative path emits the SAME tokens as the plain
        target-greedy path (exactness contract, CPU-deterministic) and
        reports acceptance telemetry."""
        post = TestGenerateEndpoint._post
        prompt = {"prompt": [1, 2, 3, 4]}
        status, plain = post(self, spec_server, prompt)
        assert status == 200
        status, spec = post(
            self, spec_server, {**prompt, "speculative": True}
        )
        assert status == 200
        assert spec["speculative"] is True
        assert spec["tokens"] == plain["tokens"]
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        assert spec["tokens_per_round"] >= 1.0
        assert spec["spec_k"] == 3

    def test_speculative_position_budget(self, spec_server):
        # The speculative budget is k tighter: prompt 119 + 8 new fits
        # the tiny model's 128 positions plain, but + k 3 does not.
        post = TestGenerateEndpoint._post
        prompt = [1] * 119
        status, _ = post(
            self, spec_server, {"prompt": prompt, "speculative": True}
        )
        assert status == 400
        status, _ = post(self, spec_server, {"prompt": prompt})
        assert status == 200


class TestContinuousBatchingEndpoint:
    """Greedy /generate rides the slot-pool batcher (WALKAI_DEMO_CB,
    on by default with the LM): concurrent requests share the running
    batch and still return exactly the standalone greedy tokens."""

    @pytest.fixture(scope="class")
    def cb_server(self, tmp_path_factory):
        # Capture armed for the WHOLE class: the recorder claims to
        # be transparent, and every exactness test here doubles as
        # proof it is; the /debug/capture contract tests then ride
        # the same (expensive) server spawn.
        capture_dir = str(tmp_path_factory.mktemp("capture"))
        proc, base = spawn_server(
            {
                "JAX_PLATFORMS": "cpu",
                "WALKAI_DEMO_MODEL": "tiny",
                "WALKAI_DEMO_LM": "1",
                "WALKAI_LM_MAX_NEW": "6",
                "WALKAI_CB_SLOTS": "2",
                "WALKAI_CB_CHUNK": "2",
                "WALKAI_MAX_BATCH": "8",
                "WALKAI_WARM_BUCKETS": "1",
                "WALKAI_CALIB_WINDOW_S": "0.2",
                # SLO objective knob: a generous TTFT p99 target so
                # the windowed compliance machinery runs (and stays
                # green) on CPU CI.
                "WALKAI_SLO_TTFT_P99_S": "60",
                "WALKAI_CAPTURE_DIR": capture_dir,
            },
            startup_timeout_s=300.0,
            poll_s=0.25,
        )
        yield base
        kill_server(proc)

    _post = TestGenerateEndpoint._post

    def test_concurrent_generations_are_batched_and_exact(self, cb_server):
        import numpy as np

        import jax
        import jax.numpy as jnp

        from walkai_nos_tpu.models.decode import make_generate_fn
        from walkai_nos_tpu.models.lm import LM_TINY, DecoderLM

        # The server builds its LM from PRNGKey(0) on LM_TINY — the
        # expected continuations are reproducible here.
        params = DecoderLM(LM_TINY).init_params(jax.random.PRNGKey(0))
        gen = make_generate_fn(LM_TINY)
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(0, LM_TINY.vocab_size, n).tolist()
            for n in (3, 5, 4, 6, 2)
        ]
        results = [None] * len(prompts)

        def hit(i):
            results[i] = self._post(cb_server, {"prompt": prompts[i]})[1]

        threads = [
            threading.Thread(target=hit, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=150)
        for i, p in enumerate(prompts):
            out = results[i]
            assert out is not None and out.get("batched") is True, out
            expect = np.asarray(
                gen(params, jnp.asarray([p], jnp.int32), max_new_tokens=6)
            )[0].tolist()
            assert out["tokens"] == expect, (i, out["tokens"], expect)

    def test_stats_expose_prefix_cache_section(self, cb_server):
        """/stats carries the shared-prefix cache view (`cb_prefix`,
        `ContinuousBatcher.prefix_stats()`) — on by default, with the
        full key contract `measure_cb_prefix_reuse` differences."""
        pre = get_json(f"{cb_server}/stats").get("cb_prefix")
        assert pre is not None and pre["enabled"] is True
        assert set(pre) >= {
            "block_hits", "block_misses", "hit_rate", "evictions",
            "cached_blocks", "parked_blocks", "cached_tokens",
            "prefill_tokens_saved", "prompt_tokens",
            "prefill_tokens_saved_frac",
        }

    def test_sampled_generation(self, cb_server):
        _, out = self._post(
            cb_server,
            {"prompt": [1, 2, 3], "temperature": 0.8, "top_k": 16,
             "seed": 42},
        )
        assert out.get("batched") is True
        assert len(out["tokens"]) == 6
        # Same seed -> same continuation; different seed -> may differ
        # (and the request is deterministic, so equal means equal).
        _, again = self._post(
            cb_server,
            {"prompt": [1, 2, 3], "temperature": 0.8, "top_k": 16,
             "seed": 42},
        )
        assert again["tokens"] == out["tokens"]

    def test_per_request_budget_and_eos(self, cb_server):
        _, out = self._post(
            cb_server, {"prompt": [1, 2, 3], "max_new_tokens": 3}
        )
        assert out.get("batched") is True
        assert len(out["tokens"]) == 3
        status, _ = self._post(
            cb_server, {"prompt": [1, 2], "max_new_tokens": 99}
        )
        assert status == 400
        # EOS set to the first greedy token: generation stops at it.
        _, plain = self._post(cb_server, {"prompt": [1, 2, 3]})
        eos = plain["tokens"][0]
        _, out = self._post(
            cb_server, {"prompt": [1, 2, 3], "eos_id": eos}
        )
        assert out["tokens"] == [eos]

    def test_streaming_generation(self, cb_server):
        """SSE streaming: token events as chunks sync, a final event
        with telemetry, and the concatenation equals the
        non-streaming (= standalone greedy) output."""
        import http.client
        import json as _json
        from urllib.parse import urlparse

        _, plain = self._post(cb_server, {"prompt": [1, 2, 3, 4]})
        conn = http.client.HTTPConnection(
            urlparse(cb_server).netloc, timeout=150
        )
        conn.request(
            "POST", "/generate",
            _json.dumps({"prompt": [1, 2, 3, 4], "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        events = []
        while True:
            line = resp.fp.readline()
            if not line:
                break
            line = line.strip()
            if line.startswith(b"data: "):
                events.append(_json.loads(line[6:]))
        conn.close()
        token_events = [e for e in events if "tokens" in e]
        streamed = [t for e in token_events for t in e["tokens"]]
        final = events[-1]
        assert final.get("done") is True, events
        assert final["n_tokens"] == 6
        assert final["engine_wall_seconds"] >= final["ttft_seconds"] >= 0
        assert streamed == plain["tokens"]
        # Chunked delivery (chunk_steps=2, 6 tokens): tokens arrive
        # across multiple events, not one blob at the end.
        assert len(token_events) >= 2

    def test_streaming_bad_knobs_same_400_as_nonstreaming(self, cb_server):
        """Engine-side validation failures must carry the same HTTP
        status either way: the streaming path holds its status line
        until the first engine event."""
        status, _ = self._post(
            cb_server,
            {"prompt": [1, 2], "stream": True, "top_p": 0.0},
        )
        assert status == 400

    def test_bad_sampling_knobs_rejected(self, cb_server):
        status, _ = self._post(
            cb_server, {"prompt": [1, 2], "temperature": -1.0}
        )
        assert status == 400
        status, _ = self._post(
            cb_server, {"prompt": [1, 2], "top_p": 0.0}
        )
        assert status == 400

    def test_seed_out_of_int32_rejected_per_request(self, cb_server):
        status, _ = self._post(
            cb_server, {"prompt": [1, 2], "seed": 2**40}
        )
        assert status == 400
        # And the engine survived: the next request still works.
        status, out = self._post(cb_server, {"prompt": [1, 2]})
        assert status == 200 and out.get("batched") is True

    def test_sampling_on_fallback_path_rejected(self, cb_server):
        # A prompt whose footprint exceeds the ENGINE CACHE falls back
        # to the greedy serialized path; with sampling knobs that must
        # be a 400, not silent greedy output. (Engine cache is 128
        # here: bucket 64 + max_new 6 bucketed up; the paged prefill
        # lane serves any prompt that FITS the cache — over-bucket no
        # longer means fallback.)
        status, _ = self._post(
            cb_server,
            {"prompt": [1] * 125, "temperature": 0.9},
        )
        assert status == 400

    def test_over_bucket_prompt_served_by_slot_pool(self, cb_server):
        # Prompts longer than the prompt bucket (64) but fitting the
        # engine cache stream in through the chunked prefill lane —
        # served batched, not bounced to the serialized path.
        status, out = self._post(cb_server, {"prompt": [1] * 80})
        assert status == 200
        assert out.get("batched") is True
        assert len(out["tokens"]) > 0

    def test_trace_id_echo_and_healthz_clock(self, cb_server):
        """/generate returns the request's cross-process trace id
        (response header + JSON field): a well-formed client
        X-Walkai-Trace is adopted verbatim, anything else gets a
        server-minted id — so a slow call is always correlatable
        with /debug/trace without guessing. /healthz carries the
        process's monotonic clock read (the fleet router's
        clock-offset estimate for trace alignment)."""
        import json
        import urllib.request

        def post_traced(header):
            headers = {"Content-Type": "application/json"}
            if header is not None:
                headers["X-Walkai-Trace"] = header
            req = urllib.request.Request(
                f"{cb_server}/generate",
                data=json.dumps({"prompt": [1, 2, 3]}).encode(),
                headers=headers,
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.headers.get("X-Walkai-Trace"), json.loads(
                    resp.read()
                )

        echoed, out = post_traced("w1234ab-00000007")
        assert out["trace_id"] == "w1234ab-00000007"
        assert echoed == "w1234ab-00000007"
        # No header: the server mints one and still returns it.
        echoed, out = post_traced(None)
        assert out["trace_id"] and echoed == out["trace_id"]
        # Malformed header (bad charset): ignored, minted instead.
        echoed, out = post_traced("bad id!")
        assert out["trace_id"] != "bad id!"
        assert echoed == out["trace_id"]
        h = get_json(f"{cb_server}/healthz")
        assert isinstance(h["monotonic_s"], float)

    def test_healthz_readiness_payload(self, cb_server):
        """/healthz is a readiness payload, not a bare liveness bit:
        engine alive + queue depth + dispatch staleness + the scale
        signals (saturation, windowed SLO compliance) a kube probe or
        autoscaler consumes without scraping Prometheus text."""
        self._post(cb_server, {"prompt": [1, 2, 3]})  # ensure dispatches
        h = get_json(f"{cb_server}/healthz")
        assert h["ok"] is True
        eng = h["engine"]
        assert eng["alive"] is True
        assert eng["slots"] == 2
        assert isinstance(eng["queue_depth"], int)
        assert eng["seconds_since_last_dispatch"] >= 0
        assert isinstance(eng["has_work"], bool)
        # The engine has dispatched, so both scale signals are live:
        # saturation is a [0, 1] float and the configured TTFT
        # objective (60 s) is comfortably met on an idle CPU server.
        assert 0.0 <= eng["saturation"] <= 1.0
        assert eng["slo_ok"] is True

    def test_debug_slo_endpoint_contract(self, cb_server):
        """/debug/slo serves the sliding-window SLO view: windowed
        quantiles per histogram, the configured objectives, compliance
        + burn rate, and the composed saturation signal."""
        self._post(cb_server, {"prompt": [1, 2, 3]})
        slo = get_json(f"{cb_server}/debug/slo")["engine"]
        assert set(slo) >= {
            "window_s", "objectives", "windows", "slo_ok", "ok",
            "burn_rate", "saturation",
        }
        assert slo["objectives"] == {"ttft_p99_s": 60.0}
        assert set(slo["windows"]) == {"ttft", "tpot", "dispatch"}
        ttft = slo["windows"]["ttft"]
        assert set(ttft) == {"count", "p50", "p99", "span_s"}
        # Traffic has flowed: the window holds TTFT samples and the
        # windowed p99 is a real (positive) bucket bound.
        assert ttft["count"] >= 1
        assert ttft["p99"] > 0
        assert slo["ok"] is True
        sat = slo["saturation"]
        assert set(sat) == {"value", "components"}
        assert set(sat["components"]) == {
            "busy", "queue", "queue_trend", "pool",
        }

    def test_debug_state_fenced_snapshot(self, cb_server):
        """/debug/state is ONE snapshot of the whole engine — slots,
        block pool, prefix trie, spec controller, attribution, SLO
        windows — and its pool counts must sum exactly like
        `kv_stats()` (free + parked + in_use == allocatable blocks),
        agreeing with the /stats cb_kv view on a drained engine."""
        self._post(cb_server, {"prompt": [1, 2, 3]})
        state = get_json(f"{cb_server}/debug/state")["engine"]
        assert set(state) >= {
            "paged", "queue_depth", "has_work", "slots",
            "prefilling", "pool", "prefix", "spec", "attrib", "slo",
        }
        assert state["paged"] is True
        assert len(state["slots"]) == 2
        for row in state["slots"]:
            assert set(row) == {
                "slot", "rid", "tokens_emitted", "budget_remaining",
                "write_head", "blocks",
            }
        pool = state["pool"]
        assert (
            pool["free"] + pool["parked"] + pool["in_use"]
            == pool["blocks_total"] - pool["scratch_blocks"]
        )
        # Cross-view agreement (engine drained, so no race): the
        # snapshot's pool counts are the kv_stats() numbers.
        kv = get_json(f"{cb_server}/stats")["cb_kv"]
        assert pool["free"] == kv["kv_blocks_free"]
        assert pool["parked"] == kv["kv_blocks_parked"]
        assert pool["in_use"] == kv["kv_blocks_in_use"]
        assert pool["reserved_virtual"] == kv["kv_blocks_reserved"]
        # Attribution rode along: dispatches were classified and the
        # device/host split measured.
        at = state["attrib"]
        assert at["device_step_ms"] > 0
        assert 0.0 <= at["host_overhead_frac"] <= 1.0
        kinds = at["kinds"]
        assert sum(v["dispatches"] for v in kinds.values()) > 0

    def test_stats_expose_slo_and_attrib_sections(self, cb_server):
        """/stats carries the new views beside cb_occupancy/cb_kv —
        the same dicts /debug/slo and /debug/state serve."""
        stats = get_json(f"{cb_server}/stats")
        assert "windows" in stats["cb_slo"]
        assert "kinds" in stats["cb_attrib"]

    def test_stats_expose_quant_section(self, cb_server):
        """/stats carries the quantization view (`cb_quant`,
        `ContinuousBatcher.quant_stats()`), and /debug/state its
        `quant` block — this fixture runs the default full-precision
        dtypes, so the knobs read back 'model' and the feature reads
        disabled (the WALKAI_CB_KV_DTYPE / WALKAI_LM_W_DTYPE env
        knobs flip them; engine-level behavior is pinned in
        tests/test_serve_quant.py)."""
        quant = get_json(f"{cb_server}/stats").get("cb_quant")
        assert quant is not None
        assert quant["enabled"] is False
        assert quant["kv_dtype"] == "model"
        assert quant["w_dtype"] == "model"
        assert quant["kv_bytes_per_token"] > 0
        assert quant["param_bytes"] > 0
        state = get_json(f"{cb_server}/debug/state")["engine"]
        assert state["quant"]["kv_dtype"] == "model"

    def test_stats_expose_tp_section(self, cb_server):
        """/stats carries the tensor-parallel view (`cb_tp`,
        `ContinuousBatcher.tp_stats()`), and /debug/state its `tp`
        block — this fixture runs single-device (WALKAI_CB_TP unset),
        so the degree reads 1 and the feature disabled; sharded
        engine behavior is pinned in tests/test_serve_tp.py."""
        tp = get_json(f"{cb_server}/stats").get("cb_tp")
        assert tp is not None
        assert tp["enabled"] is False
        assert tp["tp_devices"] == 1
        assert tp["kv_layout"] is None
        assert tp["param_shard_bytes"] == tp["param_bytes"]
        state = get_json(f"{cb_server}/debug/state")["engine"]
        assert state["tp"]["tp_devices"] == 1

    def test_stats_expose_lora_section_disabled(self, cb_server):
        """/stats carries the multi-LoRA view (`cb_lora`,
        `ContinuousBatcher.lora_stats()`) — this fixture runs without
        WALKAI_CB_LORA, so the feature reads disabled and an adapter
        body field is a 400, never a silent base-weights serve."""
        assert get_json(f"{cb_server}/stats")["cb_lora"] == {
            "enabled": False
        }
        status, _ = self._post(
            cb_server, {"prompt": [1, 2, 3], "adapter": 1}
        )
        assert status == 400

    def test_metrics_prometheus_exposition(self, cb_server):
        """/metrics serves valid Prometheus text with the serving
        registry's series after traffic."""
        import re
        import urllib.request

        self._post(cb_server, {"prompt": [1, 2, 3]})
        with urllib.request.urlopen(
            f"{cb_server}/metrics", timeout=30
        ) as resp:
            assert resp.status == 200
            assert "text/plain" in resp.headers["Content-Type"]
            text = resp.read().decode()
        assert "# TYPE cb_requests_submitted_total counter" in text
        assert "# TYPE cb_ttft_seconds histogram" in text
        assert 'cb_ttft_seconds_bucket{le="+Inf"}' in text
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? -?[0-9.eE+-]+$"
        )
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            assert sample.match(line), line
        # The engine-side stats endpoints are views of these series.
        stats = get_json(f"{cb_server}/stats")
        assert stats["cb_occupancy"]["total_slot_steps"] > 0

    def test_debug_trace_chrome_export(self, cb_server):
        _, out = self._post(cb_server, {"prompt": [1, 2, 3, 4]})
        assert out.get("batched") is True
        trace = get_json(f"{cb_server}/debug/trace")
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        assert {"queued", "decode"} <= names
        for e in events:
            assert e["ph"] in ("X", "i", "M")

    def test_debug_profile_status_and_arm_validation(self, cb_server):
        import json as _json
        import urllib.error
        import urllib.request

        status = get_json(f"{cb_server}/debug/profile")
        assert status["active"] is False
        # Arming with a bad window, malformed JSON, or a non-object
        # body is a 400, not a server error.
        for payload in (
            _json.dumps({"dispatches": 0}).encode(),
            b"not json at all",
            b"5",
        ):
            req = urllib.request.Request(
                f"{cb_server}/debug/profile",
                data=payload,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raised = None
            except urllib.error.HTTPError as e:
                raised = e.code
            assert raised == 400, payload

    def test_debug_capture_contract_and_replay(
        self, cb_server, tmp_path
    ):
        """The /debug/capture surface end-to-end, pinning the
        acceptance criterion: status carries the armed ring + the
        engine's config-fingerprint id, every /generate completion
        carries the SAME id, rotate opens a fresh file, and the
        DOWNLOADED capture replays token-identically (zero divergent
        requests) through cmd/replay.py — the server inits LM_TINY
        from PRNGKey(0), which is exactly `--init-seed 0`."""
        import json as _json
        import urllib.request

        # Traffic of our own first (greedy + seeded-sampled), so the
        # capture verifiably contains these completions.
        _, greedy = self._post(cb_server, {"prompt": [2, 4, 6]})
        _, sampled = self._post(
            cb_server,
            {"prompt": [3, 5], "temperature": 0.7, "seed": 9},
        )
        status = get_json(f"{cb_server}/debug/capture")["engine"]
        assert status["enabled"] is True
        fp_id = status["fingerprint"]
        assert fp_id and len(fp_id) == 12
        assert greedy["fingerprint"] == fp_id
        assert sampled["fingerprint"] == fp_id
        assert status["records"]["submit"] >= 2
        assert status["records"]["done"] >= 2
        assert status["bytes"] > 0
        # Rotate: a fresh file opens (each self-contained).
        req = urllib.request.Request(
            f"{cb_server}/debug/capture",
            data=_json.dumps({"action": "rotate"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            rotated = _json.loads(resp.read())["engine"]
        assert len(rotated["files"]) == len(status["files"]) + 1
        # Download -> replay: the full incident workflow.
        with urllib.request.urlopen(
            f"{cb_server}/debug/capture/download", timeout=30
        ) as resp:
            blob = resp.read().decode()
        saved = tmp_path / "capture-dl.jsonl"
        saved.write_text(blob)
        from walkai_nos_tpu.cmd.replay import main as replay_main

        assert replay_main(
            [str(saved), "--init-seed", "0", "--json"]
        ) == 0

    def test_debug_capture_bad_action_rejected(self, cb_server):
        import json as _json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{cb_server}/debug/capture",
            data=_json.dumps({"action": "destroy"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raised = None
        except urllib.error.HTTPError as e:
            raised = e.code
        assert raised == 400

    def test_debug_capture_absent_without_engine(self, server):
        # Vision-only server: status engine-null like every debug
        # endpoint; download is a 404 (nothing armed).
        import urllib.error
        import urllib.request

        assert get_json(f"{server}/debug/capture") == {"engine": None}
        try:
            urllib.request.urlopen(
                f"{server}/debug/capture/download", timeout=30
            )
            raised = None
        except urllib.error.HTTPError as e:
            raised = e.code
        assert raised == 404


class TestMultiLoraEndpoint:
    """WALKAI_CB_LORA=K arms the batcher with K synthetic adapters
    (deterministic recipe — the same weights `sim/replay.py` rebuilds
    from a capture fingerprint): /generate routes an `adapter` body
    field through the batched path, responses echo the id for
    attribution, and /stats `cb_lora` carries the registry view."""

    @pytest.fixture(scope="class")
    def lora_server(self):
        proc, base = spawn_server(
            {
                "JAX_PLATFORMS": "cpu",
                "WALKAI_DEMO_MODEL": "tiny",
                "WALKAI_DEMO_LM": "1",
                "WALKAI_LM_MAX_NEW": "6",
                "WALKAI_CB_SLOTS": "2",
                "WALKAI_CB_CHUNK": "2",
                "WALKAI_MAX_BATCH": "8",
                "WALKAI_WARM_BUCKETS": "1",
                "WALKAI_CALIB_WINDOW_S": "0.2",
                "WALKAI_CB_LORA": "3",
                "WALKAI_CB_LORA_RANK": "2",
            },
            startup_timeout_s=300.0,
            poll_s=0.25,
        )
        yield base
        kill_server(proc)

    _post = TestGenerateEndpoint._post

    def test_adapter_requests_serve_and_echo(self, lora_server):
        for adapter in (0, 1, 2):
            status, out = self._post(
                lora_server, {"prompt": [1, 2, 3], "adapter": adapter}
            )
            assert status == 200, (adapter, out)
            assert out["adapter"] == adapter
            assert out.get("batched") is True
            assert len(out["tokens"]) == 6
        # Omitting the field serves the base and says so.
        status, out = self._post(lora_server, {"prompt": [1, 2, 3]})
        assert status == 200
        assert out["adapter"] == 0

    def test_unknown_adapter_is_400(self, lora_server):
        status, _ = self._post(
            lora_server, {"prompt": [1, 2, 3], "adapter": 7}
        )
        assert status == 400

    def test_stats_expose_lora_registry(self, lora_server):
        st = get_json(f"{lora_server}/stats")["cb_lora"]
        assert st["enabled"] is True
        assert st["capacity"] == 3
        assert st["rank"] == 2
        assert sorted(st["adapters"]) == ["0", "1", "2"]
        for aid, meta in st["adapters"].items():
            if aid != "0":
                assert meta["rank"] >= 1
        assert set(st["requests_total"]) == {"0", "1", "2"}
        # The engine fingerprint behind /debug/capture carries the
        # synthetic recipe, so captures from this server replay
        # without shipping adapter weights.
        fp = get_json(f"{lora_server}/debug/state")["engine"]
        assert fp["lora"]["enabled"] is True

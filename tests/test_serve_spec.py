"""Batched speculative decoding inside the paged continuous batcher
(`models/serve.py`, `spec=True`).

Tier-1 surface for the draft-and-verify serving path: spec-on output
must be TOKEN-IDENTICAL to spec-off serving for ANY draft weights
(greedy and seeded sampling alike — acceptance replays the plain
decode scan's per-token sampling/key protocol exactly), EOS landing
inside an accepted window must cut the output exactly where stepwise
decoding would, verify-window blocks that rejection left unused must
return to the pool the same sync (pool accounting exact at every
step), prefix-index blocks must only ever cover prompt rows — never
speculative or decode writes — and the acceptance-adaptive controller
must drop k and then disable drafting when the draft earns nothing,
with generation continuing through the plain path. Deliberately NOT
in conftest's `_SLOW_FILES`: the fast control-plane loop must
exercise this correctness surface, so the shapes here stay tiny.
"""

import jax
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig, draft_config
from walkai_nos_tpu.models.serve import ContinuousBatcher

import jax.numpy as jnp

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def tiny_draft():
    """An untrained draft_config draft: acceptance against the target
    is near zero (~2% on a 64-token vocab), which is exactly what the
    any-draft-exactness and controller tests want."""
    dcfg = draft_config(CFG)
    return dcfg, DecoderLM(dcfg).init_params(jax.random.PRNGKey(1))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


def _expected(params, prompt, max_new):
    gen = make_generate_fn(CFG)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


def _spec_engine(params, draft, *, spec_k=3, **kw):
    dcfg, dparams = draft
    defaults = dict(
        slots=2, cache_len=384, prompt_bucket=16, chunk_steps=3,
        prefill_chunk=32, prefill_lanes=2, spec=True, spec_k=spec_k,
        draft_cfg=dcfg, draft_params=dparams,
        # Pin drafting ON: parity must hold however little the draft
        # earns, so the controller must not rescue a broken round.
        spec_min_accept=0.0,
    )
    defaults.update(kw)
    return ContinuousBatcher(CFG, params, **defaults)


class TestSpecParity:
    """Spec-on serving vs standalone stepwise generation: identical
    for a perfect draft (draft = target, acceptance 1.0) and for an
    untrained draft (acceptance ~0) — acceptance length must never
    leak into WHAT is emitted, only into how fast."""

    SPECS = [(3, 9), (20, 17), (100, 40), (140, 11)]

    def test_greedy_parity_self_draft_mixed_ragged(self, params):
        """Prompts of 3/20/100/140 tokens crossing the 128-row block
        edge mid-prefill (140 > 128, streamed in 32-token lane
        chunks) and mid-decode (100 + 40 crosses at step 28), on 2
        slots with draft = target: full acceptance exercises
        max-length commits (k+1 tokens per slot-round)."""
        engine = _spec_engine(params, (CFG, params))
        rids = {
            engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
            for n, m in self.SPECS
        }
        res = engine.run()
        for rid, (n, m) in rids.items():
            assert res[rid] == _expected(params, _prompt(n, seed=n), m), (
                n, m,
            )
        st = engine.spec_stats()
        assert st["acceptance_rate"] == 1.0
        assert st["accepted_per_round"] == 3.0
        assert st["emitted_per_round"] == 4.0

    def test_greedy_parity_any_draft(self, params, tiny_draft):
        """Same stream through an UNTRAINED draft: near-every proposal
        is rejected, every round commits the bonus token alone — and
        the output must still be bitwise the spec-off stream."""
        engine = _spec_engine(params, tiny_draft)
        rids = {
            engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
            for n, m in self.SPECS
        }
        res = engine.run()
        for rid, (n, m) in rids.items():
            assert res[rid] == _expected(params, _prompt(n, seed=n), m), (
                n, m,
            )
        # The draft really did earn ~nothing (else this test's
        # rejection coverage is illusory).
        assert engine.spec_stats()["acceptance_rate"] < 0.5

    @pytest.mark.parametrize("draft_kind", ["self", "tiny"])
    def test_sampled_parity_spec_on_vs_off(
        self, params, tiny_draft, draft_kind
    ):
        """(prompt, knobs, seed) fully determines sampled output with
        drafting on: the chosen-token chain must replay the plain
        scan's split-per-token key protocol, so the surviving PRNG
        key — not just the committed prefix — matches spec-off."""
        p = _prompt(11, seed=42)
        draft = (CFG, params) if draft_kind == "self" else tiny_draft
        outs = {}
        for spec in (True, False):
            if spec:
                engine = _spec_engine(
                    params, draft, slots=2, cache_len=256,
                    chunk_steps=4, prefill_chunk=8,
                )
            else:
                engine = ContinuousBatcher(
                    CFG, params, slots=2, cache_len=256, chunk_steps=4,
                    prefill_chunk=8,
                )
            rid = engine.submit(
                p, max_new_tokens=8, temperature=0.9, top_k=16,
                top_p=0.95, seed=123,
            )
            outs[spec] = engine.run()[rid]
        assert outs[True] == outs[False]
        assert len(outs[True]) == 8

    def test_eos_inside_accepted_window(self, params):
        """With draft = target and k = 3, every round commits 4
        tokens; an EOS at a non-boundary position lands INSIDE an
        accepted window, and the tokens accepted after it must be
        dropped exactly as stepwise decoding would never have emitted
        them."""
        full = _expected(params, _prompt(6, seed=6), 12)
        candidates = [
            (t, i) for i, t in enumerate(full)
            if 1 <= i < 11 and t not in full[:i]
        ]
        # Prefer an EOS position strictly inside a commit window
        # (i % 4 != 3): tokens after it in the SAME window get
        # accepted by the verify and must still be discarded.
        eos, cut = min(candidates, key=lambda c: (c[1] % 4 == 3, c[1]))
        engine = _spec_engine(
            params, (CFG, params), slots=1, cache_len=128,
            chunk_steps=4, prefill_chunk=8,
        )
        rid = engine.submit(
            _prompt(6, seed=6), max_new_tokens=12, eos_id=eos
        )
        assert engine.run()[rid] == full[:cut + 1]


class TestSpecTableEdge:
    """A verify window crossing the block table's edge (total ==
    cache_len == a 128 multiple, so the last rounds start within
    spec_k of capacity) must not corrupt committed rows: the paged
    write path DROPS out-of-capacity K/V rows. Clipping them instead
    rewrites rows 0..k-1 of the slot's last real block before the same
    dispatch's kernel reads them — the final committed tokens come out
    of corrupted attention and parity silently breaks."""

    # Tier-1 keeps only the "tiny" arm: the untrained draft's
    # single-token walks are the arm that actually failed pre-fix
    # (CHANGES PR 5); the self-draft arm re-proves the same drop rule
    # from the full-acceptance side at ~12 s — slow-lane coverage,
    # not a distinct regression pin.
    @pytest.mark.parametrize(
        "draft_kind",
        [pytest.param("self", marks=pytest.mark.slow), "tiny"],
    )
    def test_parity_at_table_capacity(
        self, params, tiny_draft, draft_kind
    ):
        """Totals of exactly cache_len=256 with prompt lengths across
        every mod-4 alignment: the self draft's full-acceptance
        windows (+4/round) and the untrained draft's single-token
        walks (+1/round) both start rounds at heads 253..255, writing
        verify rows past capacity."""
        draft = (CFG, params) if draft_kind == "self" else tiny_draft
        engine = _spec_engine(
            params, draft, slots=2, cache_len=256, prefill_chunk=64,
        )
        specs = [(200, 56), (201, 55), (230, 26), (131, 125)]
        rids = {
            engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
            for n, m in specs
        }
        res = engine.run()
        for rid, (n, m) in rids.items():
            assert res[rid] == _expected(params, _prompt(n, seed=n), m), (
                n, m,
            )


class TestSpecRollback:
    """Blocks grabbed to back a verify window whose rows were then
    rejected must return to the pool at the round's sync — residency
    tracks COMMITTED tokens exactly, never speculative lookahead."""

    def test_pool_accounting_tracks_committed_tokens_exactly(
        self, params, tiny_draft
    ):
        """A 126-token prompt decodes across the 128-row boundary
        with an untrained draft: while the head sits at 125..127,
        every round grabs block 2 for its 4-row verify window and —
        on rejection — must hand it straight back. After every
        step(), blocks in use must equal ceil(committed / 128): a
        leaked speculative block shows up as in_use = 2 one sync
        early, a lost one as an exhausted pool later."""
        engine = _spec_engine(
            params, tiny_draft, slots=1, cache_len=384,
            prefill_chunk=128, prefill_lanes=1, prefix_cache=False,
        )
        rid = engine.submit(_prompt(126, seed=9), max_new_tokens=20)
        emitted = 0
        done = {}
        while engine.has_work:
            engine.step()
            emitted += sum(
                len(v) for v in engine.drain_new_tokens().values()
            )
            done.update(engine.drain_done())
            kv = engine.kv_stats()
            assert (
                kv["kv_blocks_in_use"] + kv["kv_blocks_free"]
                == engine.pool_blocks - 1
            )
            if not done:
                # The first emitted token is sampled from prefill
                # logits; its K/V row is written by the round that
                # emits token 2 — so rows resident after a sync are
                # prompt + emitted - 1 (and just the prompt pre-flip).
                committed = 126 + max(0, emitted - 1)
                assert kv["kv_blocks_in_use"] == -(-committed // 128), (
                    emitted, kv,
                )
        assert len(done[rid]) == 20
        kv = engine.kv_stats()
        assert kv["kv_blocks_in_use"] == 0
        assert kv["kv_blocks_free"] == engine.pool_blocks - 1
        assert kv["kv_blocks_reserved"] == 0


class TestSpecPrefixInterplay:
    """The prefix index must only ever serve blocks fully covered by
    PROMPT tokens: decode-written blocks — which carry committed AND
    rejected speculative rows — are private and never matchable."""

    def test_prompt_blocks_share_decode_blocks_never_match(
        self, params
    ):
        engine = _spec_engine(
            params, (CFG, params), slots=2, cache_len=384,
            prefill_chunk=64,
        )
        pa = _prompt(140, seed=20)
        ra = engine.submit(pa, max_new_tokens=20)
        out_a = engine.run()[ra]
        assert out_a == _expected(params, pa, 20)
        base = engine.prefix_stats()
        # A's one full prompt block (rows 0..127) is cached; its
        # decode block (rows 128..255: prompt tail + committed +
        # rejected speculative rows) must NOT be.
        assert base["cached_blocks"] == 1

        # B shares A's first 128 prompt tokens: exactly that block
        # must hit, and the shared-cache output must equal cold
        # stepwise generation.
        pb = np.concatenate([pa[:128], _prompt(10, seed=21)])
        rb = engine.submit(pb, max_new_tokens=12)
        out_b = engine.run()[rb]
        assert out_b == _expected(params, pb, 12)
        after_b = engine.prefix_stats()
        assert after_b["block_hits"] == base["block_hits"] + 1

        # C's prompt extends A's full sequence INTO the decode
        # region: its second full block spells rows A physically
        # holds in a private decode block, which was never indexed —
        # so C must match only block 0 and prefill the rest fresh,
        # still token-identical to cold generation.
        pc = np.asarray(
            list(pa) + out_a + list(_prompt(100, seed=22)), np.int32
        )[:260]
        rc = engine.submit(pc, max_new_tokens=8)
        out_c = engine.run()[rc]
        assert out_c == _expected(params, pc, 8)
        after_c = engine.prefix_stats()
        assert after_c["block_hits"] == after_b["block_hits"] + 1
        assert after_c["block_misses"] > after_b["block_misses"]


class TestSpecController:
    """The acceptance-adaptive controller: EMA of accepted drafts per
    (live slot, round) under `spec_min_accept` past the warmup first
    halves k, then disables drafting for the engine's lifetime."""

    def test_disables_drafting_under_zero_acceptance(
        self, params, tiny_draft
    ):
        """An untrained draft accepts ~nothing: k must walk 2 -> 1,
        drafting must disable, and generation must finish through the
        plain chunk path — with the output still bitwise correct."""
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=384, chunk_steps=4,
            prefill_chunk=32, spec=True, spec_k=2,
            draft_cfg=tiny_draft[0], draft_params=tiny_draft[1],
            spec_warmup_rounds=3,
        )
        rid = engine.submit(_prompt(6, seed=1), max_new_tokens=60)
        assert engine.run()[rid] == _expected(
            params, _prompt(6, seed=1), 60
        )
        st = engine.spec_stats()
        assert st["drafting_disabled"] is True
        assert st["k"] == 1 and st["k_configured"] == 2
        assert int(engine.obs.spec_disabled.value()) == 1
        # Rounds stopped the moment drafting disabled: far fewer
        # verify dispatches than the 60 tokens would need at 1/round.
        assert st["verify_dispatches"] < 30

    @pytest.mark.slow
    def test_keeps_drafting_when_acceptance_earns(self, params):
        """Draft = target at the DEFAULT acceptance threshold: the
        EMA sits at k, so the controller must leave drafting on well
        past the warmup. Slow lane (~14 s): the regression-critical
        controller direction — disable under zero acceptance — stays
        tier-1 in test_disables_drafting_under_zero_acceptance."""
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=384, chunk_steps=4,
            prefill_chunk=32, spec=True, spec_k=3, draft_cfg=CFG,
            draft_params=params, spec_warmup_rounds=4,
        )
        rid = engine.submit(_prompt(8, seed=2), max_new_tokens=48)
        assert engine.run()[rid] == _expected(
            params, _prompt(8, seed=2), 48
        )
        st = engine.spec_stats()
        assert st["drafting_disabled"] is False
        assert st["k"] == 3
        assert st["acceptance_rate"] == 1.0


class TestSpecValidation:
    def test_requires_paged_engine(self, params):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128, paged=False,
                spec=True, draft_cfg=CFG, draft_params=params,
            )

    def test_requires_draft(self, params):
        with pytest.raises(ValueError, match="draft"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128, spec=True
            )

    @pytest.mark.parametrize("k", [0, 8])
    def test_spec_k_bounds(self, params, k):
        """k + 1 verify positions ride the multi-step decode kernel
        (MAX_KERNEL_STEPS = 8), so k itself caps at 7."""
        with pytest.raises(ValueError, match="spec_k"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128, spec=True,
                spec_k=k, draft_cfg=CFG, draft_params=params,
            )

    def test_vocab_mismatch_rejected(self, params):
        import dataclasses

        bad = dataclasses.replace(CFG, vocab_size=32)
        with pytest.raises(ValueError, match="vocab"):
            ContinuousBatcher(
                CFG, params, slots=1, cache_len=128, spec=True,
                draft_cfg=bad, draft_params=params,
            )

    def test_submit_lookahead_guard(self, params):
        """The verify window peeks spec_k positions past the budget:
        a request whose total fits cache_len but whose lookahead
        crosses max_seq_len must reject at submit, through the
        oversize taxonomy."""
        engine = _spec_engine(
            params, (CFG, params), slots=1, cache_len=512,
        )
        with pytest.raises(ValueError, match="lookahead"):
            engine.submit(_prompt(300, seed=3), max_new_tokens=212)
        # One token of slack under the lookahead limit admits.
        rid = engine.submit(_prompt(300, seed=3), max_new_tokens=209)
        assert isinstance(rid, int)

    @pytest.mark.slow
    def test_lookahead_guard_relaxes_after_disable(
        self, params, tiny_draft
    ):
        """Drafting disables one-way: once the controller flips it
        off no verify window ever runs again, so the submit guard —
        gated on the LIVE controller state — must go back to
        admitting requests right up to cache_len, exactly like
        spec-off serving. Slow lane (~29 s, the file's heaviest: it
        must first DRIVE the controller to disable, then serve to
        cache_len): the guard's reject side stays tier-1 in
        test_submit_lookahead_guard, and the disable walk itself in
        test_disables_drafting_under_zero_acceptance."""
        engine = _spec_engine(
            params, tiny_draft, slots=1, cache_len=512,
            spec_min_accept=0.9, spec_warmup_rounds=2,
        )
        with pytest.raises(ValueError, match="lookahead"):
            engine.submit(_prompt(500, seed=4), max_new_tokens=12)
        rid = engine.submit(_prompt(6, seed=5), max_new_tokens=24)
        assert engine.run()[rid] == _expected(
            params, _prompt(6, seed=5), 24
        )
        assert engine.spec_stats()["drafting_disabled"] is True
        rid = engine.submit(_prompt(500, seed=4), max_new_tokens=12)
        assert engine.run()[rid] == _expected(
            params, _prompt(500, seed=4), 12
        )

"""Device-resident multi-step serving loop (`models/serve.py`
`loop_steps > 1`).

Tier-1 surface for ROADMAP item 3's host-dispatch kill: folding N
decode chunks (or speculative rounds) into one donated-carry
`lax.while_loop` dispatch must be TOKEN-IDENTICAL to the per-chunk
path — greedy and seeded sampling, spec on and off, prefix reuse on
and off — because the loop changes WHEN the host learns about tokens,
never WHICH. Every loop-exit condition is exercised (EOS mid-horizon,
budget exhaustion, an unbacked-block exit with re-entry, lazy
re-backing between loop dispatches, admission-pending fallback to the
per-chunk path), and the obs counters the capacity bench derives from
must agree with loop-off within the batcher's existing contracts.
Deliberately NOT in conftest's `_SLOW_FILES`: shapes stay tiny — a
1-layer model (the loop is model-agnostic; depth only multiplies
compile time) and the minimum engine count that still covers the
combination matrix, because every `ContinuousBatcher` compiles its
own loop program.
"""

import jax
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig, draft_config
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    cfg = draft_config(CFG)
    return cfg, DecoderLM(cfg).init_params(jax.random.PRNGKey(7))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


def _expected(params, prompt, max_new):
    gen = make_generate_fn(CFG)
    out = gen(
        params, np.asarray(prompt)[None], max_new_tokens=max_new
    )
    return [int(t) for t in np.asarray(out)[0]]


def _engine(params, *, loop, **kw):
    defaults = dict(
        slots=2, cache_len=384, prompt_bucket=16, chunk_steps=3,
        prefill_chunk=32, prefill_lanes=2,
    )
    defaults.update(kw)
    return ContinuousBatcher(
        CFG, params, loop_steps=loop, **defaults
    )


class TestLoopTokenParity:
    """Loop-on output == loop-off output, token for token, for every
    engine mode combination."""

    def test_mixed_ragged_greedy_and_sampled(self, params):
        """Prompts of 3/20/100/140 tokens (140 crosses the 128-row
        block edge mid-prefill, 100+40 crosses mid-decode), greedy
        and seeded-sampled in one batch, loop_steps 1 vs 4, with the
        prefix trie OFF (the on arm is the next test) — plus the
        greedy rows pinned against standalone generation."""
        specs = [(3, 9, 0.0), (20, 17, 0.9), (100, 40, 0.0),
                 (140, 11, 1.1)]
        outs = {}
        for loop in (1, 4):
            engine = _engine(params, loop=loop, prefix_cache=False)
            rids = {
                engine.submit(
                    _prompt(n, seed=n), max_new_tokens=m,
                    temperature=t, seed=n,
                ): (n, m)
                for n, m, t in specs
            }
            res = engine.run()
            outs[loop] = {rids[r]: toks for r, toks in res.items()}
        assert outs[1] == outs[4]
        for n, m, t in specs:
            if t == 0.0:
                assert outs[1][(n, m)] == _expected(
                    params, _prompt(n, seed=n), m
                ), (n, m)

    def test_prefix_reuse_parity(self, params):
        """Shared 140-token prompt prefix served twice (the second
        admission maps the first's blocks through the trie):
        loop-on == loop-off with prefix reuse on, greedy and sampled
        tails, and the trie actually hit in both arms."""
        shared = _prompt(140, seed=3)
        outs = {}
        for loop in (1, 4):
            engine = _engine(
                params, loop=loop, prefix_cache=True,
                slots=2, cache_len=384,
            )
            # Serve the template cold first: its full prompt block
            # parks in the trie on release, so the second admission
            # MATCHES it (concurrent admissions would miss — the
            # block only turns `ready` after its writing chunk
            # dispatches).
            r1 = engine.submit(shared, max_new_tokens=9)
            out1 = engine.run()[r1]
            r2 = engine.submit(
                np.concatenate([shared[:130], _prompt(7, seed=9)]),
                max_new_tokens=8, temperature=0.8, seed=5,
            )
            outs[loop] = (out1, engine.run()[r2])
            assert engine.prefix_stats()["block_hits"] >= 1
        assert outs[1] == outs[4]
        assert outs[1][0] == _expected(params, shared, 9)

    @pytest.mark.parametrize("self_draft", [True, False])
    def test_spec_loop_parity(self, params, draft, self_draft):
        """Speculative rounds folded into the loop: spec-on loop-on ==
        spec-on loop-off, for the full-acceptance self-draft AND an
        untrained draft (near-zero acceptance), greedy + sampled;
        greedy rows pinned against spec-off standalone generation
        (spec-on == spec-off is tests/test_serve_spec.py's claim)."""
        dcfg, dparams = draft
        if self_draft:
            dcfg, dparams = CFG, params
        specs = [(3, 9, 0.0), (100, 24, 0.9), (140, 11, 0.0)]
        outs = {}
        for loop in (1, 4):
            engine = _engine(
                params, loop=loop, spec=True, spec_k=3,
                draft_cfg=dcfg, draft_params=dparams,
                spec_min_accept=0.0,
            )
            rids = {
                engine.submit(
                    _prompt(n, seed=n), max_new_tokens=m,
                    temperature=t, seed=n,
                ): (n, m)
                for n, m, t in specs
            }
            res = engine.run()
            outs[loop] = {rids[r]: toks for r, toks in res.items()}
        assert outs[1] == outs[4]
        for n, m, t in specs:
            if t == 0.0:
                assert outs[1][(n, m)] == _expected(
                    params, _prompt(n, seed=n), m
                ), (n, m)

    def test_streaming_feed_agrees_with_records(self, params):
        """`drain_new_tokens`, accumulated across loop syncs, must
        equal each request's completion record — tokens arrive at
        loop-sync granularity but never diverge."""
        engine = _engine(params, loop=4)
        rids = [
            engine.submit(_prompt(6, seed=6), max_new_tokens=10),
            engine.submit(_prompt(30, seed=8), max_new_tokens=14),
        ]
        streamed = {r: [] for r in rids}
        records = {}
        while engine.has_work:
            engine.step()
            for r, toks in engine.drain_new_tokens().items():
                streamed[r].extend(toks)
            records.update(engine.drain_done_records())
        for r, toks in engine.drain_new_tokens().items():
            streamed[r].extend(toks)
        records.update(engine.drain_done_records())
        for r in rids:
            assert streamed[r] == records[r]["tokens"]
            assert records[r]["ttft_s"] >= 0


class TestLoopExitConditions:
    def test_eos_mid_horizon(self, params):
        """A request hitting its EOS inside the fold must exit the
        loop (reason slot_done) and be released at that sync — the
        other slot keeps decoding in later loop dispatches."""
        full = _expected(params, _prompt(6, seed=6), 30)
        eos, cut = next(
            (t, i) for i, t in enumerate(full)
            if 1 <= i < 25 and t not in full[:i]
        )
        engine = _engine(params, loop=8, chunk_steps=2)
        r_eos = engine.submit(
            _prompt(6, seed=6), max_new_tokens=30, eos_id=eos
        )
        r_long = engine.submit(_prompt(9, seed=2), max_new_tokens=40)
        res = engine.run()
        assert res[r_eos] == full[:cut + 1]
        assert len(res[r_long]) == 40
        stats = engine.loop_stats()
        assert stats["exits"]["slot_done"] >= 1
        assert stats["dispatches"] >= 2  # loop re-entered after exit

    def test_budget_exhaustion_exit(self, params):
        """Budget exhaustion mid-horizon exits the loop with exactly
        the owed tokens committed — never a token more."""
        engine = _engine(params, loop=8, chunk_steps=3)
        rid = engine.submit(_prompt(5, seed=4), max_new_tokens=7)
        res = engine.run()
        assert res[rid] == _expected(params, _prompt(5, seed=4), 7)
        assert engine.loop_stats()["exits"]["slot_done"] >= 1

    def test_unbacked_exit_and_reentry(self, params):
        """A 128-aligned footprint (prompt 100 + budget 28 = exactly
        one block) makes the write head reach the backed boundary
        mid-horizon: the loop must exit `unbacked` BEFORE any live
        slot writes an unbacked row, let the host re-run its backing
        pass, re-enter, and finish with the exact per-chunk tokens."""
        engine = _engine(
            params, loop=8, chunk_steps=8, slots=1, cache_len=256,
        )
        rid = engine.submit(_prompt(100, seed=5), max_new_tokens=28)
        res = engine.run()
        assert res[rid] == _expected(params, _prompt(100, seed=5), 28)
        stats = engine.loop_stats()
        assert stats["exits"]["unbacked"] >= 1
        assert stats["dispatches"] >= 2  # re-entered after re-backing

    def test_lazy_rebacking_between_loop_dispatches(self, params):
        """A footprint spanning two blocks with a horizon shorter than
        the remainder: the host grabs the second decode block between
        loop dispatches (lazy backing survives the loop) and the
        output crosses the block edge intact."""
        engine = _engine(
            params, loop=2, chunk_steps=8, slots=1, cache_len=256,
        )
        rid = engine.submit(_prompt(100, seed=5), max_new_tokens=60)
        blocks_seen = set()
        done = {}
        while engine.has_work:
            engine.step()
            blocks_seen.add(len(engine._slot_blocks[0]))
            done.update(engine.drain_done())
        done.update(engine.drain_done())
        assert done[rid] == _expected(
            params, _prompt(100, seed=5), 60
        )
        assert {1, 2} <= blocks_seen  # second block grabbed mid-run
        assert engine.loop_stats()["exits"]["horizon"] >= 1

    def test_admission_pending_routes_per_chunk(self, params):
        """A submission arriving while slots decode must pull the
        engine back onto the per-chunk path (the lane admits it there)
        and the loop resumes after flip-live — both requests exact."""
        engine = _engine(params, loop=4, chunk_steps=3, slots=2)
        r1 = engine.submit(_prompt(9, seed=1), max_new_tokens=30)
        # Let the first request flip live and loop at least once.
        for _ in range(3):
            engine.step()
        assert engine.loop_stats()["dispatches"] >= 1
        r2 = engine.submit(_prompt(20, seed=2), max_new_tokens=12)
        res = {}
        while engine.has_work:
            engine.step()
            res.update(engine.drain_done())
        res.update(engine.drain_done())
        assert res[r1] == _expected(params, _prompt(9, seed=1), 30)
        assert res[r2] == _expected(params, _prompt(20, seed=2), 12)
        # The admission rode the per-chunk lane: prefill/mixed
        # dispatches happened alongside loop dispatches.
        kinds = engine.attrib_stats()["kinds"]
        lane_dispatches = (
            kinds["prefill"]["dispatches"] + kinds["mixed"]["dispatches"]
        )
        assert lane_dispatches >= 1
        assert engine.loop_stats()["dispatches"] >= 2

    def test_constructor_validation(self, params):
        with pytest.raises(ValueError, match="loop_steps"):
            ContinuousBatcher(CFG, params, loop_steps=0)
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(
                CFG, params, loop_steps=4, paged=False, cache_len=64
            )


class TestLoopObsInvariants:
    def test_counters_agree_with_loop_off(self, params):
        """`cb_tokens_total` must be IDENTICAL loop-on vs loop-off
        (committed tokens are committed tokens); slot-step counters
        stay within the batcher's existing contracts (busy <= total,
        busy covers every emitted token); TTFT spans equal the
        completion records exactly (the shared clock-read rule)."""
        specs = [(3, 9), (20, 17), (100, 40)]
        measured = {}
        for loop in (1, 4):
            engine = _engine(params, loop=loop)
            for n, m in specs:
                engine.submit(_prompt(n, seed=n), max_new_tokens=m)
            records = {}
            while engine.has_work:
                engine.step()
                records.update(engine.drain_done_records())
            records.update(engine.drain_done_records())
            occ = engine.occupancy()
            measured[loop] = {
                "tokens": int(engine.obs.tokens.value()),
                "busy": occ["busy_slot_steps"],
                "total": occ["total_slot_steps"],
                "records": records,
            }
        want = sum(m for _, m in specs)
        assert measured[1]["tokens"] == measured[4]["tokens"] == want
        for loop in (1, 4):
            m = measured[loop]
            assert m["busy"] <= m["total"]
            assert m["busy"] >= m["tokens"]
            for rec in m["records"].values():
                assert 0 <= rec["ttft_s"] <= rec["wall_s"]
        # TTFT spans: the trace reuses the engine's own clock reads,
        # so span-derived ttft equals the record's exactly — checked
        # on the loop-on engine with a fresh request (programs are
        # already compiled; engines are reusable).
        rid = engine.submit(_prompt(6, seed=6), max_new_tokens=8)
        records = {}
        while engine.has_work:
            engine.step()
            records.update(engine.drain_done_records())
        records.update(engine.drain_done_records())
        span = next(
            s for s in engine.obs.trace.spans() if s["rid"] == rid
        )
        assert span["first_token"] - span["submit"] == pytest.approx(
            records[rid]["ttft_s"]
        )

    def test_loop_stats_views(self, params):
        """`loop_stats()` / `debug_state()["loop"]` report the fold
        telemetry; the steps-per-sync gauge exceeds one chunk's worth
        whenever a fold ran deeper than a single chunk."""
        engine = _engine(params, loop=4, chunk_steps=3)
        rid = engine.submit(_prompt(9, seed=1), max_new_tokens=30)
        engine.run()
        stats = engine.loop_stats()
        assert stats["enabled"] and stats["loop_steps"] == 4
        assert stats["dispatches"] >= 1
        assert stats["chunks_folded"] >= stats["dispatches"]
        assert stats["steps_per_sync"] > engine.chunk_steps
        assert engine.debug_state()["loop"] == stats
        disabled = ContinuousBatcher(
            CFG, params, slots=2, cache_len=128, obs=False
        )
        view = disabled.loop_stats()
        assert view["obs_disabled"] is True
        assert view["enabled"] is False

"""Scheduling-latency benchmark harness (`walkai_nos_tpu/sim/schedbench.py`)."""

import pytest

from walkai_nos_tpu.sim.schedbench import _workload, run_scheduling_benchmark
from walkai_nos_tpu.tpu.tiling.profile import Profile


class TestWorkload:
    def test_fill_is_within_capacity(self):
        for n_nodes in (2, 10):
            plan = _workload(n_nodes)
            chips = sum(Profile.parse(p).chips for _, p in plan)
            assert 0 < chips <= n_nodes * 8
            # Largest-first ordering (first-fit-decreasing).
            sizes = [Profile.parse(p).chips for _, p in plan]
            assert sizes == sorted(sizes, reverse=True)


@pytest.mark.slow
class TestSchedulingBench:
    def test_small_cluster_end_to_end(self):
        r = run_scheduling_benchmark(
            n_nodes=2, report_interval=0.02, stagger_s=0.002, timeout_s=30.0
        )
        assert r.unscheduled == 0
        assert r.scheduled == len(_workload(2))
        assert 0 < r.p50_s <= r.p90_s <= r.max_s
        # Sharing phase: every chip-count share pod binds too.
        assert r.share_unscheduled == 0
        assert r.share_scheduled > 0
        assert 0 < r.share_p50_s <= r.share_p90_s


@pytest.mark.slow
class TestScaleOut:
    def test_twenty_node_cluster_schedules_everything(self):
        """Scale-out proof: ~94 mixed-profile pods over 20 hosts all
        bind with bounded p50 — the packer and the controller fabric
        hold up under 20 concurrent agent loops and API churn
        (measured ~0.8 s p50; the bound leaves headroom for CI load)."""
        r = run_scheduling_benchmark(
            n_nodes=20, stagger_s=0.002, timeout_s=120.0
        )
        assert r.unscheduled == 0
        assert r.scheduled == len(_workload(20))
        assert r.p50_s < 5.0

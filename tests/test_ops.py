"""Fused-op correctness: Pallas kernels vs XLA reference (interpret mode).

Mirrors the reference's rule that hardware never appears in tests
(SURVEY.md §4): Pallas runs in interpret mode on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.ops import attention as attn
from walkai_nos_tpu.ops.ring_attention import ring_attention
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 3, 256, 32
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
        for _ in range(3)
    )
    ref = attn.attention_reference(q, k, v, causal=causal)
    out = attn.flash_attention(
        q, k, v, causal=causal, block_q=128, block_k=64, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_falls_back_on_odd_shapes():
    # 100 is not a sublane multiple -> XLA reference path.
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 2, 100, 16)), jnp.float32)
    out = attn.flash_attention(q, q, q, interpret=True)
    ref = attn.attention_reference(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_causal_cross_length():
    """sq < sk (decode-style): diagonal is bottom-right aligned, matching
    the reference's tril(k=sk-sq) on both dispatch paths."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 2, 64, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 256, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 256, 32)), jnp.float32)
    # block_q must be a multiple of block_k for the kernel's causal path —
    # these blocks keep the Pallas kernel (not the fallback) under test.
    out = attn.flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True
    )
    ref = attn.attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    """Sequence sharded over a 4-way seq ring == single-device attention."""
    mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
    rng = np.random.default_rng(2)
    b, h, s, d = 1, 2, 64, 16
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
        for _ in range(3)
    )
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = attn.attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestRingFlash:
    """The flash-kernel ring body: fused per-step attention + lse merge
    (no (S/N)^2 score block per device) must match the reference in
    value AND gradient."""

    def _qkv(self, seed=3, shape=(1, 2, 64, 16)):
        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.standard_normal(shape), jnp.float32)
            for _ in range(3)
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        q, k, v = self._qkv()
        out = ring_attention(
            q, k, v, mesh, causal=causal, use_flash=True,
            block_q=8, block_k=8, interpret=True,
        )
        ref = attn.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grad_matches_reference(self, causal):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        q, k, v = self._qkv(seed=4)

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(
                    q, k, v, mesh, causal=causal, use_flash=True,
                    block_q=8, block_k=8, interpret=True,
                ) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(
                attn.attention_reference(q, k, v, causal=causal) ** 2
            )

        gr_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gr_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gr_ring, gr_ref):
            assert jnp.allclose(a, b, atol=1e-4), (
                name, float(jnp.max(jnp.abs(a - b)))
            )

    def test_untileable_shard_raises_when_forced(self):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        q, k, v = self._qkv(shape=(1, 2, 36, 16))  # shard 9: not /8
        with pytest.raises(ValueError, match="do not tile"):
            ring_attention(
                q, k, v, mesh, causal=False, use_flash=True,
                interpret=True,
            )


class TestFlashAttentionGrad:
    """The fused Pallas backward (block-recompute from the saved
    logsumexp, no S x S materialization) must produce the reference's
    gradients — pallas kernels are not auto-differentiable, so training
    correctness rides on this hand-written VJP."""

    @pytest.mark.parametrize(
        "causal,shape,block_q,block_k",
        [
            (True, (1, 2, 32, 16), 8, 8),
            (False, (1, 2, 32, 16), 8, 8),
            (True, (2, 3, 64, 32), 16, 8),   # uneven blocks
            (False, (2, 1, 48, 16), 8, 16),  # block_k > block_q
            (True, (1, 2, 64, 16), 32, 32),
        ],
    )
    def test_grad_matches_reference_in_interpret_mode(
        self, causal, shape, block_q, block_k
    ):
        rng = np.random.default_rng(5)
        q, k, v = (
            jnp.asarray(rng.standard_normal(shape), jnp.float32)
            for _ in range(3)
        )
        # A non-symmetric loss so dq/dk/dv all get distinct cotangents.
        w = jnp.asarray(rng.standard_normal(shape), jnp.float32)

        def loss_flash(q, k, v):
            return jnp.sum(
                w * attn.flash_attention(
                    q, k, v, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=True,
                ) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(
                w * attn.attention_reference(q, k, v, causal=causal) ** 2
            )

        grads_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, gf, gr in zip("qkv", grads_flash, grads_ref):
            assert jnp.allclose(gf, gr, atol=1e-4), (
                name, float(jnp.max(jnp.abs(gf - gr)))
            )

    def test_grad_causal_cross_length(self):
        """Cross-attention with sq < sk exercises the bottom-right-
        aligned diagonal in both backward kernels."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.standard_normal((1, 2, 16, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 2, 32, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((1, 2, 32, 16)), jnp.float32)

        def loss(fn):
            def inner(q, k, v):
                return jnp.sum(fn(q, k, v) ** 2)
            return inner

        flash = loss(
            lambda q, k, v: attn.flash_attention(
                q, k, v, causal=True, block_q=8, block_k=8, interpret=True
            )
        )
        ref = loss(
            lambda q, k, v: attn.attention_reference(q, k, v, causal=True)
        )
        gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            assert jnp.allclose(a, b, atol=1e-4), (
                float(jnp.max(jnp.abs(a - b)))
            )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        rng = np.random.default_rng(3)
        q, k, v = (
            jnp.asarray(rng.standard_normal((2, 4, 32, 16)), jnp.float32)
            for _ in range(3)
        )
        from walkai_nos_tpu.ops.ulysses import ulysses_attention

        out = ulysses_attention(q, k, v, mesh, causal=causal)
        ref = attn.attention_reference(q, k, v, causal=causal)
        assert jnp.allclose(out, ref, atol=2e-3), (
            float(jnp.max(jnp.abs(out - ref)))
        )

    def test_indivisible_heads_rejected(self):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        from walkai_nos_tpu.ops.ulysses import ulysses_attention

        q = jnp.ones((1, 6, 32, 16), jnp.float32)  # 6 heads, 4-way seq
        with pytest.raises(ValueError, match="ring attention"):
            ulysses_attention(q, q, q, mesh)

    def test_differentiable(self):
        mesh = build_mesh(jax.devices()[:4], axes=MeshAxes(seq=4))
        from walkai_nos_tpu.ops.ulysses import ulysses_attention

        q = jnp.asarray(
            np.random.default_rng(4).standard_normal((1, 4, 32, 16)),
            jnp.float32,
        )

        def loss(q):
            return jnp.sum(ulysses_attention(q, q, q, mesh, causal=True) ** 2)

        g = jax.grad(loss)(q)
        assert bool(jnp.all(jnp.isfinite(g)))
        assert float(jnp.max(jnp.abs(g))) > 0.0


class TestDecodeAttention:
    """Fused single-query decode attention (`ops/decode_attention.py`)
    vs its XLA reference, interpret mode (no hardware in tests)."""

    def _qkv(self, b=2, h=4, s=256, d=64, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, h, d)), dtype)
        k = jnp.asarray(rng.standard_normal((b, h, s, d)), dtype)
        v = jnp.asarray(rng.standard_normal((b, h, s, d)), dtype)
        return q, k, v

    @pytest.mark.parametrize("index", [0, 5, 127, 255])
    def test_matches_reference(self, index):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv()
        out = da.decode_attention(
            q, k, v, jnp.int32(index), interpret=True
        )
        ref = da.decode_attention_reference(q, k, v, jnp.int32(index))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_mask_hides_future_cache_rows(self):
        """Garbage beyond `index` must not leak into the output: the
        bucketed ring cache holds stale/zero rows there."""
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(seed=1)
        poisoned_k = k.at[:, :, 100:].set(1e9)
        poisoned_v = v.at[:, :, 100:].set(1e9)
        out = da.decode_attention(
            q, poisoned_k, poisoned_v, jnp.int32(99), interpret=True
        )
        clean = da.decode_attention(
            q, k, v, jnp.int32(99), interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(clean), atol=2e-5
        )

    def test_untiled_cache_falls_back(self):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(s=100)  # not a lane multiple
        out = da.decode_attention(q, k, v, jnp.int32(50))
        ref = da.decode_attention_reference(q, k, v, jnp.int32(50))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_bf16_inputs_f32_accumulation(self):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(dtype=jnp.bfloat16, seed=2)
        out = da.decode_attention(q, k, v, jnp.int32(200), interpret=True)
        ref_f32 = da.decode_attention_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), jnp.int32(200),
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref_f32), atol=3e-2
        )


class TestFlashAttentionPacked:
    """flash_attention_packed: attention straight off the fused qkv
    projection ([b, s, 3d] -> [b, s, d], the serving ViT's layout) must
    match unpacking + reference attention in value AND gradient."""

    def _qkv(self, b=2, s=24, heads=4, head_dim=16, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(
            rng.standard_normal((b, s, 3 * heads * head_dim)),
            jnp.float32,
        )

    def test_matches_reference(self):
        from walkai_nos_tpu.ops.attention import (
            _packed_reference,
            flash_attention_packed,
        )

        qkv = self._qkv()
        out = flash_attention_packed(qkv, 4, interpret=True)
        ref = _packed_reference(qkv, 4)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_matches_unpacked_flash_path(self):
        """Same math as the [b, h, s, d] kernel the rest of the stack
        uses: the packed layout is a storage choice, not a model
        change."""
        from walkai_nos_tpu.ops.attention import (
            _packed_unpack,
            flash_attention,
            flash_attention_packed,
        )

        qkv = self._qkv(seed=1)
        out = flash_attention_packed(qkv, 4, interpret=True)
        q, k, v = _packed_unpack(qkv, 4)
        o = flash_attention(q, k, v, interpret=True)
        b, s, _ = qkv.shape
        ref = o.transpose(0, 2, 1, 3).reshape(b, s, -1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_grad_matches_reference(self):
        from walkai_nos_tpu.ops.attention import (
            _packed_reference,
            flash_attention_packed,
        )

        qkv = self._qkv(seed=2)
        w = jnp.asarray(
            np.random.default_rng(3).standard_normal((2, 24, 64)),
            jnp.float32,
        )

        def loss_packed(qkv):
            return jnp.sum(
                w * flash_attention_packed(qkv, 4, interpret=True) ** 2
            )

        def loss_ref(qkv):
            return jnp.sum(w * _packed_reference(qkv, 4) ** 2)

        gp = jax.grad(loss_packed)(qkv)
        gr = jax.grad(loss_ref)(qkv)
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gr), atol=1e-4
        )

    def test_bad_minor_dim_raises(self):
        from walkai_nos_tpu.ops.attention import flash_attention_packed

        with pytest.raises(ValueError, match="3 \\* num_heads"):
            flash_attention_packed(
                jnp.zeros((1, 8, 100)), 4, interpret=True
            )


class TestFlashPaddedDispatch:
    """Untiled non-causal sequences go through the zero-pad + kv-mask
    kernel path (the ViT's 296-token serving shape), not the XLA
    fallback — exact against the reference, forward and backward."""

    def _qkv(self, sq=296, sk=296, b=1, h=2, d=64, seed=5):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, h, sq, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, h, sk, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, h, sk, d)), jnp.float32)
        return q, k, v

    def test_vit_serving_shape_matches_reference(self):
        q, k, v = self._qkv()
        out = attn.flash_attention(q, k, v, interpret=True)
        ref = attn.attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_cross_length_padding(self):
        # sq and sk pad to different block multiples.
        q, k, v = self._qkv(sq=100, sk=296)
        out = attn.flash_attention(
            q, k, v, block_q=64, block_k=128, interpret=True
        )
        ref = attn.attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_padded_gradients_match_reference(self):
        q, k, v = self._qkv(sq=296, sk=296, h=1)

        def flash_loss(q, k, v):
            return jnp.sum(
                attn.flash_attention(
                    q, k, v, block_q=128, block_k=64, interpret=True
                ) ** 2
            )

        def ref_loss(q, k, v):
            return jnp.sum(attn.attention_reference(q, k, v) ** 2)

        g = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for got, want, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-4, err_msg=name
            )

    def test_causal_untiled_still_falls_back(self):
        q, k, v = self._qkv(sq=100, sk=100)
        out = attn.flash_attention(q, k, v, causal=True, interpret=True)
        ref = attn.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_padded_gradients_finite_at_extreme_logits(self):
        """lse can go below ~-88 when every real key is strongly
        anti-aligned with q; the backward's recomputed exp(0 - lse)
        over the padded tail would overflow to inf (NaN via inf * 0)
        without the kv_len mask in the backward kernels."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(
            30.0 * rng.standard_normal((1, 1, 296, 64)), jnp.float32
        )
        k = -q  # scores ~ -|q|^2 * scale: deeply negative lse rows

        def loss(q, k):
            return jnp.sum(
                attn.flash_attention(
                    q, k, k, block_q=128, block_k=64, interpret=True
                )
            )

        gq, gk = jax.grad(loss, argnums=(0, 1))(q, k)
        assert bool(jnp.all(jnp.isfinite(gq)))
        assert bool(jnp.all(jnp.isfinite(gk)))


class TestGqaDecodeAttention:
    """Blocked grouped-query decode kernel vs the (repeat-KV) XLA
    reference, interpret mode; grouping semantics pinned explicitly."""

    def _qkv(self, b=4, h=8, kvh=2, s=256, d=64, seed=0,
             dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((b, h, d)), dtype)
        k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
        v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), dtype)
        return q, k, v

    @pytest.mark.parametrize("index", [0, 100, 255])
    @pytest.mark.parametrize("kvh", [1, 2, 4])
    def test_matches_reference(self, index, kvh):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(kvh=kvh)
        out = da.decode_attention(
            q, k, v, jnp.int32(index), interpret=True
        )
        ref = da.decode_attention_reference(q, k, v, jnp.int32(index))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_block_size_covers_odd_batch(self):
        """b*kvh = 6 exercises a non-16/8 block divisor."""
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(b=3, kvh=2)
        out = da.decode_attention(q, k, v, jnp.int32(77), interpret=True)
        ref = da.decode_attention_reference(q, k, v, jnp.int32(77))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_query_head_reads_its_kv_group(self):
        """Query head i must attend to KV head i // group: make KV head
        1 radically different from head 0 and check the output halves
        match per-group single-head references."""
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(b=2, h=4, kvh=2, seed=3)
        out = da.decode_attention(q, k, v, jnp.int32(200), interpret=True)
        for g in range(2):  # group size = 2
            ref_g = da.decode_attention_reference(
                q[:, 2 * g : 2 * g + 2],
                k[:, g : g + 1], v[:, g : g + 1],
                jnp.int32(200),
            )
            np.testing.assert_allclose(
                np.asarray(out[:, 2 * g : 2 * g + 2]),
                np.asarray(ref_g), atol=2e-5,
            )

    def test_mask_hides_future_cache_rows(self):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(seed=1)
        poisoned_k = k.at[:, :, 100:].set(1e9)
        poisoned_v = v.at[:, :, 100:].set(1e9)
        out = da.decode_attention(
            q, poisoned_k, poisoned_v, jnp.int32(99), interpret=True
        )
        clean = da.decode_attention(q, k, v, jnp.int32(99), interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(clean), atol=2e-5
        )

    def test_untiled_cache_falls_back(self):
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(s=100)
        out = da.decode_attention(q, k, v, jnp.int32(50))
        ref = da.decode_attention_reference(q, k, v, jnp.int32(50))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    @pytest.mark.parametrize("kvh", [1, 2, 8])
    def test_per_row_index_matches_reference(self, kvh):
        """Ragged decoding (continuous batching) hands the kernel a
        [batch] index vector — each cell masks at its own row's
        position."""
        from walkai_nos_tpu.ops import decode_attention as da

        q, k, v = self._qkv(b=4, kvh=kvh)
        idx = jnp.asarray([0, 17, 128, 255], jnp.int32)
        out = da.decode_attention(q, k, v, idx, interpret=True)
        ref = da.decode_attention_reference(q, k, v, idx)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )
        # And the reference itself: row i must equal a scalar-index
        # reference on that row alone.
        for i, ix in enumerate([0, 17, 128, 255]):
            solo = da.decode_attention_reference(
                q[i : i + 1], k[i : i + 1], v[i : i + 1], jnp.int32(ix)
            )
            np.testing.assert_allclose(
                np.asarray(ref[i : i + 1]), np.asarray(solo), atol=2e-5
            )

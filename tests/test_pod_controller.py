"""Pod-controller (partitioner core loop) suite — the
`mig_controller.go:35-213` behaviors, table-driven."""

from __future__ import annotations

import time

from tests.factory import NodeBuilder, PodBuilder
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.partitioner.pod_controller import (
    BatchingPodReconciler,
    PodController,
    make_node_event_mapper,
)
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Request
from walkai_nos_tpu.tpu.annotations import parse_node_annotations


def tiling_node(name: str, annotations: dict | None = None) -> dict:
    builder = (
        NodeBuilder(name)
        .with_tpu_model("tpu-v5-lite-podslice", "2x4")
        .with_tiling_enabled()
    )
    for k, v in (annotations or {}).items():
        builder.with_annotation(k, v)
    return builder.build()


def pending_slice_pod(name: str, profile: str) -> dict:
    return (
        PodBuilder(name)
        .with_slice_request(profile)
        .unschedulable()
        .build()
    )


def spec_of(kube, node_name: str):
    _, spec = parse_node_annotations(
        objects.annotations(kube.get("Node", node_name))
    )
    return {(s.mesh_index, s.profile): s.quantity for s in spec}


class TestShouldConsider:
    def setup_method(self):
        self.kube = FakeKubeClient()
        self.kube.create("Node", tiling_node("n1"))
        self.ctrl = PodController(self.kube, plan_id_fn=lambda: "plan-t")

    def _reconcile(self, pod):
        self.kube.create("Pod", pod)
        self.ctrl.reconcile(
            Request(name=objects.name(pod), namespace="default")
        )

    def test_pending_unschedulable_pod_triggers_retile(self):
        self._reconcile(pending_slice_pod("p1", "2x2"))
        assert spec_of(self.kube, "n1")  # spec written

    def test_scheduled_pod_ignored(self):
        pod = (
            PodBuilder("p1").with_slice_request("2x2").scheduled_on("n1").build()
        )
        self._reconcile(pod)
        assert not spec_of(self.kube, "n1")

    def test_pending_but_not_unschedulable_ignored(self):
        # Not yet marked Unschedulable by the scheduler: retiling can't be
        # known to help (`pod.go:38-55` semantics).
        pod = PodBuilder("p1").with_slice_request("2x2").build()
        self._reconcile(pod)
        assert not spec_of(self.kube, "n1")

    def test_daemonset_pod_ignored(self):
        pod = (
            PodBuilder("p1")
            .with_slice_request("2x2")
            .unschedulable()
            .owned_by("DaemonSet")
            .build()
        )
        self._reconcile(pod)
        assert not spec_of(self.kube, "n1")

    def test_non_slice_pod_ignored(self):
        pod = (
            PodBuilder("p1")
            .with_container("main", {"cpu": "1"})
            .unschedulable()
            .build()
        )
        self._reconcile(pod)
        assert not spec_of(self.kube, "n1")

    def test_missing_pod_is_noop(self):
        self.ctrl.reconcile(Request(name="ghost", namespace="default"))
        assert not spec_of(self.kube, "n1")


class TestProfileAlreadyPresent:
    def test_no_retile_when_a_node_already_provides(self):
        kube = FakeKubeClient()
        kube.create(
            "Node",
            tiling_node(
                "n1",
                {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-free": "1"
                },
            ),
        )
        kube.create("Node", tiling_node("n2"))
        ctrl = PodController(kube, plan_id_fn=lambda: "plan-t")
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        ctrl.reconcile(Request(name="p1", namespace="default"))
        # n1 already exposes a free 2x2: neither node gets a new spec
        # (`mig_controller.go:121-144`).
        assert not spec_of(kube, "n1")
        assert not spec_of(kube, "n2")


class TestFirstFit:
    def test_first_node_that_fits_wins(self):
        kube = FakeKubeClient()
        # n1 is full with used slices (no room); n2 is empty.
        kube.create(
            "Node",
            tiling_node(
                "n1",
                {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1-used": "8"
                },
            ),
        )
        kube.create("Node", tiling_node("n2"))
        ctrl = PodController(kube, plan_id_fn=lambda: "plan-t")
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        ctrl.reconcile(Request(name="p1", namespace="default"))
        assert not spec_of(kube, "n1")
        spec = spec_of(kube, "n2")
        assert spec.get((0, "2x2"), 0) >= 1

    def test_plan_id_written(self):
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = PodController(kube, plan_id_fn=lambda: "plan-42")
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        ctrl.reconcile(Request(name="p1", namespace="default"))
        annos = objects.annotations(kube.get("Node", "n1"))
        assert annos[constants.ANNOTATION_PARTITIONING_PLAN] == "plan-42"

    def test_used_slices_survive_retile(self):
        kube = FakeKubeClient()
        kube.create(
            "Node",
            tiling_node(
                "n1",
                {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-used": "1",
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-free": "1",
                },
            ),
        )
        ctrl = PodController(kube, plan_id_fn=lambda: "plan-t")
        kube.create("Pod", pending_slice_pod("p1", "1x2"))
        ctrl.reconcile(Request(name="p1", namespace="default"))
        spec = spec_of(kube, "n1")
        # the used 2x2 must still be in the target geometry
        assert spec.get((0, "2x2"), 0) >= 1
        assert spec.get((0, "1x2"), 0) >= 1


class TestBatchReconcile:
    """The upstream batch-window path (`gpu_partitioner_config.yaml:23-33`):
    one planning pass, one spec write per node, no double-claiming."""

    def _controller(self, kube):
        self.plan_ids: list[str] = []

        def plan_id():
            self.plan_ids.append(f"plan-{len(self.plan_ids)}")
            return self.plan_ids[-1]

        return PodController(kube, plan_id_fn=plan_id)

    def test_burst_coalesces_to_one_write_per_node(self):
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        kube.create("Pod", pending_slice_pod("p2", "2x2"))
        ctrl.reconcile_batch(
            [
                Request(name="p1", namespace="default"),
                Request(name="p2", namespace="default"),
            ]
        )
        # Both pods fit the 2x4 host; the node's spec is written exactly
        # once (one plan cycle for the agent, not two).
        assert len(self.plan_ids) == 1
        assert spec_of(kube, "n1").get((0, "2x2"), 0) >= 2

    def test_no_double_claim_of_one_free_slice(self):
        kube = FakeKubeClient()
        kube.create(
            "Node",
            tiling_node(
                "n1",
                {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-free": "1"
                },
            ),
        )
        ctrl = self._controller(kube)
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        kube.create("Pod", pending_slice_pod("p2", "2x2"))
        ctrl.reconcile_batch(
            [
                Request(name="p1", namespace="default"),
                Request(name="p2", namespace="default"),
            ]
        )
        # The free 2x2 serves one pod; the second must trigger a retile
        # providing another — the single-pod path would have skipped both
        # as "already available".
        assert spec_of(kube, "n1").get((0, "2x2"), 0) >= 2

    def test_duplicate_requests_planned_once(self):
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        ctrl.reconcile_batch(
            [Request(name="p1", namespace="default")] * 3
        )
        assert len(self.plan_ids) == 1

    def test_batching_reconciler_end_to_end(self):
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        batching = BatchingPodReconciler(ctrl, timeout=5.0, idle=0.05)
        batching.start()
        try:
            kube.create("Pod", pending_slice_pod("p1", "2x2"))
            batching.reconcile(Request(name="p1", namespace="default"))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if spec_of(kube, "n1"):
                    break
                time.sleep(0.02)
            assert spec_of(kube, "n1").get((0, "2x2"), 0) >= 1
        finally:
            batching.stop()

    def test_drain_mode_plans_without_idle_wait(self):
        """idle == 0 (the production default): the worker plans the
        moment it is free — a lone pod must not wait for any window.
        The generous assertion bound is scheduling noise, not a window:
        the old idle-window default (0.2 s) made this take >= 0.2 s."""
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        batching = BatchingPodReconciler(ctrl, timeout=5.0, idle=0.0)
        batching.start()
        try:
            kube.create("Pod", pending_slice_pod("p1", "2x2"))
            t0 = time.monotonic()
            batching.reconcile(Request(name="p1", namespace="default"))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if spec_of(kube, "n1"):
                    break
                time.sleep(0.002)
            planned_after = time.monotonic() - t0
            assert spec_of(kube, "n1").get((0, "2x2"), 0) >= 1
            assert planned_after < 0.15, planned_after
        finally:
            batching.stop()

    def test_drain_mode_coalesces_queued_requests(self):
        """Requests that queue while the planner is busy land in ONE
        reconcile_batch call (the natural coalescing that replaces the
        idle window)."""
        import threading

        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        batches: list[int] = []
        release = threading.Event()
        orig = ctrl.reconcile_batch

        def slow_batch(requests):
            batches.append(len(requests))
            if len(batches) == 1:
                release.wait(timeout=5.0)
            orig(requests)

        ctrl.reconcile_batch = slow_batch
        batching = BatchingPodReconciler(ctrl, timeout=5.0, idle=0.0)
        batching.start()
        try:
            for name in ("p1", "p2", "p3"):
                kube.create("Pod", pending_slice_pod(name, "1x1"))
            batching.reconcile(Request(name="p1", namespace="default"))
            deadline = time.monotonic() + 2.0
            while not batches and time.monotonic() < deadline:
                time.sleep(0.002)
            # Planner is now blocked inside batch 1; these two queue up.
            batching.reconcile(Request(name="p2", namespace="default"))
            batching.reconcile(Request(name="p3", namespace="default"))
            release.set()
            deadline = time.monotonic() + 5.0
            while len(batches) < 2 and time.monotonic() < deadline:
                time.sleep(0.002)
            assert batches[0] == 1
            assert batches[1] == 2  # coalesced into one batch
        finally:
            batching.stop()

    def test_restart_after_stop(self):
        # Leader-election cycles stop and restart the manager; the batch
        # worker must come back with it.
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        ctrl = self._controller(kube)
        batching = BatchingPodReconciler(ctrl, timeout=5.0, idle=0.05)
        batching.start()
        batching.stop()
        batching.start()
        try:
            kube.create("Pod", pending_slice_pod("p1", "2x2"))
            batching.reconcile(Request(name="p1", namespace="default"))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if spec_of(kube, "n1"):
                    break
                time.sleep(0.02)
            assert spec_of(kube, "n1")
        finally:
            batching.stop()


class TestNodeEventMapper:
    def test_reenqueues_pending_slice_pods(self):
        kube = FakeKubeClient()
        kube.create("Pod", pending_slice_pod("p1", "2x2"))
        kube.create(  # scheduled: must not be re-enqueued
            "Pod",
            PodBuilder("p2").with_slice_request("2x2").scheduled_on("n1").build(),
        )
        kube.create(  # no slice request: must not be re-enqueued
            "Pod",
            PodBuilder("p3")
            .with_container("main", {"cpu": "1"})
            .unschedulable()
            .build(),
        )
        enqueued: list[Request] = []
        mapper = make_node_event_mapper(kube, enqueued.append)
        mapper(Request(name="n1"))
        # The pending pod, plus the planner wake-up sentinel (empty
        # name) that drives the stranded-pool-share sweep even when
        # nothing is pending.
        assert [(r.name, r.namespace) for r in enqueued] == [
            ("p1", "default"), ("", ""),
        ]

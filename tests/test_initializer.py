"""NodeInitializer suite (`internal/partitioning/mig/initializer.go:40-79`
analogue cases)."""

from __future__ import annotations

from tests.test_pod_controller import spec_of, tiling_node
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.partitioning.initializer import NodeInitializer


class TestNodeInitializer:
    def test_fresh_node_gets_fewest_slices_tiling(self):
        kube = FakeKubeClient()
        kube.create("Node", tiling_node("n1"))
        NodeInitializer(kube).init_node_partitioning(kube.get("Node", "n1"))
        # v5e 2x4 host: the coarsest tiling is one whole-host 2x4 slice.
        assert spec_of(kube, "n1") == {(0, "2x4"): 1}
        annos = objects.annotations(kube.get("Node", "n1"))
        assert constants.ANNOTATION_PARTITIONING_PLAN in annos

    def test_already_initialized_node_untouched(self):
        kube = FakeKubeClient()
        node = tiling_node(
            "n1",
            {f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-free": "2"},
        )
        kube.create("Node", node)
        NodeInitializer(kube).init_node_partitioning(kube.get("Node", "n1"))
        # Mesh already has a geometry (from status): no spec rewrite.
        assert not spec_of(kube, "n1")

    def test_non_tpu_node_ignored(self):
        kube = FakeKubeClient()
        kube.create("Node", {"metadata": {"name": "cpu-node"}})
        NodeInitializer(kube).init_node_partitioning(
            kube.get("Node", "cpu-node")
        )
        assert not spec_of(kube, "cpu-node")

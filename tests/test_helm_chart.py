"""Helm chart structural parity checks (no helm binary in CI).

Validates what `helm template` would catch syntactically — balanced
template actions, parseable values — plus the parity contracts from the
reference chart (helm-charts/nos): every component templated,
per-component knobs, lookup-persisted installation UUID, hook wiring,
namespace validation, NOTES. The kind e2e flow (`hack/kind/e2e.sh`)
renders the chart with real helm when available.
"""

import re
from pathlib import Path

import yaml

CHART = Path(__file__).resolve().parents[1] / "helm-charts" / "walkai-nos-tpu"
TEMPLATES = sorted(CHART.glob("templates/*"))

COMPONENTS = (
    "partitioner",
    "agent",
    "sharingAgent",
    "scheduler",
    "clusterInfoExporter",
)

_OPEN = re.compile(r"\{\{-?\s*(if|range|with|define)\b")
_END = re.compile(r"\{\{-?\s*end\b")


def _values():
    return yaml.safe_load((CHART / "values.yaml").read_text())


class TestTemplateSyntax:
    def test_braces_balanced(self):
        for path in TEMPLATES:
            text = path.read_text()
            assert text.count("{{") == text.count("}}"), path.name

    def test_blocks_balanced(self):
        for path in TEMPLATES:
            text = path.read_text()
            opens = len(_OPEN.findall(text))
            ends = len(_END.findall(text))
            assert opens == ends, (path.name, opens, ends)

    def test_commands_reference_real_modules(self):
        for path in TEMPLATES:
            for mod in re.findall(
                r"walkai_nos_tpu\.cmd\.(\w+)", path.read_text()
            ):
                assert (
                    CHART.parents[1] / "walkai_nos_tpu" / "cmd" / f"{mod}.py"
                ).exists(), (path.name, mod)


class TestValuesParity:
    def test_values_parse(self):
        assert isinstance(_values(), dict)

    def test_per_component_knobs(self):
        """Reference parity: every component exposes the same knob set
        the reference chart does (values.yaml:17-378)."""
        values = _values()
        for component in COMPONENTS:
            cfg = values[component]
            for knob in (
                "enabled",
                "logLevel",
                "image",
                "resources",
                "tolerations",
                "affinity",
                "nodeSelector",
            ):
                assert knob in cfg, (component, knob)
            assert {"repository", "tag", "pullPolicy"} <= set(cfg["image"])

    def test_rbac_proxy_and_telemetry_toggles(self):
        values = _values()
        assert values["kubeRbacProxy"]["enabled"] is True
        assert "shareTelemetry" in values
        assert "allowDefaultNamespace" in values


class TestComponentTemplates:
    def test_every_component_has_a_workload(self):
        text = "".join(p.read_text() for p in TEMPLATES)
        for marker in (
            "walkai_nos_tpu.cmd.tpupartitioner",
            "walkai_nos_tpu.cmd.tpuagent",
            "walkai_nos_tpu.cmd.tpusharingagent",
            "walkai_nos_tpu.cmd.tpuscheduler",
            "walkai_nos_tpu.cmd.clusterinfoexporter",
            "walkai_nos_tpu.cmd.metricsexporter",
        ):
            assert marker in text, marker

    def test_uuid_is_lookup_persisted(self):
        """Reference: configmap_metrics.yaml:3-6 — upgrades must keep the
        installation UUID via `lookup`, not mint a new uuidv4."""
        text = (CHART / "templates" / "configmap_metrics.yaml").read_text()
        assert "uuidv4" in text
        assert 'lookup "v1" "ConfigMap"' in text
        assert "$config_lookup.data.uuid" in text

    def test_metrics_exporter_hook_wiring(self):
        text = (CHART / "templates" / "pod_metrics-exporter.yaml").read_text()
        assert "post-install,post-upgrade" in text
        assert "walkai-nos.metricsConfigMap.name" in text

    def test_validation_fails_default_namespace(self):
        text = (CHART / "templates" / "validation.yaml").read_text()
        assert "allowDefaultNamespace" in text and "fail" in text

    def test_notes_document_node_labeling(self):
        text = (CHART / "templates" / "NOTES.txt").read_text()
        assert "nos.walkai.io/tpu-partitioning=tiling" in text

    def test_metrics_bind_localhost_behind_proxy(self):
        text = (CHART / "templates" / "partitioner.yaml").read_text()
        assert '127.0.0.1:8080' in text  # proxied metrics never exposed raw

    def test_agent_daemonset_contract(self):
        """Same contract test_manifests applies to raw manifests: the
        chart's agent must mount the kubelet sockets and set NODE_NAME."""
        text = (CHART / "templates" / "daemonset_agent.yaml").read_text()
        assert "NODE_NAME" in text
        assert "/var/lib/kubelet/pod-resources" in text
        assert "/var/lib/kubelet/device-plugins" in text
        assert "nos.walkai.io/tpu-partitioning: tiling" in text
        assert "system-node-critical" in text

    def test_monitors_cover_every_scrapable_component(self):
        """Reference ships a prometheus monitor per component
        (config/*/prometheus/monitor.yaml); the chart's monitors.yaml
        must cover the partitioner Service and each agent/scheduler pod,
        scraping through the rbac proxy when it is enabled."""
        text = (CHART / "templates" / "monitors.yaml").read_text()
        assert "monitoring.enabled" in text
        assert "ServiceMonitor" in text and "PodMonitor" in text
        for comp in ('"agent"', '"sharing-agent"', '"scheduler"'):
            assert comp in text, comp
        # rbac-proxied scrape mirrors the reference monitor endpoints
        assert "bearerTokenFile" in text and "insecureSkipVerify: true" in text

    def test_agents_scrapable_behind_proxy(self):
        """Agents bind metrics to localhost and add the proxy sidecar when
        kubeRbacProxy is on (reference: config/migagent/default/
        mig_agent_auth_proxy_patch.yaml)."""
        for name in ("daemonset_agent.yaml", "daemonset_sharing-agent.yaml"):
            text = (CHART / "templates" / name).read_text()
            assert '127.0.0.1:8080' in text, name
            assert "walkai-nos.kubeRbacProxy.container" in text, name

    def test_chart_ships_quota_crds_in_sync_with_deploy(self):
        """helm installs crds/ before templates; the chart copy must
        exist and match the raw-manifest copy byte for byte."""
        chart_crds = CHART / "crds" / "elasticquota.yaml"
        deploy_crds = (
            CHART.parents[1] / "deploy" / "crds" / "elasticquota.yaml"
        )
        assert chart_crds.exists()
        assert chart_crds.read_text() == deploy_crds.read_text()
        names = {
            d["metadata"]["name"]
            for d in yaml.safe_load_all(chart_crds.read_text())
            if d
        }
        assert names == {
            "elasticquotas.nos.walkai.io",
            "compositeelasticquotas.nos.walkai.io",
        }


class TestValuesSweepKnobs:
    """Knobs adopted in the reference values sweep (VALUES_SWEEP.md)."""

    def test_pull_secrets_rendered_in_every_pod_spec(self):
        values = _values()
        assert values["imagePullSecrets"] == []
        pod_templates = [
            "partitioner.yaml",
            "daemonset_agent.yaml",
            "daemonset_sharing-agent.yaml",
            "deployment_scheduler.yaml",
            "deployment_clusterinfoexporter.yaml",
            "pod_metrics-exporter.yaml",
        ]
        for name in pod_templates:
            text = (CHART / "templates" / name).read_text()
            assert ".Values.imagePullSecrets" in text, name

    def test_service_account_annotations_per_component(self):
        values = _values()
        rbac = (CHART / "templates" / "rbac.yaml").read_text()
        for comp in COMPONENTS:
            assert values[comp]["serviceAccountAnnotations"] == {}, comp
            assert f".Values.{comp}.serviceAccountAnnotations" in rbac, comp

    def test_agent_runtime_class_knob(self):
        values = _values()
        for comp, tpl in (
            ("agent", "daemonset_agent.yaml"),
            ("sharingAgent", "daemonset_sharing-agent.yaml"),
        ):
            assert values[comp]["runtimeClassName"] == ""
            text = (CHART / "templates" / tpl).read_text()
            assert f".Values.{comp}.runtimeClassName" in text

    def test_scheduler_extra_args(self):
        assert _values()["scheduler"]["extraArgs"] == []
        text = (CHART / "templates" / "deployment_scheduler.yaml").read_text()
        assert ".Values.scheduler.extraArgs" in text

    def test_fullname_override(self):
        assert _values()["fullnameOverride"] == ""
        helpers = (CHART / "templates" / "_helpers.tpl").read_text()
        assert ".Values.fullnameOverride" in helpers

    def test_sweep_log_exists_and_linked(self):
        assert (CHART / "VALUES_SWEEP.md").is_file()
        assert "VALUES_SWEEP.md" in (CHART / "README.md").read_text()

"""Topology model tests (reference analogue: `pkg/gpu/util_test.go`)."""

import pytest

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.tpu import topology


class TestParseShape:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("2x4", (2, 4)),
            ("1x1", (1, 1)),
            ("2x2x1", (2, 2, 1)),
            ("8", (8,)),
        ],
    )
    def test_valid(self, s, expected):
        assert topology.parse_shape(s) == expected
        assert topology.format_shape(expected) == s

    @pytest.mark.parametrize("s", ["", "2x", "x4", "2x-1", "axb", "2 x 4", "0x2"])
    def test_invalid(self, s):
        with pytest.raises(ValueError):
            topology.parse_shape(s)

    def test_chip_count(self):
        assert topology.shape_chip_count((2, 4)) == 8
        assert topology.shape_chip_count((2, 2, 1)) == 4


class TestGetModel:
    def test_v5e_host(self):
        labels = {constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"}
        model = topology.get_model(labels)
        assert model is not None
        assert model.generation == "v5e"
        assert model.host_mesh == (2, 4)
        assert model.chips_per_host == 8

    def test_explicit_smaller_topology_label(self):
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            constants.LABEL_TPU_TOPOLOGY: "2x2",
        }
        model = topology.get_model(labels)
        assert model.host_mesh == (2, 2)
        assert model.chips_per_host == 4

    def test_multi_host_topology_refused(self):
        # 4x4 is a 2-host v5e slice; partitioning it would split the ICI
        # torus, so the model resolver refuses instead of falling back.
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            constants.LABEL_TPU_TOPOLOGY: "4x4",
        }
        assert topology.get_model(labels) is None
        assert topology.is_multi_host(labels)
        assert topology.get_chip_count(labels) is None

    def test_unknown_model(self):
        assert topology.get_model({constants.LABEL_TPU_ACCELERATOR: "gpu"}) is None
        assert topology.get_model({}) is None

    def test_v4_host(self):
        labels = {constants.LABEL_TPU_ACCELERATOR: "tpu-v4-podslice"}
        model = topology.get_model(labels)
        assert model.host_mesh == (2, 2, 1)
        assert topology.get_chip_count(labels) == 4


class TestMultiHost:
    @pytest.mark.parametrize("topo", ["2x2x2", "2x2x4", "4x4x4", "2x4x4"])
    def test_v5p_multi_host_pools(self, topo):
        # v5p hosts carry 4 chips (2x2x1); any 8-chip-or-larger pool spans
        # hosts and must be scheduled whole.
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
            constants.LABEL_TPU_TOPOLOGY: topo,
        }
        assert topology.is_multi_host(labels)
        assert topology.get_model(labels) is None

    def test_v5p_single_host_pool(self):
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
            constants.LABEL_TPU_TOPOLOGY: "2x2x1",
        }
        assert not topology.is_multi_host(labels)
        assert topology.get_model(labels).host_mesh == (2, 2, 1)

    def test_no_topology_label_is_single_host(self):
        labels = {constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice"}
        assert not topology.is_multi_host(labels)

    def test_malformed_topology_label(self):
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
            constants.LABEL_TPU_TOPOLOGY: "bogus",
        }
        assert not topology.is_multi_host(labels)
        assert topology.get_model(labels) is not None

    def test_non_tpu_node(self):
        assert not topology.is_multi_host({})


class TestNodeControllerMultiHostGuard:
    def test_refuses_and_emits_event(self):
        from walkai_nos_tpu.controllers.partitioner.node_controller import (
            NodeController,
        )
        from walkai_nos_tpu.kube.fake import FakeKubeClient
        from walkai_nos_tpu.kube.runtime import Request

        kube = FakeKubeClient()
        kube.create(
            "Node",
            {
                "metadata": {
                    "name": "tpu-mh",
                    "labels": {
                        constants.LABEL_TPU_PARTITIONING: "tiling",
                        constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
                        constants.LABEL_TPU_TOPOLOGY: "2x2x2",
                    },
                    # Partitioned before the pool was recognized as
                    # multi-host: the guard must clear these.
                    "annotations": {
                        f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x1": "1",
                        constants.ANNOTATION_PARTITIONING_PLAN: "123",
                    },
                },
            },
        )
        ctrl = NodeController(kube)
        ctrl.reconcile(Request(name="tpu-mh"))
        # Not initialized, and stale spec annotations cleared.
        node = kube.get("Node", "tpu-mh")
        annos = (node["metadata"].get("annotations") or {})
        assert not any("spec" in k for k in annos)
        events = kube.list("Event", namespace="default")
        assert len(events) == 1
        assert events[0]["reason"] == "MultiHostTopology"
        # Idempotent across reconciles.
        ctrl.reconcile(Request(name="tpu-mh"))
        assert len(kube.list("Event", namespace="default")) == 1

    def test_transient_event_failure_is_retried(self):
        from walkai_nos_tpu.controllers.partitioner.node_controller import (
            NodeController,
        )
        from walkai_nos_tpu.kube.client import ApiError
        from walkai_nos_tpu.kube.fake import FakeKubeClient
        from walkai_nos_tpu.kube.runtime import Request

        class FlakyEventKube(FakeKubeClient):
            def __init__(self):
                super().__init__()
                self.event_failures = 1

            def create(self, kind, obj, namespace=None):
                if kind == "Event" and self.event_failures > 0:
                    self.event_failures -= 1
                    raise ApiError(500, "transient")
                return super().create(kind, obj, namespace)

        kube = FlakyEventKube()
        kube.create(
            "Node",
            {
                "metadata": {
                    "name": "tpu-mh",
                    "labels": {
                        constants.LABEL_TPU_PARTITIONING: "tiling",
                        constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
                        constants.LABEL_TPU_TOPOLOGY: "2x2x2",
                    },
                },
            },
        )
        ctrl = NodeController(kube)
        ctrl.reconcile(Request(name="tpu-mh"))  # event create fails (500)
        assert kube.list("Event", namespace="default") == []
        ctrl.reconcile(Request(name="tpu-mh"))  # retried, not memoized
        events = kube.list("Event", namespace="default")
        assert [e["reason"] for e in events] == ["MultiHostTopology"]

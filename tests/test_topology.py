"""Topology model tests (reference analogue: `pkg/gpu/util_test.go`)."""

import pytest

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.tpu import topology


class TestParseShape:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("2x4", (2, 4)),
            ("1x1", (1, 1)),
            ("2x2x1", (2, 2, 1)),
            ("8", (8,)),
        ],
    )
    def test_valid(self, s, expected):
        assert topology.parse_shape(s) == expected
        assert topology.format_shape(expected) == s

    @pytest.mark.parametrize("s", ["", "2x", "x4", "2x-1", "axb", "2 x 4", "0x2"])
    def test_invalid(self, s):
        with pytest.raises(ValueError):
            topology.parse_shape(s)

    def test_chip_count(self):
        assert topology.shape_chip_count((2, 4)) == 8
        assert topology.shape_chip_count((2, 2, 1)) == 4


class TestGetModel:
    def test_v5e_host(self):
        labels = {constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice"}
        model = topology.get_model(labels)
        assert model is not None
        assert model.generation == "v5e"
        assert model.host_mesh == (2, 4)
        assert model.chips_per_host == 8

    def test_explicit_smaller_topology_label(self):
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            constants.LABEL_TPU_TOPOLOGY: "2x2",
        }
        model = topology.get_model(labels)
        assert model.host_mesh == (2, 2)
        assert model.chips_per_host == 4

    def test_multi_host_topology_label_falls_back_to_host_mesh(self):
        # 4x4 is a 2-host v5e slice; the per-host mesh stays 2x4.
        labels = {
            constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
            constants.LABEL_TPU_TOPOLOGY: "4x4",
        }
        assert topology.get_model(labels).host_mesh == (2, 4)

    def test_unknown_model(self):
        assert topology.get_model({constants.LABEL_TPU_ACCELERATOR: "gpu"}) is None
        assert topology.get_model({}) is None

    def test_v4_host(self):
        labels = {constants.LABEL_TPU_ACCELERATOR: "tpu-v4-podslice"}
        model = topology.get_model(labels)
        assert model.host_mesh == (2, 2, 1)
        assert topology.get_chip_count(labels) == 4

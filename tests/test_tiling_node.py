"""Node tiling-model tests (reference: `pkg/gpu/mig/node_test.go`, 635 LoC)."""

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.tpu.tiling.node import Node

V5E_LABELS = {
    constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
    constants.LABEL_TPU_TOPOLOGY: "2x4",
    constants.LABEL_TPU_PARTITIONING: "tiling",
}


def make_node(annotations=None, labels=None):
    return Node.from_node("node-1", labels or V5E_LABELS, annotations or {})


class TestFromNode:
    def test_no_tpu_labels(self):
        n = Node.from_node("n", {}, {})
        assert n.model is None
        assert n.meshes == []

    def test_empty_annotations_one_empty_mesh(self):
        n = make_node()
        assert n.model is not None
        assert len(n.meshes) == 1
        assert n.meshes[0].geometry() == {}

    def test_builds_meshes_from_status(self):
        n = make_node(
            {
                "nos.walkai.io/status-tpu-0-2x2-free": "1",
                "nos.walkai.io/status-tpu-0-2x2-used": "1",
            }
        )
        assert n.meshes[0].used == {"2x2": 1}
        assert n.meshes[0].free == {"2x2": 1}

    def test_spec_annotations_ignored_for_state(self):
        n = make_node({"nos.walkai.io/spec-tpu-0-2x2": "2"})
        assert n.meshes[0].geometry() == {}


class TestHasFreeCapacity:
    def test_any_free_slice_counts(self):
        # Reference semantics (`node.go:122-139`): ANY free device counts,
        # regardless of wanted profile — a free slice can be re-tiled.
        n = make_node({"nos.walkai.io/status-tpu-0-2x4-free": "1"})
        assert n.has_free_capacity()

    def test_fully_used_valid_geometry_has_none(self):
        n = make_node({"nos.walkai.io/status-tpu-0-2x2-used": "2"})
        assert not n.has_free_capacity()

    def test_invalid_geometry_counts_as_capacity(self):
        # 1x1:3 is not an allowed geometry (not a full or generated tiling)
        # -> repartitioning could help (`node.go:124-143`).
        n = make_node({"nos.walkai.io/status-tpu-0-1x1-used": "3"})
        assert n.has_free_capacity()

    def test_no_meshes(self):
        n = Node.from_node("n", {}, {})
        assert not n.has_free_capacity()


class TestUpdateGeometryFor:
    def test_empty_node_gets_geometry(self):
        n = make_node()
        assert n.update_geometry_for({"2x2": 2})
        assert n.provides_profiles({"2x2": 2})

    def test_already_provided_no_change(self):
        n = make_node(
            {
                "nos.walkai.io/status-tpu-0-2x2-free": "2",
            }
        )
        assert not n.update_geometry_for({"2x2": 1})

    def test_partial_free_tops_up(self):
        n = make_node(
            {
                "nos.walkai.io/status-tpu-0-2x2-free": "1",
                "nos.walkai.io/status-tpu-0-2x2-used": "1",
            }
        )
        # wants 2, has 1 free: needs 1 more, but geometry already 2x2:2 —
        # no allowed geometry provides 3x 2x2 on 8 chips, so no change.
        assert not n.update_geometry_for({"2x2": 2})

    def test_respects_used(self):
        n = make_node({"nos.walkai.io/status-tpu-0-2x2-used": "1"})
        changed = n.update_geometry_for({"1x1": 4})
        assert changed
        assert n.meshes[0].used == {"2x2": 1}
        assert n.meshes[0].free_count("1x1") == 4

    def test_add_pod_consumes_free(self):
        n = make_node({"nos.walkai.io/status-tpu-0-2x2-free": "2"})
        n.add_pod({"2x2": 1})
        assert n.meshes[0].used == {"2x2": 1}
        assert n.meshes[0].free == {"2x2": 1}

    def test_clone_independent(self):
        n = make_node({"nos.walkai.io/status-tpu-0-2x2-free": "1"})
        c = n.clone()
        c.add_pod({"2x2": 1})
        assert n.meshes[0].used == {}

    def test_geometry_map(self):
        n = make_node({"nos.walkai.io/status-tpu-0-2x4-free": "1"})
        assert n.geometry() == {0: {"2x4": 1}}


class TestReviewRegressions:
    def test_fresh_node_has_capacity(self):
        # A never-partitioned node (empty geometry) must count as having
        # capacity, else pending pods never trigger initial partitioning.
        n = make_node(annotations={})
        assert n.has_free_capacity()

    def test_add_pod_is_atomic(self):
        n = make_node({"nos.walkai.io/status-tpu-0-1x1-free": "1"})
        import pytest as _pytest

        from walkai_nos_tpu.tpu.errors import GenericError

        with _pytest.raises(GenericError):
            n.add_pod({"1x1": 2})
        assert n.meshes[0].used == {}
        assert n.meshes[0].free == {"1x1": 1}

"""End-to-end integration: the §7.3 minimum slice, on the sim harness.

The analogue of the reference's envtest suites
(`internal/controllers/migagent/suite_int_test.go`,
`actuator_int_test.go:64-206`): real controllers, fake boundaries, assert
on node-annotation / pod-scheduling side effects with eventually-semantics.
"""


from tests.helpers import eventually
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.sim import SimCluster
from walkai_nos_tpu.tpu.annotations import parse_node_annotations




class TestEndToEnd:
    def test_node_init_agent_report_pod_schedules(self):
        cluster = SimCluster()
        cluster.add_node("tpu-node-a", mesh=(2, 4))
        with cluster:
            # 1. Node controller initializes the fresh node to the coarsest
            #    tiling (whole-host 2x4).
            def node_initialized():
                node = cluster.kube.get("Node", "tpu-node-a")
                _, spec = parse_node_annotations(objects.annotations(node))
                return any(
                    s.profile == "2x4" and s.quantity == 1 for s in spec
                )

            eventually(node_initialized, msg="node init writes default tiling spec")

            # 2. The agent materializes the slice and reports status.
            def status_reported():
                node = cluster.kube.get("Node", "tpu-node-a")
                status, _ = parse_node_annotations(objects.annotations(node))
                return any(
                    s.profile == "2x4" and s.status.value == "free"
                    for s in status
                )

            eventually(status_reported, msg="agent reports free 2x4")
            node_dev = cluster.nodes["tpu-node-a"].tpudev
            assert [s.profile for s in node_dev.list_slices()] == ["2x4"]

            # 3. A pod requesting a 2x2 (not exposed) goes pending; the
            #    partitioner re-tiles; the pod schedules.
            cluster.create_slice_pod("job-1", "2x2")

            def pod_scheduled():
                pod = cluster.kube.get("Pod", "job-1", "default")
                return objects.pod_is_scheduled(pod)

            eventually(pod_scheduled, msg="pending pod triggers re-tiling and binds")

            pod = cluster.kube.get("Pod", "job-1", "default")
            assert pod["spec"]["nodeName"] == "tpu-node-a"

            # 4. The node's reported status converges to spec, with the 2x2
            #    used by the pod.
            def converged():
                node = cluster.kube.get("Node", "tpu-node-a")
                status, spec = parse_node_annotations(objects.annotations(node))
                used_2x2 = sum(
                    s.quantity
                    for s in status
                    if s.profile == "2x2" and s.status.value == "used"
                )
                return used_2x2 == 1

            eventually(converged, msg="status shows used 2x2")

            # 5. Plan-ID ack: status-partitioning-plan equals the spec plan.
            def plan_acked():
                node = cluster.kube.get("Node", "tpu-node-a")
                ann = objects.annotations(node)
                return (
                    ann.get(constants.ANNOTATION_PARTITIONING_PLAN)
                    is not None
                    and ann.get(constants.ANNOTATION_PARTITIONING_PLAN)
                    == ann.get(constants.ANNOTATION_REPORTED_PARTITIONING_PLAN)
                )

            eventually(plan_acked, msg="reporter acks the plan id")

    def test_second_pod_fits_remaining_capacity(self):
        cluster = SimCluster()
        cluster.add_node("tpu-node-a", mesh=(2, 4))
        with cluster:
            cluster.create_slice_pod("job-1", "2x2")
            cluster.create_slice_pod("job-2", "2x2")

            def both_scheduled():
                pods = [
                    cluster.kube.get("Pod", n, "default")
                    for n in ("job-1", "job-2")
                ]
                return all(objects.pod_is_scheduled(p) for p in pods)

            eventually(both_scheduled, timeout=15, msg="both 2x2 pods bind")

    def test_device_plugin_restarted_on_retile(self):
        cluster = SimCluster()
        cluster.add_node("tpu-node-a", mesh=(2, 4))
        with cluster:
            # wait for initial materialization
            def initial():
                return [
                    s.profile
                    for s in cluster.nodes["tpu-node-a"].tpudev.list_slices()
                ] == ["2x4"]

            eventually(initial, msg="initial whole-host slice")

            # The initial materialization itself restarts the plugin pod;
            # wait until the actuator's apply has fully settled (status
            # reflects the slice) before capturing the pod uid, else the
            # listing races the delete/respawn window.
            def settled():
                node = cluster.kube.get("Node", "tpu-node-a")
                status, _ = parse_node_annotations(objects.annotations(node))
                pods = cluster.kube.list(
                    "Pod",
                    label_selector={
                        constants.DEVICE_PLUGIN_LABEL_KEY:
                            constants.DEVICE_PLUGIN_LABEL_VALUE
                    },
                )
                return len(pods) == 1 and any(
                    s.profile == "2x4" for s in status
                )

            eventually(settled, msg="initial apply settled")
            plugin_before = cluster.kube.list(
                "Pod",
                label_selector={
                    constants.DEVICE_PLUGIN_LABEL_KEY:
                        constants.DEVICE_PLUGIN_LABEL_VALUE
                },
            )
            uid_before = objects.uid(plugin_before[0])

            cluster.create_slice_pod("job-1", "1x2")

            def retiled_and_plugin_restarted():
                pods = cluster.kube.list(
                    "Pod",
                    label_selector={
                        constants.DEVICE_PLUGIN_LABEL_KEY:
                            constants.DEVICE_PLUGIN_LABEL_VALUE
                    },
                )
                return (
                    len(pods) == 1
                    and objects.uid(pods[0]) != uid_before
                )

            eventually(
                retiled_and_plugin_restarted,
                timeout=15,
                msg="device plugin pod replaced after re-tiling",
            )

    def test_multi_node_first_fit(self):
        cluster = SimCluster()
        cluster.add_node("node-a", mesh=(2, 4))
        cluster.add_node("node-b", mesh=(2, 4))
        with cluster:
            # Five 2x2 pods: one host provides at most two -> both nodes used.
            for i in range(4):
                cluster.create_slice_pod(f"job-{i}", "2x2")

            def all_scheduled():
                pods = [
                    cluster.kube.get("Pod", f"job-{i}", "default")
                    for i in range(4)
                ]
                return all(objects.pod_is_scheduled(p) for p in pods)

            eventually(all_scheduled, timeout=20, msg="4x 2x2 across two hosts")
            nodes_used = {
                cluster.kube.get("Pod", f"job-{i}", "default")["spec"]["nodeName"]
                for i in range(4)
            }
            assert nodes_used == {"node-a", "node-b"}

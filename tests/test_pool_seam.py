"""The pool-share consumer seam, driven end to end (VERDICT r3 #6).

Half one: two pool-member hosts serve their pool shares through the
REAL device plugin (gRPC, fake kubelet) with `pool_worker_source`
merging the multi-host worker coordinates into the Allocate env —
asserted field-by-field against the `tpudev/env.py` contract.

Half two: two actual OS processes take those Allocate envs, bootstrap
through `parallel/multihost.py` (`resolve_distributed_config` ->
`initialize_distributed` -> `multihost_mesh`) on a CPU backend, and run
a real collective over the combined 2-host mesh — proving the env the
plugin injects is sufficient for a gang worker to join its slice.
"""

import os
import shutil
import socket
import subprocess
import sys
import tempfile
import textwrap

import grpc
import pytest

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.deviceplugin import SliceDevicePlugin, pool_worker_source
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.protos_gen import deviceplugin_pb2 as dp
from walkai_nos_tpu.resource.fake_kubelet import FakeKubelet
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpudev.env import make_pool_worker_env, make_slice_env
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient

POOL = "pool-a"
POOL_PROFILE = "2x4"  # 8 chips over two (2, 2) hosts
HOST_MESH = (2, 2)


def _member_node(i: int) -> dict:
    return {
        "metadata": {
            "name": f"{POOL}-{i}",
            "labels": {
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: POOL_PROFILE,
                constants.LABEL_TPU_PARTITIONING: "tiling",
                constants.LABEL_TPU_NODEPOOL: POOL,
                constants.LABEL_TPU_WORKER_ID: str(i),
            },
        }
    }


def _pool_share_allocate_env(kube, worker: int) -> dict:
    """One member host's Allocate env for its pool share, through the
    real plugin gRPC surface."""
    tpudev = FakeTpudevClient(mesh=HOST_MESH)
    tpudev.create_slices([Placement(POOL_PROFILE, (0, 0), HOST_MESH)])
    root = tempfile.mkdtemp(prefix="ps-", dir="/tmp")
    kubelet = FakeKubelet(root)
    kubelet.start()
    plugin = SliceDevicePlugin(
        f"walkai.io/tpu-{POOL_PROFILE}",
        None,
        plugin_dir=kubelet.plugin_dir,
        source=pool_worker_source(
            tpudev.list_slices, kube, f"{POOL}-{worker}"
        ),
    )
    plugin.start()
    try:
        plugin.register(kubelet.registration_socket)
        channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
        resp = channel.unary_unary(
            "/v1beta1.DevicePlugin/Allocate",
            request_serializer=dp.AllocateRequest.SerializeToString,
            response_deserializer=dp.AllocateResponse.FromString,
        )(
            dp.AllocateRequest(
                container_requests=[
                    dp.ContainerAllocateRequest(
                        devicesIDs=[f"{POOL_PROFILE}@0-0"]
                    )
                ]
            )
        )
        return dict(resp.container_responses[0].envs)
    finally:
        plugin.stop()
        kubelet.stop()
        shutil.rmtree(root, ignore_errors=True)


class TestPoolShareEnvContract:
    def test_allocate_env_matches_contract_field_by_field(self):
        kube = FakeKubeClient()
        for i in range(2):
            kube.create("Node", _member_node(i))
        hostnames = [f"{POOL}-0", f"{POOL}-1"]
        for worker in range(2):
            got = _pool_share_allocate_env(kube, worker)
            placement = Placement(POOL_PROFILE, (0, 0), HOST_MESH)
            want = {
                **make_slice_env(placement, (0, 1, 2, 3)),
                **make_pool_worker_env(worker, hostnames),
            }
            assert got == want, worker
            # The contract spelled out, so a drift in either helper is
            # caught against the literal wire values:
            assert got["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
            assert got["TPU_PROCESS_BOUNDS"] == "1,1,1"
            assert got["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
            assert got["TPU_SLICE_ID"] == f"{POOL_PROFILE}@0-0"
            assert got["TPU_WORKER_ID"] == str(worker)
            assert got["TPU_WORKER_HOSTNAMES"] == f"{POOL}-0,{POOL}-1"
            assert (
                got["MEGASCALE_COORDINATOR_ADDRESS"] == f"{POOL}-0:8476"
            )

    def test_host_local_slices_untouched(self):
        kube = FakeKubeClient()
        for i in range(2):
            kube.create("Node", _member_node(i))
        tpudev = FakeTpudevClient(mesh=HOST_MESH)
        tpudev.create_slices([Placement("2x2", (0, 0), (2, 2))])
        source = pool_worker_source(
            tpudev.list_slices, kube, f"{POOL}-0"
        )
        (s,) = source()
        assert "TPU_WORKER_ID" not in s.env
        assert "TPU_WORKER_HOSTNAMES" not in s.env

    def test_incomplete_membership_serves_visibility_only(self):
        # A member without a worker-id label: don't guess coordinates.
        kube = FakeKubeClient()
        kube.create("Node", _member_node(0))
        broken = _member_node(1)
        del broken["metadata"]["labels"][constants.LABEL_TPU_WORKER_ID]
        kube.create("Node", broken)
        tpudev = FakeTpudevClient(mesh=HOST_MESH)
        tpudev.create_slices([Placement(POOL_PROFILE, (0, 0), HOST_MESH)])
        source = pool_worker_source(
            tpudev.list_slices, kube, f"{POOL}-0"
        )
        (s,) = source()
        assert "TPU_WORKER_ID" not in s.env
        assert "TPU_VISIBLE_CHIPS" in s.env


_WORKER_SCRIPT = textwrap.dedent(
    """
    import os
    import sys

    import numpy as np

    from walkai_nos_tpu.parallel.mesh import MeshAxes
    from walkai_nos_tpu.parallel.multihost import (
        initialize_distributed,
        multihost_mesh,
        resolve_distributed_config,
    )

    cfg = resolve_distributed_config()
    assert cfg is not None, "allocate env carried no multi-host contract"
    assert cfg.num_processes == 2, cfg
    assert cfg.process_id == int(os.environ["TPU_WORKER_ID"])

    initialize_distributed()
    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2
    assert jax.device_count() == 8  # 4 visible chips per worker

    from jax.experimental import multihost_utils

    ids = multihost_utils.process_allgather(
        np.asarray([cfg.process_id], np.int32)
    )
    assert sorted(np.ravel(ids).tolist()) == [0, 1], ids

    from jax.sharding import NamedSharding, PartitionSpec

    mesh = multihost_mesh(MeshAxes(data=8))
    sharding = NamedSharding(mesh, PartitionSpec("data"))
    x = jax.make_array_from_callback(
        (8,), sharding, lambda idx: np.ones((1,), np.float32)
    )
    total = jax.jit(lambda a: a.sum(), out_shardings=None)(x)
    assert float(total) == 8.0, total
    print("POOL-SEAM-OK", cfg.process_id)

    # The FLAGSHIP serving path over the pool share (VERDICT r4 #8):
    # tensor-parallel llama-family decode — Megatron column/row rules
    # within each host (ICI), data axis across the two hosts (DCN) —
    # compiled and executed with the same device-plugin-injected env.
    from walkai_nos_tpu.models.decode import make_generate_fn
    from walkai_nos_tpu.models.lm import LMConfig, init_lm_state

    llama_cfg = LMConfig(
        vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", mlp="swiglu",
        mlp_dim=128, rope=True, use_bias=False, head_bias=False,
    )
    tp_mesh = multihost_mesh(MeshAxes(model=4, data=2))
    state = init_lm_state(llama_cfg, tp_mesh, jax.random.PRNGKey(3))
    gen = make_generate_fn(llama_cfg, tp_mesh)
    prompt_np = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % 100
    prompt = jax.make_array_from_callback(
        (2, 8),
        NamedSharding(tp_mesh, PartitionSpec()),
        lambda idx: prompt_np[idx],
    )
    out = gen(state.params, prompt, max_new_tokens=4)
    ok = jax.jit(
        lambda t: jnp.all((0 <= t) & (t < llama_cfg.vocab_size))
        & (t.size == 8)
    )(out)
    assert bool(ok), "sharded llama decode over the pool share failed"
    print("POOL-SEAM-LLAMA-OK", cfg.process_id)
    """
)


class TestPoolGangConsumesAllocateEnv:
    def test_two_process_collective_over_combined_mesh(self):
        """Two worker processes bootstrap from their Allocate envs and
        run a collective over the combined mesh."""
        kube = FakeKubeClient()
        for i in range(2):
            kube.create("Node", _member_node(i))
        envs = [_pool_share_allocate_env(kube, w) for w in range(2)]

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        procs = []
        for w, alloc_env in enumerate(envs):
            env = dict(os.environ)
            env.update(alloc_env)
            # The node names in the contract aren't resolvable in the
            # test network; point the coordinator at loopback (a real
            # cluster resolves the worker-0 hostname). Chip visibility
            # maps to the CPU device count so the combined mesh has the
            # gang's true shape.
            env["MEGASCALE_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            n_chips = len(alloc_env["TPU_VISIBLE_CHIPS"].split(","))
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={n_chips}"
            )
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", _WORKER_SCRIPT],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"gang worker hung; partial output: {outs}")
        for w, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {w} failed:\n{out}"
            assert f"POOL-SEAM-OK {w}" in out
            assert f"POOL-SEAM-LLAMA-OK {w}" in out

"""Flagship model + mesh/sharding runtime on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models import (
    VIT_TINY,
    ViTDetector,
    init_train_state,
    make_infer_step,
    make_train_step,
)
from walkai_nos_tpu.parallel import mesh as meshlib
from walkai_nos_tpu.parallel import sharding as shardlib


def _tiny_batch(cfg, b=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "images": jnp.asarray(
            rng.standard_normal((b, cfg.image_size, cfg.image_size, 3)),
            jnp.float32,
        ),
        "labels": jnp.asarray(
            rng.integers(0, cfg.num_classes, (b, cfg.num_det_tokens))
        ),
        "boxes": jnp.asarray(
            rng.uniform(0, 1, (b, cfg.num_det_tokens, 4)), jnp.float32
        ),
    }


class TestMesh:
    def test_build_mesh_factors_axes(self):
        m = meshlib.build_mesh(jax.devices())
        assert m.shape == {
            "pipe": 1, "data": 2, "fsdp": 1,
            "expert": 1, "model": 4, "seq": 1,
        }

    def test_slice_mesh_uses_slice_geometry_for_tp(self):
        m = meshlib.slice_mesh("2x4", jax.devices())
        assert m.shape["model"] == 4 and m.shape["data"] == 2
        m = meshlib.slice_mesh("2x2", jax.devices()[:4])
        assert m.shape["model"] == 2 and m.shape["data"] == 2

    def test_slice_mesh_rejects_wrong_device_count(self):
        with pytest.raises(ValueError, match="devices are visible"):
            meshlib.slice_mesh("2x2", jax.devices())

    def test_explicit_axes_must_match(self):
        with pytest.raises(ValueError, match="need"):
            meshlib.build_mesh(jax.devices(), axes=meshlib.MeshAxes(data=3))


class TestShardingRules:
    def test_tp_rules_cover_transformer_params(self):
        assert shardlib.param_partition_spec("block0/attn/qkv/kernel") == (
            jax.sharding.PartitionSpec("fsdp", "model")
        )
        assert shardlib.param_partition_spec("block0/attn/out_proj/kernel") == (
            jax.sharding.PartitionSpec("model", "fsdp")
        )
        assert shardlib.param_partition_spec("block0/mlp/fc1/kernel") == (
            jax.sharding.PartitionSpec("fsdp", "model")
        )
        assert shardlib.param_partition_spec("norm/scale") == (
            jax.sharding.PartitionSpec()
        )

    def test_shard_params_places_on_mesh(self):
        m = meshlib.build_mesh(jax.devices())
        params = ViTDetector(VIT_TINY).init_params(jax.random.PRNGKey(0))
        sharded = shardlib.shard_params(params, m)
        qkv = sharded["block0"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == jax.sharding.PartitionSpec("fsdp", "model")


class TestModel:
    def test_forward_shapes(self):
        cfg = VIT_TINY
        model = ViTDetector(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        out = model.apply(
            {"params": params},
            jnp.zeros((2, cfg.image_size, cfg.image_size, 3)),
        )
        assert out["logits"].shape == (2, cfg.num_det_tokens, cfg.num_classes)
        assert out["boxes"].shape == (2, cfg.num_det_tokens, 4)
        assert bool(jnp.all((out["boxes"] >= 0) & (out["boxes"] <= 1)))

    def test_train_step_decreases_loss_on_mesh(self):
        cfg = VIT_TINY
        mesh = meshlib.build_mesh(jax.devices())
        state = init_train_state(cfg, mesh, jax.random.PRNGKey(0), lr=1e-3)
        step = make_train_step(cfg, mesh, lr=1e-3)
        batch = _tiny_batch(cfg)
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_infer_step_sharded(self):
        cfg = VIT_TINY
        mesh = meshlib.build_mesh(jax.devices())
        params = shardlib.shard_params(
            ViTDetector(cfg).init_params(jax.random.PRNGKey(0)), mesh
        )
        infer = make_infer_step(cfg, mesh)
        out = infer(params, _tiny_batch(cfg)["images"])
        assert out["logits"].shape[0] == 8


class TestViTRemat:
    def test_remat_matches_stored_activations(self):
        """jax.checkpoint must not change the detector's math: same
        params, same batch -> identical loss and gradients."""
        from dataclasses import replace

        from walkai_nos_tpu.models.train import detection_loss
        from walkai_nos_tpu.models.vit import VIT_TINY, ViTDetector

        batch = _tiny_batch(VIT_TINY, b=2)
        results = []
        for remat in (False, True):
            cfg = replace(VIT_TINY, remat=remat, dtype="float32")
            model = ViTDetector(cfg)
            params = model.init_params(jax.random.PRNGKey(0))

            def loss_fn(p, model=model, cfg=cfg):
                out = model.apply({"params": p}, batch["images"])
                return detection_loss(
                    out, batch, num_classes=cfg.num_classes
                )

            loss, grads = jax.value_and_grad(loss_fn)(params)
            results.append((float(loss), grads))
        assert abs(results[0][0] - results[1][0]) < 1e-6
        for a, b in zip(
            jax.tree_util.tree_leaves(results[0][1]),
            jax.tree_util.tree_leaves(results[1][1]),
        ):
            assert jnp.allclose(a, b, atol=1e-5)

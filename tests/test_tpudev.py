"""tpudev fake + stub + tiling client tests (mock analogue of
`pkg/test/mocks/nvml` usage)."""

import pytest

from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus
from walkai_nos_tpu.tpu.errors import GenericError, NotFoundError
from walkai_nos_tpu.tpu.tiling.client import TilingClient
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient
from walkai_nos_tpu.tpudev.stub import StubTpudevClient


class TestFakeTpudev:
    def test_topology(self):
        t = FakeTpudevClient(mesh=(2, 4)).get_topology()
        assert t.mesh == (2, 4)
        assert t.chip_count == 8
        assert t.chips[0].device_path == "/dev/accel0"

    def test_create_list_delete(self):
        c = FakeTpudevClient(mesh=(2, 4))
        created = c.create_slices(
            [Placement("2x2", (0, 0), (2, 2)), Placement("2x2", (0, 2), (2, 2))]
        )
        assert len(created) == 2
        assert {s.slice_id for s in c.list_slices()} == {"2x2@0-0", "2x2@0-2"}
        assert created[0].env["TPU_VISIBLE_CHIPS"]
        c.delete_slice("2x2@0-0")
        assert len(c.list_slices()) == 1
        with pytest.raises(NotFoundError):
            c.delete_slice("2x2@0-0")

    def test_overlap_rejected(self):
        c = FakeTpudevClient(mesh=(2, 4))
        c.create_slices([Placement("2x2", (0, 0), (2, 2))])
        with pytest.raises(GenericError):
            c.create_slices([Placement("2x2", (0, 1), (2, 2))])

    def test_partial_failure_returns_created(self):
        c = FakeTpudevClient(mesh=(2, 4))
        created = c.create_slices(
            [
                Placement("2x2", (0, 0), (2, 2)),
                Placement("2x2", (0, 0), (2, 2)),  # duplicate fails
            ]
        )
        assert len(created) == 1

    def test_delete_all_except(self):
        c = FakeTpudevClient(mesh=(2, 4))
        c.create_slices(
            [Placement("2x2", (0, 0), (2, 2)), Placement("2x2", (0, 2), (2, 2))]
        )
        deleted = c.delete_all_slices_except({"2x2@0-0"})
        assert deleted == ["2x2@0-2"]
        assert [s.slice_id for s in c.list_slices()] == ["2x2@0-0"]

    def test_mesh_index_lookup(self):
        c = FakeTpudevClient(mesh=(2, 4), mesh_index=0)
        c.create_slices([Placement("2x2", (0, 0), (2, 2))])
        assert c.get_slice_mesh_index("2x2@0-0") == 0
        with pytest.raises(NotFoundError):
            c.get_slice_mesh_index("nope")


class TestStub:
    def test_all_methods_fail(self):
        s = StubTpudevClient()
        for call in [
            s.get_topology,
            s.list_slices,
            lambda: s.get_slice_mesh_index("x"),
            lambda: s.create_slices([]),
            lambda: s.delete_slice("x"),
            lambda: s.delete_all_slices_except(set()),
        ]:
            with pytest.raises(GenericError, match="disabled"):
                call()


class TestTilingClient:
    def _setup(self):
        tpudev = FakeTpudevClient(mesh=(2, 4))
        tpudev.create_slices(
            [Placement("2x2", (0, 0), (2, 2)), Placement("2x2", (0, 2), (2, 2))]
        )
        res = FakeResourceClient()
        res.set_allocatable(
            [
                Device("walkai.io/tpu-2x2", "2x2@0-0", DeviceStatus.UNKNOWN),
                Device("walkai.io/tpu-2x2", "2x2@0-2", DeviceStatus.UNKNOWN),
            ]
        )
        return TilingClient(res, tpudev), res, tpudev

    def test_used_plus_free(self):
        client, res, _ = self._setup()
        res.mark_used("2x2@0-0")
        devices = client.get_tpu_devices()
        by_status = devices.group_by_status()
        assert [d.device_id for d in by_status[DeviceStatus.USED]] == ["2x2@0-0"]
        assert [d.device_id for d in by_status[DeviceStatus.FREE]] == ["2x2@0-2"]

    def test_stale_device_raises_not_found(self):
        client, res, tpudev = self._setup()
        tpudev.delete_slice("2x2@0-2")  # kubelet still advertises it
        with pytest.raises(NotFoundError):
            client.get_tpu_devices()

    def test_delete_all_except(self):
        client, res, tpudev = self._setup()
        from walkai_nos_tpu.tpu.device import DeviceList

        keep = DeviceList(
            [Device("walkai.io/tpu-2x2", "2x2@0-0", DeviceStatus.USED)]
        )
        deleted = client.delete_all_except(keep)
        assert deleted == ["2x2@0-2"]

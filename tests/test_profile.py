"""Profile / resource-name tests (reference: `pkg/gpu/mig/profile.go` tests +
`util.go` helpers)."""

import pytest

from walkai_nos_tpu.tpu.tiling import profile as prof


class TestProfile:
    def test_parse(self):
        p = prof.Profile.parse("2x2")
        assert p.chip_count() == 4
        assert str(p) == "2x2"
        assert p.as_resource_name() == "walkai.io/tpu-2x2"

    def test_ordering(self):
        small = prof.Profile.parse("1x1")
        big = prof.Profile.parse("2x4")
        assert small.smaller_than(big)
        assert sorted([big, small]) == [small, big]

    def test_invalid(self):
        with pytest.raises(ValueError):
            prof.Profile.parse("2x-2")


class TestResourceNames:
    @pytest.mark.parametrize(
        "res,ok",
        [
            ("walkai.io/tpu-2x2", True),
            ("walkai.io/tpu-2x2x1", True),
            ("walkai.io/tpu-8", True),
            ("google.com/tpu", False),
            ("walkai.io/tpu-", False),
            ("walkai.io/tpu-2x", False),
            ("nvidia.com/mig-1g.10gb", False),
            ("walkai.io/tpu-shared-2c", False),
        ],
    )
    def test_is_slice_resource(self, res, ok):
        assert prof.is_slice_resource(res) == ok

    def test_extract(self):
        assert prof.extract_profile_name("walkai.io/tpu-2x4") == "2x4"
        with pytest.raises(ValueError):
            prof.extract_profile_name("google.com/tpu")


class TestGetRequestedProfiles:
    def pod(self, requests, init_requests=None):
        containers = [
            {"resources": {"requests": r, "limits": dict(r)}} for r in requests
        ]
        spec = {"containers": containers}
        if init_requests:
            spec["initContainers"] = [
                {"resources": {"requests": r}} for r in init_requests
            ]
        return {"spec": spec}

    def test_single_container(self):
        p = self.pod([{"walkai.io/tpu-2x2": "1"}])
        assert prof.get_requested_profiles(p) == {"2x2": 1}

    def test_sums_containers(self):
        p = self.pod(
            [{"walkai.io/tpu-2x2": "1"}, {"walkai.io/tpu-2x2": "1", "cpu": "1"}]
        )
        assert prof.get_requested_profiles(p) == {"2x2": 2}

    def test_init_containers_max(self):
        p = self.pod(
            [{"walkai.io/tpu-1x1": "1"}],
            init_requests=[{"walkai.io/tpu-1x1": "3"}],
        )
        assert prof.get_requested_profiles(p) == {"1x1": 3}

    def test_non_slice_resources_ignored(self):
        p = self.pod([{"cpu": "2", "google.com/tpu": "4"}])
        assert prof.get_requested_profiles(p) == {}

    def test_limits_only(self):
        p = {
            "spec": {
                "containers": [
                    {"resources": {"limits": {"walkai.io/tpu-2x4": "1"}}}
                ]
            }
        }
        assert prof.get_requested_profiles(p) == {"2x4": 1}


class TestQuantityRobustness:
    def pod_with(self, qty):
        return {
            "spec": {
                "containers": [
                    {"resources": {"requests": {"walkai.io/tpu-2x2": qty}}}
                ]
            }
        }

    def test_k8s_suffix(self):
        import walkai_nos_tpu.tpu.tiling.profile as prof

        assert prof.get_requested_profiles(self.pod_with("2k")) == {"2x2": 2000}

    @pytest.mark.parametrize("qty", ["1.5", "", "zz", "0", "-1"])
    def test_bad_quantities_skipped(self, qty):
        import walkai_nos_tpu.tpu.tiling.profile as prof

        assert prof.get_requested_profiles(self.pod_with(qty)) == {}

"""Tensor-parallel serving parity + contract tests (tier-1).

The continuous batcher sharded over an emulated `model`-axis mesh
(conftest forces 8 virtual CPU devices — the WALKAI_TP_EMULATE story,
no TPU needed) must be TOKEN-IDENTICAL to the single-device engine
across the serving feature matrix: mixed greedy/sampled ragged
batches (block-boundary-crossing prompts included), spec on/off,
prefix cache on/off, device-resident loop 1/8, plus the
head-replicated arm at tp > kv_heads and the fused-QKV seam. The
host-side books (block tables, pool accounting, prefix trie) must
stay byte-identical — only device arrays shard.

Configs are tiny and fp32 (bf16 ulp noise under the psum's changed
reduction order could flip a near-tied argmax; fp32 keeps the pinned
streams stable for fixed seeds)."""

import dataclasses

import jax
import numpy as np
import pytest

from walkai_nos_tpu.models.lm import (
    DecoderLM,
    LMConfig,
    draft_config,
    expand_kv_heads,
)
from walkai_nos_tpu.models.serve import ContinuousBatcher
from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS

CFG = LMConfig(
    vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
    num_kv_heads=2, max_seq_len=256, dtype="float32",
    norm="rmsnorm", mlp="swiglu", mlp_dim=128, rope=True,
    use_bias=False, head_bias=False,
)

# Mixed ragged prompts: one crossing the 128-row block boundary so
# multi-chunk prefill + a second pool block are exercised, two short.
PROMPTS = [
    list(range(1, 8)),
    [(i % 120) + 1 for i in range(137)],
    [5, 9, 2],
]


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    dcfg = draft_config(CFG)
    return dcfg, DecoderLM(dcfg).init_params(jax.random.PRNGKey(1))


def _serve(params, tp, *, spec_draft=None, **kw):
    """Build an engine at the given tp degree, run the shared
    greedy+sampled workload, return (tokens per request, engine)."""
    cfg = dataclasses.replace(CFG, tp_devices=tp)
    if spec_draft is not None:
        dcfg, dparams = spec_draft
        kw.update(
            spec=True, spec_k=2, draft_cfg=dcfg, draft_params=dparams,
            spec_min_accept=0.0,
        )
    eng = ContinuousBatcher(
        cfg, params, slots=3, cache_len=256, chunk_steps=4,
        prefill_chunk=64, **kw,
    )
    rids = [eng.submit(p, max_new_tokens=12) for p in PROMPTS]
    rids.append(
        eng.submit([2, 4, 6], max_new_tokens=10, temperature=0.9, seed=7)
    )
    out = eng.run()
    return [out[r] for r in rids], eng


# Memoized runs: several tests read the same (tp, arm) pair's tokens
# or engine, and each engine build costs a full serving-program
# compile — cache by arm so the module's compile budget is one build
# per distinct (tp, arm), not per test.
_RUNS: dict = {}


def _serve_cached(params, tp, *, spec_draft=None, **kw):
    key = (tp, spec_draft is not None, tuple(sorted(kw.items())))
    if key not in _RUNS:
        _RUNS[key] = _serve(params, tp, spec_draft=spec_draft, **kw)
    return _RUNS[key]


class TestTpParity:
    """tp=2 (kv-split: kv_heads=2 splits one head per shard) vs tp=1,
    token for token, spec on/off x prefix on/off x loop 1/8 with
    greedy and sampled requests mixed in every batch."""

    def test_plain_engine_tp2(self, params):
        base, _ = _serve_cached(params, 1)
        tp2, eng = _serve_cached(params, 2)
        assert tp2 == base
        assert eng.tp == 2

    def test_loop8_tp2(self, params):
        base, _ = _serve_cached(params, 1, loop_steps=8)
        tp2, eng = _serve_cached(params, 2, loop_steps=8)
        assert tp2 == base
        # The fold actually ran device-resident on the sharded state.
        assert eng.loop_stats()["dispatches"] > 0

    def test_prefix_off_tp2(self, params):
        base, _ = _serve_cached(params, 1, prefix_cache=False)
        tp2, _ = _serve_cached(params, 2, prefix_cache=False)
        assert tp2 == base

    def test_spec_tp2(self, params, draft):
        base, _ = _serve_cached(params, 1, spec_draft=draft)
        tp2, eng = _serve_cached(params, 2, spec_draft=draft)
        assert tp2 == base
        assert eng.spec_stats()["verify_dispatches"] > 0

    def test_spec_loop8_tp2(self, params, draft):
        base, _ = _serve_cached(params, 1, spec_draft=draft, loop_steps=8)
        tp2, _ = _serve_cached(params, 2, spec_draft=draft, loop_steps=8)
        assert tp2 == base

    def test_spec_prefix_off_loop8_tp2(self, params, draft):
        # The remaining corner of the matrix in one arm: spec on,
        # prefix off, loop 8.
        base, _ = _serve(
            params, 1, spec_draft=draft, loop_steps=8,
            prefix_cache=False,
        )
        tp2, _ = _serve(
            params, 2, spec_draft=draft, loop_steps=8,
            prefix_cache=False,
        )
        assert tp2 == base

    def test_fused_qkv_seam_tp2(self, params, monkeypatch):
        # WALKAI_FUSED_QKV=1 routes decode through the fused QKV
        # path's TP wrapper (per-shard weight-section slices,
        # in-shard caller scatter) — off-TPU via the reference
        # composition, the same seam the single-device fused tests
        # use.
        monkeypatch.setenv("WALKAI_FUSED_QKV", "1")
        base, _ = _serve(params, 1)
        tp2, _ = _serve(params, 2)
        assert tp2 == base


class TestHeadReplicated:
    """tp=4 > kv_heads=2: each kv head replicates across the two
    shards whose query heads read it (the engine expands the cache
    and qkv K/V columns to 4 effective heads)."""

    def test_plain_engine_tp4(self, params):
        base, _ = _serve_cached(params, 1)
        tp4, eng = _serve_cached(params, 4)
        assert tp4 == base
        assert eng._tp_kv_layout == "head-replicated"
        # The served cache runs tp effective kv heads.
        assert eng.cfg.kv_heads == 4

    def test_expand_kv_heads_exact_forward(self, params):
        """The expansion itself is exact: the expanded tree under
        num_kv_heads=4 reproduces the original model's full-forward
        logits bit for bit (repeated kv heads hold identical K/V)."""
        import jax.numpy as jnp

        expanded = expand_kv_heads(params, CFG, 4)
        ecfg = dataclasses.replace(CFG, num_kv_heads=4)
        tokens = jnp.asarray([PROMPTS[0]], jnp.int32)
        want = np.asarray(
            jax.jit(DecoderLM(CFG).apply)({"params": params}, tokens)
        )
        got = np.asarray(
            jax.jit(DecoderLM(ecfg).apply)({"params": expanded}, tokens)
        )
        np.testing.assert_array_equal(got, want)


class TestTpConstructor:
    """tp configs that don't divide heads/MLP dims (or fit the
    kv-split / head-replicated rule) fail at LMConfig construction
    with the bad_request-style ValueError taxonomy — never a jit
    crash."""

    def test_tp_must_be_positive(self):
        with pytest.raises(ValueError, match="tp_devices must be >= 1"):
            dataclasses.replace(CFG, tp_devices=0)

    def test_tp_must_divide_heads(self):
        with pytest.raises(ValueError, match="divide num_heads"):
            dataclasses.replace(CFG, tp_devices=3)

    def test_tp_must_divide_mlp_width(self):
        # heads=6 divides tp=6; mlp_dim=64 does not.
        with pytest.raises(ValueError, match="MLP width"):
            LMConfig(
                vocab_size=64, hidden_dim=48, num_layers=1,
                num_heads=6, mlp_dim=64, tp_devices=6,
            )

    def test_tp_must_fit_kv_rule(self):
        # kv_heads=4 with tp=6: neither kv-split (4 % 6) nor
        # head-replicated (6 % 4) — the documented GQA decision has
        # no arm for it.
        with pytest.raises(ValueError, match="kv-split"):
            LMConfig(
                vocab_size=64, hidden_dim=48, num_layers=1,
                num_heads=12, num_kv_heads=4, mlp_dim=48, tp_devices=6,
            )

    def test_engine_requires_paged(self, params):
        with pytest.raises(ValueError, match="requires the paged"):
            ContinuousBatcher(
                dataclasses.replace(CFG, tp_devices=2), params,
                slots=2, cache_len=256, paged=False,
            )

    def test_engine_rejects_tp_past_visible_devices(self, params):
        cfg = dataclasses.replace(
            CFG, num_heads=16, hidden_dim=128, num_kv_heads=16,
            tp_devices=16,
        )
        bigger = DecoderLM(cfg).init_params(jax.random.PRNGKey(2))
        with pytest.raises(ValueError, match="visible devices"):
            ContinuousBatcher(cfg, bigger, slots=2, cache_len=256)


class TestPerShardPool:
    def test_pool_exceeds_one_shard_budget_and_serves(self, params):
        """The acceptance shape: a config whose TOTAL KV footprint
        exceeds what one shard physically backs still serves — each
        chip holds only its kv-head slices of every block, so the
        per-chip pool budget is total/tp while the block ids (and
        every host-side book) stay global."""
        tokens, eng = _serve_cached(params, 2)
        kv = eng.kv_stats()
        assert kv["kv_shard_backing_bytes"] * 2 == kv["kv_backing_bytes"]
        # The whole pool would NOT fit a budget of one shard's bytes.
        assert kv["kv_backing_bytes"] > kv["kv_shard_backing_bytes"]
        # Placement proof, leaf-level: the pool leaves are physically
        # split on the kv-head dim across the mesh.
        pools = [
            leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                eng._state[0]
            )[0]
            if getattr(path[-1], "key", None) in (
                "cached_key", "cached_value"
            )
        ]
        assert pools
        for leaf in pools:
            shard = leaf.sharding.shard_shape(leaf.shape)
            assert shard[1] == leaf.shape[1] // 2
        # And the workload completed through the sharded pools.
        assert all(len(t) > 0 for t in tokens)

    def test_host_books_identical_to_single_device(self, params):
        """The batcher/BlockPool/table surface is byte-identical at
        tp=2: same block ids in the table, same free-list count, same
        residency — the host never learns the device sharded."""
        _, e1 = _serve_cached(params, 1)
        _, e2 = _serve_cached(params, 2)
        np.testing.assert_array_equal(e2._table, e1._table)
        assert len(e2._free_blocks) == len(e1._free_blocks)
        assert e2.kv_stats()["kv_blocks_in_use"] == (
            e1.kv_stats()["kv_blocks_in_use"]
        )


class TestTpStats:
    def test_stats_contract(self, params):
        _, eng = _serve_cached(params, 2)
        st = eng.tp_stats()
        assert st["enabled"] is True
        assert st["tp_devices"] == 2
        assert st["kv_layout"] == "kv-split"
        assert st["kv_heads_served"] == 2
        # Per-shard weight bytes sit strictly between half and all of
        # the tree (embeddings/norms replicate).
        assert (
            st["param_bytes"] / 2 < st["param_shard_bytes"]
            < st["param_bytes"]
        )
        assert st["ici_bytes_per_token"] > 0
        # The registry gauges the engine build set.
        assert eng.obs.tp_devices_gauge.value() == 2
        assert eng.debug_state()["tp"]["tp_devices"] == 2

    def test_single_device_stats_shape(self, params):
        _, eng = _serve_cached(params, 1)
        st = eng.tp_stats()
        assert st["enabled"] is False
        assert st["tp_devices"] == 1
        assert st["kv_layout"] is None
        assert st["ici_bytes_per_token"] == 0
        assert st["param_shard_bytes"] == st["param_bytes"]

    def test_obs_disabled_shape(self, params):
        eng = ContinuousBatcher(
            dataclasses.replace(CFG, tp_devices=2), params, slots=2,
            cache_len=256, chunk_steps=4, obs=False,
        )
        st = eng.tp_stats()
        assert st["obs_disabled"] is True
        assert set(st) >= {
            "enabled", "tp_devices", "kv_layout", "param_shard_bytes",
            "ici_bytes_per_step",
        }


def test_blocks_cross_boundary_residency(params):
    """Lazy decode backing under TP: the boundary-crossing prompt
    grabs its second block mid-flight exactly like the single-device
    engine (pool accounting is host-side and unsharded)."""
    _, eng = _serve_cached(params, 2)
    # All slots released at drain; residency returns to zero in-use
    # (prefix-cached blocks may stay parked).
    kv = eng.kv_stats()
    assert kv["kv_blocks_in_use"] == 0
    assert kv["kv_blocks_free"] + kv["kv_blocks_parked"] == (
        eng.pool_blocks - 1
    )
    assert eng.pool_blocks >= -(-len(PROMPTS[1]) // PAGE_ROWS)

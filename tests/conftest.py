"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Tests never touch TPU hardware (mirrors the reference's rule that no test
touches NVML — SURVEY.md §4). The interpreter may arrive with jax already
imported and pointed at real hardware (sitecustomize + JAX_PLATFORMS=axon
tunneling one TPU chip), so we override via jax.config, which works
post-import as long as no backend is initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the test suite: the suite
# builds hundreds of tiny-model jit programs, and most are IDENTICAL
# HLO (every ContinuousBatcher instance traces its own closures, so
# the in-process jit cache never dedupes them — measured ~7.7 s per
# cold engine build vs ~1.3 s warm). The cache dir is a FIXED
# REPO-LOCAL path (gitignored `.xla_cache/`; was a per-session temp
# dir): entries are content-addressed — the key includes the HLO
# fingerprint, compile options, and the jaxlib version — so
# cross-run reuse is exact-by-construction, and a warm dir takes the
# tier-1 lane's XLA time out of its 870 s budget instead of
# re-paying it every run (a fully cold run no longer fits the
# budget). Repo-local rather than /tmp because the checkout persists
# exactly as long as the test surface it caches for, and a
# world-shared /tmp path created by one user leaves every other
# user's cache WRITES failing EACCES — silently degrading them back
# to cold compiles. Staleness cannot occur (a changed program is a
# different key; a changed jaxlib misses); a stray corrupt entry is
# self-healing (delete the dir). WALKAI_TEST_NO_COMPILE_CACHE=1
# disables (e.g. to time true cold compiles).
if os.environ.get("WALKAI_TEST_NO_COMPILE_CACHE") != "1":
    _jax_cache_dir = os.environ.get(
        "WALKAI_TEST_COMPILE_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), "..", ".xla_cache"),
    )
    os.makedirs(_jax_cache_dir, exist_ok=True)
    # Spawned subprocesses (the demo-server tests) inherit the same
    # cache through the env var jax reads natively, so each server
    # spawn stops recompiling the full serving program set.
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _jax_cache_dir)
    jax.config.update("jax_compilation_cache_dir", _jax_cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        # Bound the persistent dir (LRU eviction): min_compile_time 0
        # caches every program, and a jaxlib upgrade or config change
        # orphans all prior keys — without a cap the dir grows without
        # bound across runs (a full suite writes ~100 MB).
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
    except AttributeError:  # older jaxlib: no cap flag, accept growth
        pass
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except AttributeError:  # older jaxlib: flag absent, default is 0
        pass

import pytest  # noqa: E402

from walkai_nos_tpu.tpu.tiling import known_tilings  # noqa: E402

# Generational-GC taming: the suite keeps thousands of long-lived
# objects alive (module-scoped engines, params trees, jax's global
# caches), and every gen-2 collection SCANS all of them — while jit
# tracing allocates millions of short-lived tracers that keep
# triggering those collections. The effect compounds across the run:
# the SAME serving test measured 9 s early-suite and 87 s at the 80%
# mark (tensor-parallel PR timing work; the inflation hits every
# trace-heavy test, not just new ones). `gc.freeze()` at each module
# boundary moves everything that survived the module into the
# permanent generation, so later collections scan only young objects;
# per-module leak-cycles stay frozen (bounded: one suite's worth) and
# refcounting still frees everything acyclic.
# WALKAI_TEST_NO_GC_FREEZE=1 opts out (e.g. to hunt a leak).
if os.environ.get("WALKAI_TEST_NO_GC_FREEZE") != "1":
    import gc

    @pytest.fixture(autouse=True, scope="module")
    def _gc_freeze_module_survivors():
        yield
        gc.collect()
        gc.freeze()


# Modules dominated by XLA compilation: the control-plane feedback loop
# (`pytest -m "not slow"`) skips them; CI runs both halves. File-level
# because the compile cost is per-module (model init + jit), not per-test.
_SLOW_FILES = {
    "test_bench_serving.py",
    "test_decode.py",
    "test_demo_server.py",
    "test_e2e_apiserver.py",
    "test_quota_chaos.py",
    "test_hf.py",
    "test_lm.py",
    "test_models_parallel.py",
    "test_moe.py",
    "test_multihost.py",
    "test_ops.py",
    "test_pipeline.py",
    "test_pool_seam.py",
    "test_serve.py",
    "test_speculative.py",
    "test_trainer.py",
}


def pytest_collection_modifyitems(items):
    import pathlib

    missing = {
        name for name in _SLOW_FILES
        if not (pathlib.Path(__file__).parent / name).exists()
    }
    if missing:  # a rename must not silently un-mark a heavy module
        raise RuntimeError(
            f"_SLOW_FILES entries without a file: {sorted(missing)}"
        )
    for item in items:
        if item.path.name in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _reset_geometry_overrides():
    yield
    known_tilings.clear_known_geometries()


@pytest.fixture()
def api():
    """In-process HTTP API server (tests/apiserver.py); yields its URL."""
    from tests.apiserver import MiniApiServer

    server = MiniApiServer()
    url = server.start()
    yield url
    server.stop()

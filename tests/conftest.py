"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Tests never touch TPU hardware (mirrors the reference's rule that no test
touches NVML — SURVEY.md §4). The interpreter may arrive with jax already
imported and pointed at real hardware (sitecustomize + JAX_PLATFORMS=axon
tunneling one TPU chip), so we override via jax.config, which works
post-import as long as no backend is initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from walkai_nos_tpu.tpu.tiling import known_tilings  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_geometry_overrides():
    yield
    known_tilings.clear_known_geometries()


@pytest.fixture()
def api():
    """In-process HTTP API server (tests/apiserver.py); yields its URL."""
    from tests.apiserver import MiniApiServer

    server = MiniApiServer()
    url = server.start()
    yield url
    server.stop()

"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Tests never touch TPU hardware (mirrors the reference's rule that no test
touches NVML — SURVEY.md §4). The interpreter may arrive with jax already
imported and pointed at real hardware (sitecustomize + JAX_PLATFORMS=axon
tunneling one TPU chip), so we override via jax.config, which works
post-import as long as no backend is initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from walkai_nos_tpu.tpu.tiling import known_tilings  # noqa: E402


# Modules dominated by XLA compilation: the control-plane feedback loop
# (`pytest -m "not slow"`) skips them; CI runs both halves. File-level
# because the compile cost is per-module (model init + jit), not per-test.
_SLOW_FILES = {
    "test_bench_serving.py",
    "test_decode.py",
    "test_demo_server.py",
    "test_e2e_apiserver.py",
    "test_quota_chaos.py",
    "test_hf.py",
    "test_lm.py",
    "test_models_parallel.py",
    "test_moe.py",
    "test_multihost.py",
    "test_ops.py",
    "test_pipeline.py",
    "test_pool_seam.py",
    "test_serve.py",
    "test_speculative.py",
    "test_trainer.py",
}


def pytest_collection_modifyitems(items):
    import pathlib

    missing = {
        name for name in _SLOW_FILES
        if not (pathlib.Path(__file__).parent / name).exists()
    }
    if missing:  # a rename must not silently un-mark a heavy module
        raise RuntimeError(
            f"_SLOW_FILES entries without a file: {sorted(missing)}"
        )
    for item in items:
        if item.path.name in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _reset_geometry_overrides():
    yield
    known_tilings.clear_known_geometries()


@pytest.fixture()
def api():
    """In-process HTTP API server (tests/apiserver.py); yields its URL."""
    from tests.apiserver import MiniApiServer

    server = MiniApiServer()
    url = server.start()
    yield url
    server.stop()

"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Tests never touch TPU hardware (mirrors the reference's rule that no test
touches NVML — SURVEY.md §4). Must run before any jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from walkai_nos_tpu.tpu.tiling import known_tilings  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_geometry_overrides():
    yield
    known_tilings.clear_known_geometries()

"""Shadow/canary serving plane (router/core.py canary role,
obs/canary.py, cmd/serverouter.py /debug/canary, hack/canary_check.py).

The acceptance contract: a canary-armed router mirrors a sampled
fraction of live submits to a candidate-config replica — same prompt,
knobs, and effective seed — while the primary serves the user and the
mirror stays invisible to routing, admission pressure, and every
scale signal. A same-config canary at 100% mirror must reach the
PROMOTE verdict with zero digest divergences; an injected-weights
canary must REJECT naming the exact first divergent (request, token)
with a readable flight bundle. Both verdicts run end-to-end through
serverouter's HTTP surface, and the `make canary-check` gate is
pinned fast here.
"""

import importlib.util
import json
import pathlib
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from walkai_nos_tpu.obs.anomaly import FlightRecorder
from walkai_nos_tpu.obs.canary import CanaryController
from walkai_nos_tpu.obs.router import RouterObs
from walkai_nos_tpu.router.core import PAGE_ROWS, FleetRouter
from walkai_nos_tpu.sim.replay import (
    classify_config_delta,
    first_divergence,
    load_capture,
)

_ROOT = pathlib.Path(__file__).resolve().parents[1]


class FakeReplica:
    """Scripted replica: submits are recorded with their kwargs,
    records complete on the next step with scripted tokens — the
    no-jax seam the mirror-fork and invisibility tests drive."""

    def __init__(self, name, tokens=(1, 2, 3), queue=0):
        self.name = name
        self.tokens = list(tokens)
        self.submits: list[dict] = []
        self.fail_submits = False
        self._rid = 0
        self._pending = {}
        self._draining = False
        self._queue = queue

    def submit(self, prompt, **kwargs):
        if self._draining:
            raise ValueError("draining")
        if self.fail_submits:
            raise RuntimeError("scripted submit failure")
        rid = self._rid
        self._rid += 1
        self.submits.append(dict(kwargs))
        self._pending[rid] = {
            "tokens": list(self.tokens), "ttft_s": 0.01,
            "wall_s": 0.03, "truncated": False,
            "trace_id": kwargs.get("trace_id"),
        }
        return rid

    def step(self):
        pass

    def drain_done_records(self):
        done, self._pending = self._pending, {}
        return done

    saturation = 0.0
    slo_ok = None
    slots = 4

    @property
    def queue_depth(self):
        return self._queue

    @property
    def has_work(self):
        return bool(self._pending)

    def drain(self):
        self._draining = True

    @property
    def draining(self):
        return self._draining

    def prefix_stats(self):
        return {}


def _template(seed, extra=8):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 64, PAGE_ROWS + extra).astype(np.int32)


def _rec(tokens, *, ttft=0.01, wall=0.05, truncated=False, **extra):
    return {
        "tokens": list(tokens), "ttft_s": ttft, "wall_s": wall,
        "truncated": truncated, **extra,
    }


class TestConfigDeltaClassification:
    """The up-front gate decision: which config deltas demand
    byte-identical tokens and which only allow latency comparison."""

    def _fp(self, cfg=None, engine=None):
        return {"cfg": dict(cfg or {}), "engine": dict(engine or {})}

    def test_identical_configs_are_token_preserving(self):
        fp = self._fp({"hidden_dim": 32}, {"loop_steps": 1})
        out = classify_config_delta(fp, fp)
        assert out["token_preserving"] is True
        assert out["delta"] == []

    def test_engine_knob_delta_keeps_the_gate_armed(self):
        a = self._fp({}, {"loop_steps": 1, "prefill_chunk": 64})
        b = self._fp({}, {"loop_steps": 8, "prefill_chunk": 32})
        out = classify_config_delta(a, b)
        assert out["token_preserving"] is True
        assert len(out["delta"]) == 2

    def test_model_dim_delta_moves_the_function(self):
        a = self._fp({"hidden_dim": 32}, {})
        b = self._fp({"hidden_dim": 64}, {})
        out = classify_config_delta(a, b)
        assert out["token_preserving"] is False
        assert out["moving_fields"] == ["cfg.hidden_dim"]

    def test_int8_sim_preserves_real_int8_moves(self):
        a = self._fp({"kv_dtype": "model"}, {})
        sim = self._fp({"kv_dtype": "int8-sim"}, {})
        real = self._fp({"kv_dtype": "int8"}, {})
        assert classify_config_delta(a, sim)["token_preserving"]
        out = classify_config_delta(a, real)
        assert out["token_preserving"] is False
        assert out["moving_fields"] == ["cfg.kv_dtype"]

    def test_first_divergence_prefix_rule(self):
        assert first_divergence([1, 2, 3], [1, 2, 9]) == 2
        assert first_divergence([1, 2], [1, 2, 3]) == 2  # prefix end


class TestControllerVerdicts:
    """The verdict machine on scripted completion pairs — no router,
    no engines; the controller owns no side effects."""

    def _ctrl(self, **kw):
        kw.setdefault("min_compared", 2)
        kw.setdefault("promote_ticks", 2)
        kw.setdefault("reject_ticks", 2)
        return CanaryController(obs=RouterObs(), **kw)

    def _feed_match(self, ctrl, rid, now=0.0):
        ctrl.on_mirrored()
        ctrl.on_primary(rid, _rec([1, 2, 3]), now)
        ctrl.on_mirror(rid, _rec([1, 2, 3]), now)

    def test_promote_hysteresis(self):
        ctrl = self._ctrl()
        assert ctrl.state == "warming"
        self._feed_match(ctrl, 0)
        assert ctrl.evaluate(1.0) == "warming"  # below min_compared
        self._feed_match(ctrl, 1)
        assert ctrl.evaluate(2.0) == "observing"
        assert ctrl.evaluate(3.0) == "promote"  # 2 clean ticks
        # Terminal verdicts are sticky.
        ctrl.on_primary(2, _rec([7]), 4.0)
        ctrl.on_mirror(2, _rec([8]), 4.0)
        assert ctrl.evaluate(5.0) == "promote"

    def test_digest_divergence_rejects_immediately(self):
        ctrl = self._ctrl()
        self._feed_match(ctrl, 0)
        ctrl.on_primary(5, _rec([1, 2, 3, 4]), 1.0)
        ctrl.on_mirror(5, _rec([1, 2, 9, 4]), 1.0)
        assert ctrl.state == "reject"  # no vote, no window
        assert ctrl.divergences == 1
        first = ctrl.first_divergence
        assert first["rid"] == 5
        assert first["token_index"] == 2
        assert first["expected_token"] == 3
        assert first["got_token"] == 9

    def test_truncated_streams_compare_by_common_prefix(self):
        ctrl = self._ctrl()
        ctrl.on_primary(0, _rec([1, 2, 3], truncated=True), 0.0)
        ctrl.on_mirror(0, _rec([1, 2, 3, 4, 5]), 0.0)
        assert ctrl.state == "warming"  # prefix match, no divergence
        assert ctrl.divergences == 0
        ctrl.on_primary(1, _rec([1, 9], truncated=True), 0.5)
        ctrl.on_mirror(1, _rec([1, 2, 3]), 0.5)
        assert ctrl.state == "reject"  # value moved INSIDE the prefix

    def test_moving_config_delta_gates_latency_only(self):
        ctrl = self._ctrl()
        ctrl.set_fingerprints(
            {"cfg": {"hidden_dim": 32}, "engine": {}},
            {"cfg": {"hidden_dim": 64}, "engine": {}},
        )
        assert ctrl.gate_armed is False
        ctrl.on_primary(0, _rec([1, 2, 3]), 0.0)
        ctrl.on_mirror(0, _rec([9, 9, 9]), 0.0)  # declared drift
        assert ctrl.divergences == 0
        assert ctrl.state == "warming"
        assert ctrl.stats()["gate"] == "latency_only"
        assert ctrl.stats()["config_delta"]["moving_fields"] == [
            "cfg.hidden_dim"
        ]

    def test_sustained_latency_breach_rejects(self):
        ctrl = self._ctrl(latency_budget_pct=20.0, window_s=300.0)
        ctrl.set_fingerprints(
            {"cfg": {"kv_dtype": "model"}, "engine": {}},
            {"cfg": {"kv_dtype": "int8"}, "engine": {}},
        )
        for rid in range(4):
            ctrl.on_mirrored()
            ctrl.on_primary(rid, _rec([1, 2, 3], ttft=0.01), 1.0)
            ctrl.on_mirror(
                rid, _rec([1, 2, 3], ttft=0.5, wall=2.0), 1.0
            )
        assert ctrl.evaluate(2.0) == "observing"
        assert ctrl.evaluate(3.0) == "reject"  # 2 breached ticks
        assert "latency regression" in ctrl.verdict_reason
        delta = ctrl.stats()["latency_delta_pct"]["ttft_p99"]
        assert delta is not None and delta > 20.0

    def test_engine_knob_delta_keeps_digest_gate(self):
        ctrl = self._ctrl()
        ctrl.set_fingerprints(
            {"cfg": {}, "engine": {"loop_steps": 1}},
            {"cfg": {}, "engine": {"loop_steps": 8}},
        )
        assert ctrl.gate_armed is True
        assert ctrl.stats()["gate"] == "digest_exact"

    def test_mirror_error_never_promotes_past(self):
        ctrl = self._ctrl()
        ctrl.on_primary(0, _rec([1, 2, 3]), 0.0)
        ctrl.on_mirror(0, {"error": "boom", "tokens": None}, 0.0)
        assert ctrl.mirror_errors == 1
        assert ctrl.divergences == 0

    def test_divergence_dumps_flight_bundle(self, tmp_path):
        flight = FlightRecorder(str(tmp_path), min_interval_s=0.0)
        ctrl = CanaryController(
            obs=RouterObs(), flight=flight, min_compared=2,
        )
        ctrl.set_fingerprints(
            {"id": "aaa", "cfg": {}, "engine": {}},
            {"id": "bbb", "cfg": {}, "engine": {}},
        )
        ctrl.on_primary(3, _rec([1, 2, 3], trace_id="t-3"), 0.0)
        ctrl.on_mirror(3, _rec([1, 5, 3]), 0.0)
        path = ctrl.first_divergence["bundle_path"]
        assert path and pathlib.Path(path).is_file()
        with open(path) as f:
            bundle = json.load(f)
        payload = bundle.get("payload", bundle)
        assert payload["verdict"]["rid"] == 3
        assert payload["verdict"]["token_index"] == 1
        assert payload["verdict"]["expected_token"] == 2
        assert payload["verdict"]["got_token"] == 5
        assert payload["record"]["primary_tokens"] == [1, 2, 3]
        assert payload["record"]["mirror_tokens"] == [1, 5, 3]
        assert payload["primary_fingerprint"]["id"] == "aaa"
        assert payload["canary_fingerprint"]["id"] == "bbb"


class TestMirrorForkAndInvisibility:
    """The router half on scripted fakes: the fork's sampling, seed
    pinning, and the canary's invisibility to routing, admission
    pressure, and scale signals."""

    def _fleet(self, canary_queue=0, **router_kw):
        a, b = FakeReplica("a"), FakeReplica("b")
        canary = FakeReplica("c", queue=canary_queue)
        router = FleetRouter([a, b], seed=0, **router_kw)
        router.add_replica(canary, role="canary")
        return router, (a, b), canary

    def test_full_mirror_and_primary_records_unchanged(self):
        router, (a, b), canary = self._fleet(canary_mirror=1.0)
        rids = [
            router.submit(_template(i), max_new_tokens=3)
            for i in range(6)
        ]
        router.step()
        records = router.drain_done_records()
        assert sorted(records) == sorted(rids)
        # The user's records come from primaries; every submit also
        # reached the canary, whose routed count never moves.
        assert all(
            records[r]["replica"] in ("a", "b") for r in rids
        )
        assert len(canary.submits) == 6
        assert router.canary_stats()["mirrored"] == 6
        assert router.canary_stats()["compared"] == 6
        assert router.canary_stats()["divergences"] == 0

    def test_sampled_mirror_fraction_is_deterministic(self):
        router, _, canary = self._fleet(canary_mirror=0.5)
        for i in range(10):
            router.submit(_template(i), max_new_tokens=3)
        assert len(canary.submits) == 5  # Bresenham: exactly N*f

    def test_sampled_seed_pinned_for_both_streams(self):
        router, (a, b), canary = self._fleet(canary_mirror=1.0)
        rid = router.submit(
            _template(0), max_new_tokens=3, temperature=1.0,
        )
        primary_kwargs = (a.submits + b.submits)[0]
        mirror_kwargs = canary.submits[0]
        assert primary_kwargs["seed"] == rid % (2 ** 31)
        assert mirror_kwargs["seed"] == primary_kwargs["seed"]
        # Greedy needs no pin: the record stays replayable as-is.
        router.submit(_template(1), max_new_tokens=3)
        assert canary.submits[1].get("seed") is None

    def test_canary_invisible_to_routing_and_signals(self):
        router, (a, b), canary = self._fleet(
            canary_queue=7, canary_mirror=1.0, fleet_refresh_s=0.0,
        )
        assert {h.name for h in router.active_handles()} == {"a", "b"}
        for i in range(8):
            router.submit(_template(i % 2), max_new_tokens=3)
        router.step()
        # Affinity and block-home maps never point at the canary.
        assert all(
            h.name != "c" for h in router._affinity.values()
        )
        assert all(
            h.name != "c" for h in router._block_home.values()
        )
        # Admission pressure: the canary's queue (7) is invisible.
        assert router.obs.queue_depth.value() == 0
        # Capacity signal: 2 active x 4 slots, not 12.
        assert router.obs.fleet_capacity.value() == 8
        # The canary handle took no ROUTED traffic.
        canary_handle = next(
            h for h in router._handles if h.name == "c"
        )
        assert canary_handle.routed == 0

    def test_second_canary_rejected(self):
        router, _, _ = self._fleet()
        with pytest.raises(ValueError, match="already has a canary"):
            router.add_replica(FakeReplica("c2"), role="canary")

    def test_promote_flips_to_serving_role(self):
        router, _, canary = self._fleet(
            canary_mirror=1.0,
            canary_opts={"min_compared": 2, "promote_ticks": 2},
        )
        for i in range(4):
            router.submit(_template(i), max_new_tokens=3)
        for _ in range(4):
            router.step()
            router.drain_done_records()
        stats = router.canary_stats()
        assert stats["state"] == "promote"
        assert stats["armed"] is False
        assert {h.name for h in router.active_handles()} == {
            "a", "b", "c",
        }

    def test_reject_drains_with_canary_reject_reason(self):
        router, _, canary = self._fleet(
            canary_mirror=1.0,
            canary_opts={"min_compared": 2},
        )
        canary.tokens = [9, 9, 9]  # scripted divergence
        router.submit(_template(0), max_new_tokens=3)
        router.step()
        router.drain_done_records()
        router.step()
        stats = router.canary_stats()
        assert stats["state"] == "reject"
        assert "divergence" in stats["verdict_reason"]
        assert canary.draining
        # The drain carries the canary_reject trace reason.
        events = [
            e for e in router.trace.ring.snapshot()
            if e.get("name") == "drain_start"
        ]
        assert any(
            e["args"].get("reason") == "canary_reject" for e in events
        )
        # Once drained the router retires it (no reconciler here).
        router.step()
        assert all(h.name != "c" for h in router._handles)
        # The terminal verdict stays readable after retirement.
        assert router.canary_stats()["state"] == "reject"

    def test_mirror_failure_is_operational_not_divergent(self):
        router, _, canary = self._fleet(canary_mirror=1.0)
        canary.fail_submits = True  # mirror submits now raise
        router.submit(_template(0), max_new_tokens=3)
        router.step()
        records = router.drain_done_records()
        assert len(records) == 1  # the user is never failed
        stats = router.canary_stats()
        assert stats["mirror_errors"] == 1
        assert stats["divergences"] == 0

    def test_mirrored_capture_rows_skipped_by_default(self, tmp_path):
        capture_dir = str(tmp_path / "cap")
        router, _, canary = self._fleet(
            canary_mirror=1.0, capture=capture_dir,
        )
        rids = [
            router.submit(_template(i), max_new_tokens=3)
            for i in range(4)
        ]
        router.step()
        router.drain_done_records()
        cap = load_capture(capture_dir)
        assert [r.rid for r in cap.records] == sorted(rids)
        assert cap.mirrored_skipped == 4
        assert not any(r.mirrored for r in cap.records)
        full = load_capture(capture_dir, include_mirrored=True)
        assert len(full.records) == 8
        assert sum(1 for r in full.records if r.mirrored) == 4
        assert full.mirrored_skipped == 0


import jax  # noqa: E402,F401 — conftest pins the CPU backend

from walkai_nos_tpu.models.lm import DecoderLM, LMConfig  # noqa: E402
from walkai_nos_tpu.sim.trafficbench import (  # noqa: E402
    default_engine_factory,
)

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def fleet():
    """(params, engine-replica factory) — tiny engines sharing one
    weight set, the canary e2e's primaries."""
    _, params, make = default_engine_factory(CFG, None, slots=2)
    return params, make


@pytest.fixture(scope="module")
def injected_make():
    """A factory over DIFFERENT weights under the SAME config — the
    failure class the digest gate exists for."""
    bad = DecoderLM(CFG).init_params(jax.random.PRNGKey(99))
    _, _, make = default_engine_factory(CFG, bad, slots=2)
    return make


def _drive(router, n=10, sampled=True):
    rids = []
    for i in range(n):
        kwargs = {"max_new_tokens": 5}
        if sampled and i % 3 == 0:
            kwargs["temperature"] = 1.0
        rids.append(router.submit(_template(100 + i), **kwargs))
    records = {}
    for _ in range(80):
        router.step()
        records.update(router.drain_done_records())
        if len(records) >= n and not router.has_work:
            break
    for _ in range(6):  # verdict ticks after traffic drains
        router.step()
    return rids, records


class TestCanaryEndToEnd:
    def test_same_config_mirror_token_identity_promotes(
        self, fleet, tmp_path
    ):
        """The acceptance scenario, primary half: a same-config
        canary at 100% mirror sees token-identical streams (greedy
        AND seeded-sampled) and reaches PROMOTE; the capture carries
        the mirrored shadow rows marked and skippable."""
        _, make = fleet
        replicas = [make("p0"), make("p1")]
        canary = make("cny-same")
        for replica in replicas + [canary]:
            replica.warm()
        capture_dir = str(tmp_path / "cap")
        router = FleetRouter(
            replicas, seed=0, canary_mirror=1.0,
            capture=capture_dir,
            canary_opts={"min_compared": 4, "promote_ticks": 2},
        )
        router.add_replica(canary, role="canary")
        rids, records = _drive(router)
        assert sorted(records) == sorted(rids)  # users all served
        stats = router.canary_stats()
        assert stats["state"] == "promote"
        assert stats["gate"] == "digest_exact"
        assert stats["divergences"] == 0
        assert stats["mirrored"] == len(rids)
        assert stats["winning_fingerprint"]
        # The promoted canary now serves.
        assert "cny-same" in {
            h.name for h in router.active_handles()
        }
        # Mirrored rows ride the capture marked, skipped by default.
        cap = load_capture(capture_dir)
        assert cap.mirrored_skipped > 0
        assert not any(r.mirrored for r in cap.records)

    def test_injected_weights_reject_names_first_divergence(
        self, fleet, injected_make, tmp_path
    ):
        """The acceptance scenario, reject half: same config over
        different weights — the delta classifier arms the digest
        gate, the first mirrored pair diverges, and the verdict names
        the exact (request, token) with a readable flight bundle."""
        _, make = fleet
        replicas = [make("q0"), make("q1")]
        canary = injected_make("cny-bad")
        for replica in replicas + [canary]:
            replica.warm()
        router = FleetRouter(
            replicas, seed=0, canary_mirror=1.0,
            flight_dir=str(tmp_path / "flight"),
            canary_opts={"min_compared": 4},
        )
        router.add_replica(canary, role="canary")
        rids, records = _drive(router)
        assert sorted(records) == sorted(rids)  # users unaffected
        stats = router.canary_stats()
        assert stats["state"] == "reject"
        assert stats["gate"] == "digest_exact"  # same config!
        assert stats["divergences"] >= 1
        first = stats["first_divergence"]
        assert first["rid"] in rids
        assert isinstance(first["token_index"], int)
        assert first["expected_token"] != first["got_token"]
        with open(first["bundle_path"]) as f:
            bundle = json.load(f)
        payload = bundle.get("payload", bundle)
        idx = payload["verdict"]["token_index"]
        assert payload["record"]["primary_tokens"][idx] == (
            payload["verdict"]["expected_token"]
        )
        assert payload["record"]["mirror_tokens"][idx] == (
            payload["verdict"]["got_token"]
        )
        assert payload["config_delta"]["token_preserving"] is True


class TestServerouterCanary:
    """The same verdicts through the real binary surface: POST
    /generate drives traffic, GET /debug/canary serves the verdict,
    /metrics federates the canary's engine series."""

    def _serve(self, router):
        from walkai_nos_tpu.cmd.serverouter import (
            RouterDriver,
            RouterServer,
            make_handler,
        )

        driver = RouterDriver(router, idle_tick_s=0.01)
        httpd = RouterServer(
            ("127.0.0.1", 0),
            make_handler(driver, router.obs),
        )
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        return base, driver, httpd

    def _generate(self, base, prompt, n=3):
        body = json.dumps({
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": n,
        }).encode()
        req = urllib.request.Request(
            f"{base}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def _poll_verdict(self, base, terminal, tries=400):
        import time as _time

        payload = None
        for _ in range(tries):
            with urllib.request.urlopen(
                f"{base}/debug/canary", timeout=10
            ) as resp:
                payload = json.loads(resp.read())["canary"]
            if payload["state"] in terminal:
                return payload
            _time.sleep(0.05)
        return payload

    def test_debug_canary_404_when_unarmed(self):
        router = FleetRouter(
            [FakeReplica("a"), FakeReplica("b")], seed=0,
        )
        base, driver, httpd = self._serve(router)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"{base}/debug/canary", timeout=10
                )
            assert err.value.code == 404
        finally:
            httpd.shutdown()
            driver.stop()

    def test_promote_and_reject_through_http(
        self, fleet, injected_make, tmp_path
    ):
        _, make = fleet
        # --- promote arm: same config at 100% mirror -------------
        replicas = [make("s0"), make("s1")]
        canary = make("s-cny")
        for replica in replicas + [canary]:
            replica.warm()
        router = FleetRouter(
            replicas, seed=0, canary_mirror=1.0,
            canary_opts={"min_compared": 3, "promote_ticks": 2},
        )
        router.add_replica(canary, role="canary")
        base, driver, httpd = self._serve(router)
        try:
            for i in range(4):
                out = self._generate(base, _template(200 + i))
                assert out["tokens"]
                assert out["replica"] in ("s0", "s1")
            payload = self._poll_verdict(
                base, ("promote", "reject")
            )
            assert payload["state"] == "promote"
            assert payload["gate"] == "digest_exact"
            assert payload["divergences"] == 0
            assert payload["mirrored"] >= 3
            assert payload["winning_fingerprint"]
            # Federation carries the canary's engine series.
            with urllib.request.urlopen(
                f"{base}/metrics", timeout=30
            ) as resp:
                text = resp.read().decode()
            assert (
                'cb_requests_submitted_total{replica="s-cny"}' in text
            )
            assert "router_canary_mirrored_total" in text
        finally:
            httpd.shutdown()
            driver.stop()
        # --- reject arm: injected weights, same config -----------
        replicas = [make("t0"), make("t1")]
        canary = injected_make("t-cny")
        for replica in replicas + [canary]:
            replica.warm()
        router = FleetRouter(
            replicas, seed=0, canary_mirror=1.0,
            flight_dir=str(tmp_path / "flight"),
            canary_opts={"min_compared": 3},
        )
        router.add_replica(canary, role="canary")
        base, driver, httpd = self._serve(router)
        try:
            for i in range(3):
                out = self._generate(base, _template(300 + i))
                assert out["tokens"]  # the user is never failed
            payload = self._poll_verdict(base, ("reject",))
            assert payload["state"] == "reject"
            first = payload["first_divergence"]
            assert first is not None
            assert isinstance(first["rid"], int)
            assert isinstance(first["token_index"], int)
            assert first["expected_token"] != first["got_token"]
            assert pathlib.Path(first["bundle_path"]).is_file()
        finally:
            httpd.shutdown()
            driver.stop()


class TestServerouterFlags:
    def test_canary_flags_inproc_only(self):
        from walkai_nos_tpu.cmd.serverouter import parse_args

        args = parse_args(
            ["--inproc", "2", "--canary",
             "--canary-override", "loop_steps=4"]
        )
        assert args.canary is True
        assert args.canary_override == [("loop_steps", 4)]
        assert args.canary_mirror == 1.0
        with pytest.raises(SystemExit):
            parse_args(
                ["--replica", "http://x:1", "--canary"]
            )
        with pytest.raises(SystemExit):
            parse_args(["--canary-replica", "http://x:1"])
        with pytest.raises(SystemExit):
            parse_args(["--inproc", "2", "--canary-mirror", "1.5"])


class TestCanaryCheckGate:
    def test_canary_check_is_green(self, fleet):
        """`make canary-check` pinned fast: exit 0 on the same-config
        arm (promote, zero divergences), exit 1 — the designed trip —
        on the injected-divergence arm."""
        spec = importlib.util.spec_from_file_location(
            "walkai_canary_check", _ROOT / "hack" / "canary_check.py"
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules["walkai_canary_check"] = mod
        spec.loader.exec_module(mod)
        assert mod.main([]) == 0
        assert mod.main(["--inject-divergence"]) == 1

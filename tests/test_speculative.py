"""Speculative decoding: exact greedy equivalence for ANY draft.

The defining property of greedy speculative decoding with exact-match
acceptance: the output is identical to greedy decoding of the target
model alone — the draft only buys speed. The tests pin that with a
RANDOM (useless) draft, a shared-architecture (perfect) draft, and
boundary ks, so acceptance paths from a=0 to a=k all execute.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.speculative import make_speculative_generate_fn

TARGET = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=128,
)
DRAFT = LMConfig(
    vocab_size=64, hidden_dim=16, num_layers=1, num_heads=2,
    max_seq_len=128,
)


def _prompt(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, TARGET.vocab_size, (1, n)), jnp.int32)


@pytest.fixture(scope="module")
def params():
    target = DecoderLM(TARGET).init_params(jax.random.PRNGKey(0))
    draft = DecoderLM(DRAFT).init_params(jax.random.PRNGKey(1))
    return target, draft


class TestExactGreedyEquivalence:
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_random_draft_matches_target_greedy(self, params, k):
        """A draft with RANDOM weights (near-zero acceptance) must still
        produce the target's exact greedy sequence."""
        target_params, draft_params = params
        prompt = _prompt()
        reference = make_generate_fn(TARGET)(
            target_params, prompt, max_new_tokens=12
        )
        spec = make_speculative_generate_fn(TARGET, DRAFT, k=k)(
            target_params, draft_params, prompt, max_new_tokens=12
        )
        assert jnp.array_equal(spec, reference), (spec, reference)

    def test_perfect_draft_matches_target_greedy(self, params):
        """Draft == target (same params): every round fully accepts
        (a = k, the bonus-token path) and the output is still exact."""
        target_params, _ = params
        prompt = _prompt(seed=3)
        reference = make_generate_fn(TARGET)(
            target_params, prompt, max_new_tokens=10
        )
        spec = make_speculative_generate_fn(TARGET, TARGET, k=3)(
            target_params, target_params, prompt, max_new_tokens=10
        )
        assert jnp.array_equal(spec, reference), (spec, reference)

    def test_partial_acceptance_matches_target_greedy(self, params):
        """A near-target draft (target weights + small noise) produces
        MIXED acceptance — the dominant real-world case. The histogram
        proves a=0, 0<a<k, and a=k all executed in one run, and the
        output still equals stepwise target greedy exactly (the
        mid-prefix rewind path cannot hide behind the extremes)."""
        target_params, _ = params
        # Deterministic per-leaf noise keys (leaf order is the stable
        # pytree flatten order): the old hash(str(shape)) derivation
        # was salted by PYTHONHASHSEED, so the noise — and the
        # acceptance histogram asserted below — changed per process.
        leaves, treedef = jax.tree_util.tree_flatten(target_params)
        keys = jax.random.split(jax.random.PRNGKey(3), len(leaves))
        noisy_draft = jax.tree_util.tree_unflatten(treedef, [
            leaf + 0.01 * jax.random.normal(key, leaf.shape, leaf.dtype)
            for leaf, key in zip(leaves, keys)
        ])
        prompt = _prompt(seed=3)
        reference = make_generate_fn(TARGET)(
            target_params, prompt, max_new_tokens=24
        )
        gen = make_speculative_generate_fn(
            TARGET, TARGET, k=4, return_stats=True
        )
        spec, stats = gen(
            target_params, noisy_draft, prompt, max_new_tokens=24
        )
        assert jnp.array_equal(spec, reference), (spec, reference)
        hist = np.asarray(stats["acceptance_hist"])
        assert hist[0] > 0, hist       # full-rejection rounds
        assert hist[1:-1].sum() > 0, hist  # PARTIAL acceptance rounds
        assert hist[-1] > 0, hist      # full-acceptance rounds

    def test_gqa_target_verify_through_kernel(self, params):
        """A GQA target's k+1-position verify forward routes through
        the streamed decode kernel (multi-step queries); with the
        kernel forced on in interpret mode the output must still be
        the target's exact greedy sequence."""
        import dataclasses

        cfg = dataclasses.replace(
            TARGET, num_kv_heads=1, max_seq_len=256
        )
        target_params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        prompt = _prompt(seed=7)
        reference = make_generate_fn(cfg)(
            target_params, prompt, max_new_tokens=10
        )
        import os
        from unittest import mock

        with mock.patch.dict(
            os.environ, {"WALKAI_DECODE_INTERPRET": "1"}
        ):
            spec = make_speculative_generate_fn(cfg, DRAFT, k=3)(
                target_params,
                DecoderLM(DRAFT).init_params(jax.random.PRNGKey(1)),
                prompt, max_new_tokens=10,
            )
        assert jnp.array_equal(spec, reference), (spec, reference)

    def test_single_new_token(self, params):
        target_params, draft_params = params
        prompt = _prompt(seed=5)
        reference = make_generate_fn(TARGET)(
            target_params, prompt, max_new_tokens=1
        )
        spec = make_speculative_generate_fn(TARGET, DRAFT, k=2)(
            target_params, draft_params, prompt, max_new_tokens=1
        )
        assert jnp.array_equal(spec, reference)


class TestGuards:
    def test_batch_rejected(self, params):
        target_params, draft_params = params
        gen = make_speculative_generate_fn(TARGET, DRAFT, k=2)
        with pytest.raises(ValueError, match="single-sequence"):
            gen(
                target_params, draft_params,
                jnp.zeros((2, 4), jnp.int32), max_new_tokens=4,
            )

    def test_overflow_rejected(self, params):
        target_params, draft_params = params
        gen = make_speculative_generate_fn(TARGET, DRAFT, k=2)
        with pytest.raises(ValueError, match="exceeds"):
            gen(
                target_params, draft_params,
                jnp.zeros((1, 4), jnp.int32), max_new_tokens=126,
            )

    def test_boundary_generation_allowed(self, params):
        """The guard is exact: prompt + new + k == max_seq_len runs
        (positions stay < the limit); one more is rejected."""
        target_params, draft_params = params
        gen = make_speculative_generate_fn(TARGET, DRAFT, k=2)
        prompt = _prompt(seed=7)
        out = gen(
            target_params, draft_params, prompt,
            max_new_tokens=TARGET.max_seq_len - prompt.shape[1] - 2,
        )
        assert out.shape == (1, TARGET.max_seq_len - prompt.shape[1] - 2)
        with pytest.raises(ValueError, match="exceeds"):
            gen(
                target_params, draft_params, prompt,
                max_new_tokens=TARGET.max_seq_len - prompt.shape[1] - 1,
            )

    def test_vocab_mismatch_rejected(self):
        import dataclasses

        with pytest.raises(ValueError, match="vocabulary"):
            make_speculative_generate_fn(
                TARGET, dataclasses.replace(DRAFT, vocab_size=32)
            )

"""Continuous batching (`models/serve.py`): exact parity with one-shot
greedy generation, under staggered admission, slot reuse, and EOS."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2, max_seq_len=64
)


def _params(cfg=CFG, seed=0):
    return DecoderLM(cfg).init_params(jax.random.PRNGKey(seed))


def _prompts(n, seed=0, lo=2, hi=9):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG.vocab_size, rng.integers(lo, hi))
        .astype(np.int32)
        for _ in range(n)
    ]


def _expected(cfg, params, prompt, max_new):
    gen = make_generate_fn(cfg)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


class TestExactParity:
    def test_concurrent_requests_match_standalone_greedy(self):
        """Five ragged requests sharing 2 slots, all token-identical to
        independent generate() calls — batch composition must never
        leak into any sequence's output."""
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, prompt_bucket=16,
            chunk_steps=4,
        )
        prompts = _prompts(5)
        rids = {
            engine.submit(p, max_new_tokens=7): p for p in prompts
        }
        results = engine.run()
        for rid, p in rids.items():
            assert results[rid] == _expected(CFG, params, p, 7), rid

    def test_staggered_admission(self):
        """Requests submitted while the batch is mid-flight join at a
        chunk boundary and still decode exactly."""
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=4, cache_len=64, chunk_steps=2,
        )
        early = _prompts(2, seed=1)
        late = _prompts(2, seed=2)
        rids = {engine.submit(p, max_new_tokens=9): p for p in early}
        engine.step()
        engine.step()
        rids.update({engine.submit(p, max_new_tokens=5): p for p in late})
        results = engine.run()
        for rid, p in rids.items():
            expect = _expected(
                CFG, params, p, 9 if any(p is e for e in early) else 5
            )
            assert results[rid] == expect, rid

    @pytest.mark.parametrize(
        "variant",
        [
            dict(num_kv_heads=1),
            dict(norm="rmsnorm", mlp="swiglu", rope=True,
                 use_bias=False, head_bias=False, num_kv_heads=1),
        ],
        ids=["gqa", "llama"],
    )
    def test_architecture_variants(self, variant):
        cfg = dataclasses.replace(CFG, **variant)
        params = _params(cfg)
        engine = ContinuousBatcher(cfg, params, slots=2, cache_len=64)
        prompts = _prompts(3, seed=3)
        rids = {engine.submit(p, max_new_tokens=6): p for p in prompts}
        results = engine.run()
        for rid, p in rids.items():
            assert results[rid] == _expected(cfg, params, p, 6), rid


class TestLifecycle:
    def test_eos_frees_the_slot_early(self):
        """A sequence hitting EOS leaves mid-stream; its output stops
        at the EOS token and the freed slot serves the queue."""
        params = _params()
        prompts = _prompts(3, seed=4)
        full = _expected(CFG, params, prompts[0], 8)
        # Force an early exit mid-stream: the chosen eos token's FIRST
        # occurrence must be at its index (a repeat earlier in the
        # sequence would legitimately end the request there instead).
        eos, cut = next(
            (t, i) for i, t in enumerate(full)
            if 1 <= i < 7 and t not in full[:i]
        )
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=64, chunk_steps=2,
        )
        r0 = engine.submit(prompts[0], max_new_tokens=8, eos_id=eos)
        r1 = engine.submit(prompts[1], max_new_tokens=4)
        results = engine.run()
        assert results[r0] == full[:cut + 1]  # truncated at EOS, inclusive
        assert results[r1] == _expected(CFG, params, prompts[1], 4)

    def test_single_token_request(self):
        params = _params()
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=64)
        p = _prompts(1, seed=5)[0]
        rid = engine.submit(p, max_new_tokens=1)
        assert engine.run()[rid] == _expected(CFG, params, p, 1)

    def test_more_requests_than_slots_queue(self):
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, chunk_steps=3,
        )
        prompts = _prompts(7, seed=6)
        rids = {engine.submit(p, max_new_tokens=5): p for p in prompts}
        results = engine.run()
        assert len(results) == 7
        for rid, p in rids.items():
            assert results[rid] == _expected(CFG, params, p, 5), rid


class TestGuards:
    @pytest.mark.parametrize("paged", [True, False], ids=["paged", "dense"])
    def test_over_bucket_prompt_served_not_rejected(self, paged):
        """Prompts longer than `prompt_bucket` are served — the paged
        lane streams them in chunks; dense mode picks the smallest
        power-of-two bucket that fits — and stay token-identical to
        standalone greedy generation."""
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=64, prompt_bucket=8,
            paged=paged,
        )
        prompt = _prompts(1, seed=20, lo=13, hi=14)[0]  # 13 > bucket 8
        rid = engine.submit(prompt, max_new_tokens=4)
        assert engine.run()[rid] == _expected(CFG, params, prompt, 4)

    def test_cache_overflow_rejected(self):
        engine = ContinuousBatcher(CFG, _params(), slots=1, cache_len=32)
        with pytest.raises(ValueError, match="cache_len"):
            engine.submit(np.arange(4), max_new_tokens=40)

    def test_empty_prompt_rejected(self):
        engine = ContinuousBatcher(CFG, _params(), slots=1, cache_len=32)
        with pytest.raises(ValueError, match="empty"):
            engine.submit(np.array([], np.int32), max_new_tokens=2)

    def test_prompt_bucket_exceeding_cache_rejected(self):
        with pytest.raises(ValueError, match="prompt_bucket"):
            ContinuousBatcher(
                CFG, _params(), slots=1, cache_len=32, prompt_bucket=64
            )


class TestPerRequestSampling:
    def test_mixed_batch_keeps_greedy_exact(self):
        """A sampling co-tenant must not perturb greedy slots."""
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, chunk_steps=2,
        )
        prompts = _prompts(3, seed=8)
        greedy_rids = {
            engine.submit(p, max_new_tokens=6): p for p in prompts[:2]
        }
        sampled = engine.submit(
            prompts[2], max_new_tokens=6, temperature=1.0, seed=11
        )
        results = engine.run()
        for rid, p in greedy_rids.items():
            assert results[rid] == _expected(CFG, params, p, 6), rid
        toks = results[sampled]
        assert len(toks) == 6
        assert all(0 <= t < CFG.vocab_size for t in toks)

    def test_sampling_deterministic_across_batch_compositions(self):
        """(prompt, knobs, seed) fully determines the output — the
        per-slot key schedule makes sampling independent of co-tenants,
        slot index, and admission timing."""
        params = _params()
        target = _prompts(1, seed=9)[0]

        def run_with_cotenants(n_cotenants, slots):
            engine = ContinuousBatcher(
                CFG, params, slots=slots, cache_len=64, chunk_steps=3,
            )
            for p in _prompts(n_cotenants, seed=10):
                engine.submit(p, max_new_tokens=8, temperature=0.7)
            rid = engine.submit(
                target, max_new_tokens=8, temperature=0.9, top_k=16,
                top_p=0.95, seed=123,
            )
            return engine.run()[rid]

        a = run_with_cotenants(0, slots=1)
        b = run_with_cotenants(3, slots=4)
        assert a == b
        assert len(a) == 8

    def test_top_k_one_collapses_to_greedy(self):
        params = _params()
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=64)
        p = _prompts(1, seed=12)[0]
        rid = engine.submit(
            p, max_new_tokens=6, temperature=1.0, top_k=1, seed=5
        )
        assert engine.run()[rid] == _expected(CFG, params, p, 6)

    def test_bad_knobs_rejected(self):
        engine = ContinuousBatcher(CFG, _params(), slots=1, cache_len=64)
        with pytest.raises(ValueError, match="temperature"):
            engine.submit([1], max_new_tokens=2, temperature=-1.0)
        with pytest.raises(ValueError, match="top_p"):
            engine.submit([1], max_new_tokens=2, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            engine.submit([1], max_new_tokens=2, top_k=-2)


class TestLatencyTelemetry:
    def test_drain_latencies_one_sample_per_request(self):
        params = _params()
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=64, chunk_steps=2,
        )
        for p in _prompts(5, seed=13):
            engine.submit(p, max_new_tokens=4)
        engine.run()
        lat = engine.drain_latencies()
        assert len(lat) == 5
        assert all(t > 0 for t in lat)
        assert engine.drain_latencies() == []  # drained means drained

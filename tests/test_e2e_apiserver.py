"""Full-stack e2e over a real HTTP API server — the envtest-grade suite.

The reference proves its controllers against a real kube-apiserver via
envtest (`internal/controllers/migagent/suite_int_test.go:33-163`). Here
the same §7.3 scenario the FakeKubeClient e2e runs
(`tests/test_integration_e2e.py`) is exercised with the REAL
`RestKubeClient` wire path — HTTP watch framing, cluster-wide collection
routes, JSON merge patches, the pods/binding subresource — against the
in-process `MiniApiServer` (`tests/apiserver.py`): node init → agent
actuation in the fake tpudev → status report → pending 2x2 pod →
re-tile → bind.
"""

from __future__ import annotations

from tests.helpers import eventually
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.rest import RestKubeClient
from walkai_nos_tpu.sim.harness import SimCluster
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.device import DeviceStatus


class TestE2EOverApiServer:
    def test_init_report_retile_bind(self, api):
        kube = RestKubeClient(server=api)
        sim = SimCluster(report_interval=0.1, kube=kube)
        sim.add_node("host-a", mesh=(2, 4))
        with sim:
            # (a) NodeController initializes the node with the default
            # fewest-slices tiling over real HTTP patches.
            def initialized():
                node = kube.get("Node", "host-a")
                _, spec = parse_node_annotations(objects.annotations(node))
                return any(
                    s.profile == "2x4" and s.quantity == 1 for s in spec
                )

            eventually(initialized, timeout=30.0, msg="node init (spec 2x4)")

            # (b) the agent actuates and the reporter writes status
            # annotations + the plan ack.
            def reported():
                node = kube.get("Node", "host-a")
                status, _ = parse_node_annotations(objects.annotations(node))
                annos = objects.annotations(node)
                return (
                    any(s.profile == "2x4" for s in status)
                    and constants.ANNOTATION_REPORTED_PARTITIONING_PLAN
                    in annos
                )

            eventually(reported, timeout=30.0, msg="status report + plan ack")

            # (c) a pending 2x2 pod triggers a re-tile and gets bound via
            # the pods/binding subresource.
            sim.create_slice_pod("job-1", "2x2")

            def bound():
                pod = kube.get("Pod", "job-1", "default")
                return (pod.get("spec") or {}).get("nodeName") == "host-a"

            eventually(bound, timeout=30.0, msg="pod bound after retile")

            # (d) the node's status annotations converge to the used slice.
            def status_used():
                node = kube.get("Node", "host-a")
                status, _ = parse_node_annotations(objects.annotations(node))
                return any(
                    s.profile == "2x2"
                    and s.status == DeviceStatus.USED
                    and s.quantity >= 1
                    for s in status
                )

            eventually(status_used, timeout=30.0, msg="status 2x2 used")

    def test_multi_host_pool_gang_over_http(self, api):
        """Pool lifecycle over the REAL wire path: a 2-host v5p pool
        initializes to the whole-pool share under one coordinated plan,
        each member's agent actuates its share, and a 2-pod gang binds
        one pod per host via pods/binding."""
        kube = RestKubeClient(server=api)
        sim = SimCluster(report_interval=0.1, kube=kube)
        sim.add_pool("pool-w", n_hosts=2)
        with sim:
            def shares_reported():
                for i in range(2):
                    node = kube.get("Node", f"pool-w-{i}")
                    status, spec = parse_node_annotations(
                        objects.annotations(node)
                    )
                    if not any(
                        s.profile == "2x2x2" and s.quantity == 1
                        for s in spec
                    ):
                        return False
                    if not any(
                        s.profile == "2x2x2"
                        and s.status == DeviceStatus.FREE
                        for s in status
                    ):
                        return False
                return True

            eventually(
                shares_reported, timeout=30.0,
                msg="pool members init + report free shares over HTTP",
            )

            sim.create_slice_pod("gang-0", "2x2x2")
            sim.create_slice_pod("gang-1", "2x2x2")

            def gang_bound():
                hosts = set()
                for name in ("gang-0", "gang-1"):
                    pod = kube.get("Pod", name, "default")
                    node = (pod.get("spec") or {}).get("nodeName")
                    if not node:
                        return False
                    hosts.add(node)
                return hosts == {"pool-w-0", "pool-w-1"}

            eventually(
                gang_bound, timeout=30.0,
                msg="gang binds one pod per member host",
            )

    def test_second_pod_lands_on_remaining_capacity(self, api):
        kube = RestKubeClient(server=api)
        sim = SimCluster(report_interval=0.1, kube=kube)
        sim.add_node("host-a", mesh=(2, 4))
        with sim:
            sim.create_slice_pod("job-1", "2x2")
            sim.create_slice_pod("job-2", "2x2")

            def both_bound():
                pods = [
                    kube.get("Pod", n, "default") for n in ("job-1", "job-2")
                ]
                return all(
                    (p.get("spec") or {}).get("nodeName") == "host-a"
                    for p in pods
                )

            eventually(both_bound, timeout=30.0, msg="both 2x2 pods bound")

    def test_quota_scheduler_binds_and_labels_over_http(self, api):
        """The restored ERQ capability over the real wire path: CRD routes
        (/apis/nos.walkai.io/v1alpha1/elasticquotas), the /status
        subresource, the pods/binding subresource, and the capacity
        labeler's merge patches."""
        from tests.factory import NodeBuilder, PodBuilder
        from walkai_nos_tpu.cmd.tpuscheduler import SCHEDULER_NAME, build_manager
        from walkai_nos_tpu.quota.labeler import IN_QUOTA, LABEL_CAPACITY

        kube = RestKubeClient(server=api)
        kube.create(
            "Node",
            NodeBuilder("host-a")
            .with_allocatable("walkai.io/tpu-2x2", "2")
            .build(),
        )
        kube.create(
            "ElasticQuota",
            {
                "metadata": {"name": "team-a", "namespace": "default"},
                "spec": {"min": {constants.RESOURCE_TPU_CHIPS: "4"}},
            },
            namespace="default",
        )
        pod = PodBuilder("q-pod").with_slice_request("2x2").build()
        pod["spec"]["schedulerName"] = SCHEDULER_NAME
        kube.create("Pod", pod)
        manager = build_manager(kube)
        manager.start()
        try:
            def bound():
                pod = kube.get("Pod", "q-pod", "default")
                return (pod.get("spec") or {}).get("nodeName") == "host-a"

            eventually(bound, timeout=30.0, msg="quota pod bound over HTTP")

            # kubelet's role: the pod runs, so quota usage counts it.
            kube.patch_status(
                "Pod", "q-pod", {"status": {"phase": "Running"}}, "default"
            )

            def labeled_and_counted():
                pod = kube.get("Pod", "q-pod", "default")
                label = objects.labels(pod).get(LABEL_CAPACITY)
                quota = kube.get("ElasticQuota", "team-a", "default")
                used = ((quota.get("status") or {}).get("used") or {}).get(
                    constants.RESOURCE_TPU_CHIPS
                )
                return label == IN_QUOTA and str(used) == "4"

            eventually(
                labeled_and_counted,
                timeout=30.0,
                msg="capacity label + quota status over HTTP",
            )
        finally:
            manager.stop()

    def test_sharing_loop_over_http(self, api):
        """Dynamic sharing (the restored MPS-analogue planning loop) over
        the real wire path: plan -> advertise -> bind -> report."""
        kube = RestKubeClient(server=api)
        sim = SimCluster(report_interval=0.1, kube=kube)
        sim.add_sharing_node("share-host", mesh=(2, 4))
        with sim:
            sim.create_shared_pod("share-job", "2c")

            def bound():
                pod = kube.get("Pod", "share-job", "default")
                return (pod.get("spec") or {}).get("nodeName") == "share-host"

            eventually(bound, timeout=30.0, msg="shared pod bound over HTTP")

            def status_used():
                node = kube.get("Node", "share-host")
                status, _ = parse_node_annotations(objects.annotations(node))
                return any(
                    s.profile == "2c" and s.status == DeviceStatus.USED
                    for s in status
                )

            eventually(
                status_used, timeout=30.0, msg="share status used over HTTP"
            )

    def test_multi_host_node_refused_over_http(self, api):
        kube = RestKubeClient(server=api)
        sim = SimCluster(report_interval=0.1, kube=kube)
        with sim:
            kube.create(
                "Node",
                {
                    "metadata": {
                        "name": "host-mh",
                        "labels": {
                            constants.LABEL_TPU_ACCELERATOR: "tpu-v5p-slice",
                            constants.LABEL_TPU_TOPOLOGY: "2x2x2",
                            constants.LABEL_TPU_PARTITIONING: "tiling",
                        },
                        "annotations": {
                            f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x1": "1",
                        },
                    },
                },
            )

            def refused():
                node = kube.get("Node", "host-mh")
                annos = objects.annotations(node)
                if any(
                    k.startswith(constants.ANNOTATION_TPU_SPEC_PREFIX)
                    for k in annos
                ):
                    return False
                events = kube.list("Event", namespace="default")
                return any(
                    e.get("reason") == "MultiHostTopology" for e in events
                )

            eventually(refused, timeout=30.0, msg="multi-host refusal event + cleanup")

"""Actuator failure-path suite — the envtest-actuator-case analogue
(`internal/controllers/migagent/actuator_int_test.go:64-206` plus the
rollback/staleness logic of `actuator.go:75-296`)."""

from __future__ import annotations

import pytest

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent.actuator import Actuator
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Request
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.device import Device, DeviceStatus
from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpu.tiling.client import TilingClient
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient

NODE = "host-a"


class RecordingPlugin:
    """DevicePluginClient stand-in that records restarts."""

    def __init__(self) -> None:
        self.restarts = 0

    def restart(self, node_name: str) -> None:
        self.restarts += 1


class FailingCreateTpudev(FakeTpudevClient):
    """Fails create_slices a configurable number of times, then behaves."""

    def __init__(self, mesh=(2, 4), fail_times: int = 1) -> None:
        super().__init__(mesh=mesh)
        self.fail_times = fail_times
        self.create_calls = 0

    def create_slices(self, placements):
        self.create_calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise GenericError("injected create failure")
        return super().create_slices(placements)


def advertise(resources: FakeResourceClient, tpudev) -> None:
    """What the device plugin does: one allocatable device per slice."""
    resources.set_allocatable(
        [
            Device(
                resource_name=constants.RESOURCE_TPU_SLICE_PREFIX + s.profile,
                device_id=s.slice_id,
                status=DeviceStatus.UNKNOWN,
                mesh_index=s.mesh_index,
            )
            for s in tpudev.list_slices()
        ]
    )


def build(tpudev, spec_annotations: dict, reported: bool = True):
    kube = FakeKubeClient()
    kube.create(
        "Node",
        {"metadata": {"name": NODE, "annotations": dict(spec_annotations)}},
    )
    resources = FakeResourceClient()
    advertise(resources, tpudev)
    shared = SharedState()
    if reported:
        shared.on_report_done()
    plugin = RecordingPlugin()
    actuator = Actuator(
        kube, TilingClient(resources, tpudev), plugin, shared, NODE
    )
    return actuator, kube, resources, plugin, shared


SPEC_2X2 = {f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2": "2"}


class TestActuatorFailurePaths:
    def test_rollback_recreates_deleted_on_failed_create(self):
        # Host holds one free 2x4 slice; spec wants 2x 2x2, so the plan is
        # delete-the-free-2x4 + create-two-2x2. Creation fails -> the
        # deleted 2x4 must be rolled back (`actuator.go:287-296`).
        tpudev = FailingCreateTpudev(fail_times=1)
        FakeTpudevClient.create_slices(  # seed without tripping the failure
            tpudev, [Placement("2x4", (0, 0), (2, 4))]
        )
        actuator, *_ = build(tpudev, SPEC_2X2)
        with pytest.raises(GenericError):
            actuator.reconcile(Request(name=NODE))
        slices = tpudev.list_slices()
        assert [s.profile for s in slices] == ["2x4"], (
            "deleted free slice must be re-created after the failed create"
        )

    def test_successful_apply_restarts_plugin_once(self):
        tpudev = FakeTpudevClient()
        actuator, _, _, plugin, shared = build(tpudev, SPEC_2X2)
        actuator.reconcile(Request(name=NODE))
        assert sorted(s.profile for s in tpudev.list_slices()) == [
            "2x2",
            "2x2",
        ]
        assert plugin.restarts == 1
        # apply consumed the report latch (`shared.go:43-48`)
        assert not shared.at_least_one_report_since_last_apply()

    def test_gated_until_reporter_has_reported(self):
        tpudev = FakeTpudevClient()
        actuator, *_ = build(tpudev, SPEC_2X2, reported=False)
        result = actuator.reconcile(Request(name=NODE))
        assert result.requeue_after == 1.0
        assert tpudev.list_slices() == []  # nothing actuated

    def test_same_plan_and_status_not_reapplied(self):
        # After an apply, reconciling again with unchanged (plan, status)
        # must be a no-op even though spec != status annotations
        # (`actuator.go:113-116` dedup).
        tpudev = FailingCreateTpudev(fail_times=0)
        spec = dict(SPEC_2X2)
        spec[constants.ANNOTATION_PARTITIONING_PLAN] = "plan-1"
        actuator, _, _, plugin, shared = build(tpudev, spec)
        actuator.reconcile(Request(name=NODE))
        first_calls = tpudev.create_calls
        shared.on_report_done()  # reporter ran, but status annos unchanged
        actuator.reconcile(Request(name=NODE))
        assert tpudev.create_calls == first_calls
        assert plugin.restarts == 1

    def test_stale_kubelet_device_restarts_plugin(self):
        # kubelet advertises a device tpudev doesn't know -> restart the
        # plugin instead of failing (`actuator.go:135-138`).
        tpudev = FakeTpudevClient()
        actuator, _, resources, plugin, _ = build(tpudev, SPEC_2X2)
        resources.set_allocatable(
            [
                Device(
                    resource_name=constants.RESOURCE_TPU_SLICE_PREFIX + "2x2",
                    device_id="ghost-slice",
                    status=DeviceStatus.UNKNOWN,
                    mesh_index=0,
                )
            ]
        )
        result = actuator.reconcile(Request(name=NODE))
        assert plugin.restarts == 1
        assert result.requeue_after == 1.0
        assert tpudev.list_slices() == []

    def test_unadvertised_slice_restarts_plugin(self):
        # Symmetric staleness: tpudev holds a slice the kubelet does NOT
        # advertise (crash between create and plugin re-registration).
        tpudev = FakeTpudevClient()
        actuator, _, resources, plugin, _ = build(tpudev, SPEC_2X2)
        # materialized but not advertised
        tpudev.create_slices([Placement("2x2", (0, 0), (2, 2))])
        result = actuator.reconcile(Request(name=NODE))
        assert plugin.restarts == 1
        assert result.requeue_after == 1.0

    def test_used_slices_never_deleted(self):
        # Spec asks for a full-host 2x4, but a used 2x2 pins the mesh: the
        # apply must fail placement rather than delete the used slice.
        tpudev = FakeTpudevClient()
        tpudev.create_slices([Placement("2x2", (0, 0), (2, 2))])
        actuator, _, resources, _, _ = build(
            tpudev,
            {f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x4": "1"},
        )
        resources.mark_used(tpudev.list_slices()[0].slice_id)
        with pytest.raises(GenericError):
            actuator.reconcile(Request(name=NODE))
        assert [s.profile for s in tpudev.list_slices()] == ["2x2"]

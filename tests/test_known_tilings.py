"""Allowed-tilings generation + override tests.

Reference analogue: `pkg/gpu/mig/known_config_test.go`,
`allowed_geometries_test.go`.
"""

import pytest

from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.partitioning import (
    get_fewest_slices_geometry,
    geometry_total_slices,
)
from walkai_nos_tpu.tpu.tiling import known_tilings

V5E = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]
V4 = topology.KNOWN_MODELS["tpu-v4-podslice"]


class TestCandidateShapes:
    def test_v5e_2x4(self):
        shapes = known_tilings.candidate_shapes((2, 4))
        names = {known_tilings.canonical_profile(s) for s in shapes}
        # GKE v5e single-host shapes exactly.
        assert names == {"1x1", "1x2", "1x4", "2x2", "2x4"}

    def test_power_of_two_only(self):
        shapes = known_tilings.candidate_shapes((2, 4))
        for s in shapes:
            n = topology.shape_chip_count(s)
            assert n & (n - 1) == 0

    def test_v4_2x2x1(self):
        names = {
            known_tilings.canonical_profile(s)
            for s in known_tilings.candidate_shapes((2, 2, 1))
        }
        assert names == {"1x1x1", "1x1x2", "1x2x2"}


class TestGenerateTilings:
    def test_v5e_contains_expected_geometries(self):
        geoms = known_tilings.get_allowed_geometries(V5E)
        as_sets = [tuple(sorted(g.items())) for g in geoms]
        for expected in [
            {"2x4": 1},
            {"2x2": 2},
            {"1x4": 2},
            {"1x1": 8},
            {"2x2": 1, "1x2": 2},
            {"1x2": 4},
            {"2x2": 1, "1x1": 4},
        ]:
            assert tuple(sorted(expected.items())) in as_sets, expected

    def test_every_geometry_covers_all_chips(self):
        for g in known_tilings.get_allowed_geometries(V5E):
            total = sum(
                topology.shape_chip_count(topology.parse_shape(p)) * q
                for p, q in g.items()
            )
            assert total == 8

    def test_fewest_slices_is_whole_host(self):
        geoms = known_tilings.get_allowed_geometries(V5E)
        assert get_fewest_slices_geometry(geoms) == {"2x4": 1}

    def test_deterministic(self):
        a = known_tilings.get_allowed_geometries(V5E)
        b = known_tilings.get_allowed_geometries(V5E)
        assert a == b

    def test_v4_geometries(self):
        geoms = known_tilings.get_allowed_geometries(V4)
        as_sets = [tuple(sorted(g.items())) for g in geoms]
        assert tuple(sorted({"1x2x2": 1}.items())) in as_sets
        assert tuple(sorted({"1x1x1": 4}.items())) in as_sets
        assert tuple(sorted({"1x1x2": 2}.items())) in as_sets


class TestOverrides:
    def test_set_and_clear(self):
        known_tilings.set_known_geometries(
            {"tpu-v5-lite-podslice": [{"2x4": 1}, {"2x2": 2}]}
        )
        assert known_tilings.get_allowed_geometries(V5E) == [
            {"2x4": 1},
            {"2x2": 2},
        ]
        known_tilings.clear_known_geometries()
        assert len(known_tilings.get_allowed_geometries(V5E)) > 2

    def test_unknown_model_rejected(self):
        with pytest.raises(ValueError, match="unknown TPU model"):
            known_tilings.set_known_geometries({"nope": [{"2x4": 1}]})

    def test_too_many_chips_rejected(self):
        with pytest.raises(ValueError, match="chips"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"2x4": 2}]}
            )

    def test_non_canonical_profile_rejected(self):
        with pytest.raises(ValueError, match="canonical"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"4x2": 1}]}
            )

    def test_non_pow2_rejected(self):
        with pytest.raises(ValueError):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"1x3": 1}]}
            )

    def test_unplaceable_rejected(self):
        # 1x4 + 2x2 = 8 chips but cannot tile a 2x4 grid together: the 1x4
        # row leaves a 1x4 strip that a 2x2 cannot occupy.
        with pytest.raises(ValueError, match="not placeable"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"1x4": 1, "2x2": 1}]}
            )

    def test_all_or_nothing(self):
        with pytest.raises(ValueError):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"2x4": 1}, {"2x4": 2}]}
            )
        # first (valid) entry must not have been installed
        assert geometry_total_slices(
            get_fewest_slices_geometry(
                known_tilings.get_allowed_geometries(V5E)
            )
        ) == 1

    def test_partial_geometry_allowed_in_override(self):
        # Operators may expose fewer chips than the host has.
        known_tilings.set_known_geometries(
            {"tpu-v5-lite-podslice": [{"2x2": 1}]}
        )
        assert known_tilings.get_allowed_geometries(V5E) == [{"2x2": 1}]

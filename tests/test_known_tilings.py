"""Allowed-tilings generation + override tests.

Reference analogue: `pkg/gpu/mig/known_config_test.go`,
`allowed_geometries_test.go`.
"""

import pytest

from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.partitioning import (
    get_fewest_slices_geometry,
    geometry_total_slices,
)
from walkai_nos_tpu.tpu.tiling import known_tilings

V5E = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]
V4 = topology.KNOWN_MODELS["tpu-v4-podslice"]


class TestCandidateShapes:
    def test_v5e_2x4(self):
        shapes = known_tilings.candidate_shapes((2, 4))
        names = {known_tilings.canonical_profile(s) for s in shapes}
        # GKE v5e single-host shapes exactly.
        assert names == {"1x1", "1x2", "1x4", "2x2", "2x4"}

    def test_power_of_two_only(self):
        shapes = known_tilings.candidate_shapes((2, 4))
        for s in shapes:
            n = topology.shape_chip_count(s)
            assert n & (n - 1) == 0

    def test_v4_2x2x1(self):
        names = {
            known_tilings.canonical_profile(s)
            for s in known_tilings.candidate_shapes((2, 2, 1))
        }
        assert names == {"1x1x1", "1x1x2", "1x2x2"}


class TestGenerateTilings:
    def test_v5e_contains_expected_geometries(self):
        geoms = known_tilings.get_allowed_geometries(V5E)
        as_sets = [tuple(sorted(g.items())) for g in geoms]
        for expected in [
            {"2x4": 1},
            {"2x2": 2},
            {"1x4": 2},
            {"1x1": 8},
            {"2x2": 1, "1x2": 2},
            {"1x2": 4},
            {"2x2": 1, "1x1": 4},
        ]:
            assert tuple(sorted(expected.items())) in as_sets, expected

    def test_every_geometry_covers_all_chips(self):
        for g in known_tilings.get_allowed_geometries(V5E):
            total = sum(
                topology.shape_chip_count(topology.parse_shape(p)) * q
                for p, q in g.items()
            )
            assert total == 8

    def test_fewest_slices_is_whole_host(self):
        geoms = known_tilings.get_allowed_geometries(V5E)
        assert get_fewest_slices_geometry(geoms) == {"2x4": 1}

    def test_deterministic(self):
        a = known_tilings.get_allowed_geometries(V5E)
        b = known_tilings.get_allowed_geometries(V5E)
        assert a == b

    def test_v4_geometries(self):
        geoms = known_tilings.get_allowed_geometries(V4)
        as_sets = [tuple(sorted(g.items())) for g in geoms]
        assert tuple(sorted({"1x2x2": 1}.items())) in as_sets
        assert tuple(sorted({"1x1x1": 4}.items())) in as_sets
        assert tuple(sorted({"1x1x2": 2}.items())) in as_sets


class TestOverrides:
    def test_set_and_clear(self):
        known_tilings.set_known_geometries(
            {"tpu-v5-lite-podslice": [{"2x4": 1}, {"2x2": 2}]}
        )
        assert known_tilings.get_allowed_geometries(V5E) == [
            {"2x4": 1},
            {"2x2": 2},
        ]
        known_tilings.clear_known_geometries()
        assert len(known_tilings.get_allowed_geometries(V5E)) > 2

    def test_unknown_model_rejected(self):
        with pytest.raises(ValueError, match="unknown TPU model"):
            known_tilings.set_known_geometries({"nope": [{"2x4": 1}]})

    def test_too_many_chips_rejected(self):
        with pytest.raises(ValueError, match="chips"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"2x4": 2}]}
            )

    def test_non_canonical_profile_rejected(self):
        with pytest.raises(ValueError, match="canonical"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"4x2": 1}]}
            )

    def test_non_pow2_rejected(self):
        with pytest.raises(ValueError):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"1x3": 1}]}
            )

    def test_unplaceable_rejected(self):
        # 1x4 + 2x2 = 8 chips but cannot tile a 2x4 grid together: the 1x4
        # row leaves a 1x4 strip that a 2x2 cannot occupy.
        with pytest.raises(ValueError, match="not placeable"):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"1x4": 1, "2x2": 1}]}
            )

    def test_all_or_nothing(self):
        with pytest.raises(ValueError):
            known_tilings.set_known_geometries(
                {"tpu-v5-lite-podslice": [{"2x4": 1}, {"2x4": 2}]}
            )
        # first (valid) entry must not have been installed
        assert geometry_total_slices(
            get_fewest_slices_geometry(
                known_tilings.get_allowed_geometries(V5E)
            )
        ) == 1

    def test_partial_geometry_allowed_in_override(self):
        # Operators may expose fewer chips than the host has.
        known_tilings.set_known_geometries(
            {"tpu-v5-lite-podslice": [{"2x2": 1}]}
        )
        assert known_tilings.get_allowed_geometries(V5E) == [{"2x2": 1}]


class TestGeneratedTilingsArePackable:
    """Property sweep (VERDICT r3 weak #6): every geometry the generator
    emits for every known model must actually be placeable by the exact
    packer — a generator bug would otherwise surface as a runtime
    GenericError on a customer node, not in CI."""

    @pytest.mark.parametrize("model_name", sorted(topology.KNOWN_MODELS))
    def test_every_generated_geometry_packs_exactly(self, model_name):
        from walkai_nos_tpu.tpu.tiling import packing

        model = topology.KNOWN_MODELS[model_name]
        geometries = known_tilings.get_allowed_geometries(model)
        assert geometries, model_name
        mesh_cells = topology.shape_chip_count(model.host_mesh)
        for geom in geometries:
            placements = packing.pack_geometry(
                model.host_mesh, dict(geom), pinned=[]
            )
            assert placements is not None, (model_name, geom)
            # The packing realizes exactly the requested multiset...
            placed: dict[str, int] = {}
            for pl in placements:
                placed[pl.profile] = placed.get(pl.profile, 0) + 1
            assert placed == {p: q for p, q in geom.items() if q > 0}
            # ...on disjoint in-mesh cells covering the whole host
            # (tilings are exact covers by construction).
            cells = [c for pl in placements for c in pl.cells()]
            assert len(cells) == len(set(cells)), (model_name, geom)
            assert len(cells) == mesh_cells, (model_name, geom)
            for c in cells:
                assert all(
                    0 <= x < d for x, d in zip(c, model.host_mesh)
                ), (model_name, geom, c)

    @pytest.mark.parametrize("model_name", sorted(topology.KNOWN_MODELS))
    def test_every_generated_geometry_passes_override_validation(
        self, model_name
    ):
        # The validator must accept everything the generator emits —
        # otherwise an operator cannot pin the generated table via YAML.
        model = topology.KNOWN_MODELS[model_name]
        for geom in known_tilings.get_allowed_geometries(model):
            known_tilings.validate_geometry(model, geom)

    def test_unpackable_override_rejected_with_precise_error(self):
        # 1x4 takes a full row of the 2x4 host; the 2x2 then needs a
        # 2x2 block spanning both rows — chips fit (8), placement
        # doesn't. The error must say so, not just "invalid".
        with pytest.raises(ValueError, match="not placeable on 2x4"):
            known_tilings.validate_geometry(V5E, {"1x4": 1, "2x2": 1})

"""Rotating capture-corpus replay gate (hack/replay_corpus.py,
`make replay-corpus-check` — ROADMAP item 4(c)).

Rotation/pruning is plain-file logic (pinned with synthetic entries);
the gate itself is pinned on the tiny self-contained corpus: a base
run AND a multi-LoRA run recorded through real engines, every entry
replayed through cmd/replay.py — the LoRA entry proving a LoRA-armed
capture replays digest-exact from its fingerprint recipe alone. A
tampered capture must turn the gate red.
"""

import importlib.util
import json
import os
import pathlib

_ROOT = pathlib.Path(__file__).resolve().parents[1]

spec = importlib.util.spec_from_file_location(
    "replay_corpus", _ROOT / "hack" / "replay_corpus.py"
)
replay_corpus = importlib.util.module_from_spec(spec)
spec.loader.exec_module(replay_corpus)


def _fake_entry(tmp_path, name: str, nbytes: int = 8) -> str:
    """One pretend capture file rotated into the corpus."""
    src = tmp_path / f"src-{name}"
    src.mkdir()
    (src / "capture-000001.jsonl").write_bytes(b"x" * nbytes)
    return str(src)


class TestCorpusRotation:
    def test_entries_sequence_and_order(self, tmp_path):
        corpus = str(tmp_path / "corpus")
        for i in range(3):
            replay_corpus.add_capture(
                corpus, _fake_entry(tmp_path, f"c{i}"), name=f"c{i}"
            )
        entries = replay_corpus.corpus_entries(corpus)
        assert [os.path.basename(e) for e in entries] == [
            "0000-c0", "0001-c1", "0002-c2",
        ]

    def test_prune_keeps_last_n(self, tmp_path):
        corpus = str(tmp_path / "corpus")
        for i in range(5):
            replay_corpus.add_capture(
                corpus, _fake_entry(tmp_path, f"c{i}"), name=f"c{i}",
                max_captures=3,
            )
        entries = replay_corpus.corpus_entries(corpus)
        # Last 3 survive; the sequence keeps counting (no id reuse —
        # "last N" stays meaningful across prunes).
        assert [os.path.basename(e) for e in entries] == [
            "0002-c2", "0003-c3", "0004-c4",
        ]

    def test_prune_by_bytes_never_drops_newest(self, tmp_path):
        corpus = str(tmp_path / "corpus")
        for i in range(3):
            replay_corpus.add_capture(
                corpus, _fake_entry(tmp_path, f"c{i}", nbytes=100),
                name=f"c{i}", max_bytes=150,
            )
        entries = replay_corpus.corpus_entries(corpus)
        # 3x100 bytes over a 150 budget: oldest two pruned, the
        # newest stays even though it alone fits the budget exactly.
        assert [os.path.basename(e) for e in entries] == ["0002-c2"]
        # An entry bigger than the whole budget still survives alone.
        replay_corpus.prune_corpus(corpus, max_bytes=10)
        assert len(replay_corpus.corpus_entries(corpus)) == 1

    def test_add_missing_capture_raises(self, tmp_path):
        import pytest

        with pytest.raises(FileNotFoundError):
            replay_corpus.add_capture(
                str(tmp_path / "corpus"), str(tmp_path / "nope")
            )


class TestReplayCorpusGate:
    def test_gate_is_green_on_demo_corpus(self, capsys):
        """The `make replay-corpus-check` flow in-process: build the
        self-contained demo corpus (a base capture AND a multi-LoRA
        capture — adapters rebuilt from the fingerprint's synthetic
        recipe, digest-exact by construction) and replay every entry
        through cmd/replay.py. rc 0 is the whole contract."""
        assert replay_corpus.main([]) == 0
        out = capsys.readouterr().out
        assert "0000-base: token-identical" in out
        assert "0001-lora: token-identical" in out

    def test_gate_is_red_on_tampered_capture(self, tmp_path, capsys):
        """Flip one captured token and the gate must exit nonzero —
        a corpus gate that can't fail is decoration."""
        capture_dir = tmp_path / "cap"
        capture_dir.mkdir()
        replay_corpus.record_lora_traffic(str(capture_dir))
        fname = next(
            f for f in sorted(os.listdir(capture_dir))
            if f.startswith("capture-")
        )
        path = capture_dir / fname
        lines = path.read_text().splitlines()
        for i, line in enumerate(lines):
            obj = json.loads(line)
            if obj.get("kind") == "done" and obj.get("tokens"):
                obj["tokens"][0] = (obj["tokens"][0] + 1) % 64
                lines[i] = json.dumps(obj)
                break
        else:
            raise AssertionError("no done record to tamper with")
        path.write_text("\n".join(lines) + "\n")
        corpus = str(tmp_path / "corpus")
        replay_corpus.add_capture(corpus, str(capture_dir), name="bad")
        assert replay_corpus.main([corpus]) != 0
        assert "DIVERGENT" in capsys.readouterr().out

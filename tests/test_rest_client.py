"""RestKubeClient against a minimal in-process API server.

The stdlib-HTTP fake emulates just enough of the k8s REST surface (CRUD,
merge-patch, label selectors, streaming watch with resourceVersion) to
exercise the client's real wire path.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from walkai_nos_tpu.kube.client import ApiError, NotFound
from walkai_nos_tpu.kube.rest import RestKubeClient
from walkai_nos_tpu.kube.runtime import Controller, Request, Result


class _MiniApiServer:
    """Cluster-scoped /api/v1/nodes + namespaced /api/v1/pods, with watch."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rv = 0
        self._objects: dict[tuple, dict] = {}  # (plural, ns, name) -> obj
        self._events: list[tuple[int, str, dict]] = []
        self._cond = threading.Condition(self._lock)
        self._httpd = None
        self._thread = None

    # ------------------------------------------------------------------ state

    def _bump(self, etype, obj):
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        self._events.append((self._rv, etype, json.loads(json.dumps(obj))))
        self._cond.notify_all()

    # ---------------------------------------------------------------- serving

    def start(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _parse(self):
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                # ["api","v1",("namespaces",ns)?,plural,(name)?]
                assert parts[:2] == ["api", "v1"]
                rest = parts[2:]
                ns = ""
                if rest and rest[0] == "namespaces":
                    ns = rest[1]
                    rest = rest[2:]
                plural = rest[0]
                name = rest[1] if len(rest) > 1 else None
                return plural, ns, name, parse_qs(u.query)

            def _send(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def do_GET(self):
                plural, ns, name, query = self._parse()
                if not name and query.get("watch"):
                    rv = int(query.get("resourceVersion", ["0"])[0])
                    self._watch(plural, ns, rv)
                    return
                with outer._lock:
                    if name:
                        obj = outer._objects.get((plural, ns, name))
                        if obj is None:
                            self._send(404, {"message": "not found"})
                        else:
                            self._send(200, obj)
                        return
                    sel = {}
                    for pair in query.get("labelSelector", [""])[0].split(","):
                        if "=" in pair:
                            k, v = pair.split("=", 1)
                            sel[k] = v
                    items = [
                        o
                        for (p, n2, _), o in sorted(outer._objects.items())
                        if p == plural
                        and (not ns or n2 == ns)
                        and all(
                            (o.get("metadata", {}).get("labels") or {}).get(k)
                            == v
                            for k, v in sel.items()
                        )
                    ]
                    self._send(
                        200,
                        {
                            "items": items,
                            "metadata": {"resourceVersion": str(outer._rv)},
                        },
                    )

            def _watch(self, plural, ns, rv):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                deadline = time.monotonic() + 2.0
                sent = rv
                while time.monotonic() < deadline:
                    with outer._cond:
                        events = [
                            (v, t, o)
                            for v, t, o in outer._events
                            if v > sent
                        ]
                        if not events:
                            outer._cond.wait(0.1)
                            continue
                    for v, etype, obj in events:
                        line = (
                            json.dumps({"type": etype, "object": obj}) + "\n"
                        ).encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()
                        sent = v
                self.wfile.write(b"0\r\n\r\n")

            def do_POST(self):
                plural, ns, name, _ = self._parse()
                obj = self._read_body()
                name = obj["metadata"]["name"]
                with outer._lock:
                    key = (plural, ns, name)
                    if key in outer._objects:
                        self._send(409, {"message": "exists"})
                        return
                    outer._objects[key] = obj
                    outer._bump("ADDED", obj)
                    self._send(201, obj)

            def do_PATCH(self):
                plural, ns, name, _ = self._parse()
                patch = self._read_body()
                with outer._lock:
                    obj = outer._objects.get((plural, ns, name))
                    if obj is None:
                        self._send(404, {"message": "not found"})
                        return
                    _merge(obj, patch)
                    outer._bump("MODIFIED", obj)
                    self._send(200, obj)

            def do_PUT(self):
                plural, ns, name, _ = self._parse()
                obj = self._read_body()
                with outer._lock:
                    outer._objects[(plural, ns, name)] = obj
                    outer._bump("MODIFIED", obj)
                    self._send(200, obj)

            def do_DELETE(self):
                plural, ns, name, _ = self._parse()
                with outer._lock:
                    obj = outer._objects.pop((plural, ns, name), None)
                    if obj is None:
                        self._send(404, {"message": "not found"})
                        return
                    outer._bump("DELETED", obj)
                    self._send(200, {})

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _merge(target: dict, patch: dict):
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict) and isinstance(target.get(k), dict):
            _merge(target[k], v)
        else:
            target[k] = v


@pytest.fixture
def api():
    server = _MiniApiServer()
    url = server.start()
    yield url, server
    server.stop()


class TestRestKubeClient:
    def test_crud_roundtrip(self, api):
        url, _ = api
        client = RestKubeClient(server=url)
        client.create(
            "Node",
            {"metadata": {"name": "n1", "labels": {"role": "tpu"}}},
        )
        node = client.get("Node", "n1")
        assert node["metadata"]["name"] == "n1"
        client.patch(
            "Node", "n1", {"metadata": {"annotations": {"a": "1"}}}
        )
        assert client.get("Node", "n1")["metadata"]["annotations"] == {"a": "1"}
        assert [o["metadata"]["name"] for o in client.list("Node")] == ["n1"]
        assert client.list("Node", label_selector={"role": "tpu"})
        assert not client.list("Node", label_selector={"role": "gpu"})
        client.delete("Node", "n1")
        with pytest.raises(NotFound):
            client.get("Node", "n1")

    def test_namespaced_pods(self, api):
        url, _ = api
        client = RestKubeClient(server=url)
        client.create(
            "Pod",
            {"metadata": {"name": "p1", "namespace": "ml"}},
            namespace="ml",
        )
        assert client.get("Pod", "p1", "ml")["metadata"]["name"] == "p1"
        with pytest.raises(NotFound):
            client.get("Pod", "p1", "default")

    def test_list_all_namespaces_uses_cluster_path(self, api):
        """namespace=None on a namespaced kind must list ALL namespaces
        (the KubeClient contract) — not silently only 'default'."""
        url, _ = api
        client = RestKubeClient(server=url)
        client.create("Pod", {"metadata": {"name": "p1", "namespace": "ml"}})
        client.create(
            "Pod", {"metadata": {"name": "p2", "namespace": "default"}}
        )
        names = {o["metadata"]["name"] for o in client.list("Pod")}
        assert names == {"p1", "p2"}
        # Single-object addressing still defaults to the default namespace.
        assert client.get("Pod", "p2")["metadata"]["namespace"] == "default"

    def test_watch_all_namespaces(self, api):
        url, _ = api
        client = RestKubeClient(server=url)
        client.create("Pod", {"metadata": {"name": "p1", "namespace": "ml"}})
        client.create("Pod", {"metadata": {"name": "p2", "namespace": "ops"}})
        done = threading.Event()
        seen = []
        for etype, obj in client.watch("Pod", stop=done.is_set):
            seen.append(obj["metadata"]["name"])
            if len(seen) >= 2:
                done.set()
                break
        assert set(seen) == {"p1", "p2"}

    @staticmethod
    def _make_flaky(client, on_outage):
        """Patch client._watch_once to fail once, running `on_outage`
        during the simulated stream outage."""
        orig = client._watch_once
        failed = []

        def flaky(kind, namespace, rv_box, stop):
            if not failed:
                failed.append(True)
                on_outage()
                raise ApiError(410, "gone")
            return orig(kind, namespace, rv_box, stop)

        client._watch_once = flaky

    def test_relist_is_framed_resync_to_synced(self, api):
        """After an outage the relist replay is framed RESYNC…SYNCED and
        names only survivors — that framing is what lets consumers drop
        objects deleted during the outage."""
        url, _ = api
        client = RestKubeClient(server=url)
        admin = RestKubeClient(server=url)
        client.create("Node", {"metadata": {"name": "n1"}})
        client.create("Node", {"metadata": {"name": "n2"}})
        self._make_flaky(client, lambda: admin.delete("Node", "n2"))
        events = []
        done = threading.Event()

        def consume():
            for etype, obj in client.watch("Node", stop=done.is_set):
                events.append((etype, (obj.get("metadata") or {}).get("name")))
                if sum(1 for t, _ in events if t == "SYNCED") >= 2:
                    done.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=5.0)
        done.set()
        assert events[:3] == [
            ("ADDED", "n1"), ("ADDED", "n2"), ("SYNCED", None)
        ]
        resync = events.index(("RESYNC", None))
        replay = [n for (t2, n) in events[resync:] if t2 == "MODIFIED"]
        assert replay == ["n1"]  # n2 is gone, not re-mentioned
        assert events[-1] == ("SYNCED", None)

    def test_controller_prunes_deleted_during_outage(self, api):
        """End-to-end: a Controller on the real wire path reconciles (and
        un-caches) an object deleted while its watch stream was down."""
        url, _ = api
        client = RestKubeClient(server=url)
        admin = RestKubeClient(server=url)
        admin.create("Node", {"metadata": {"name": "n1"}})
        admin.create("Node", {"metadata": {"name": "n2"}})
        self._make_flaky(client, lambda: admin.delete("Node", "n2"))
        deleted = threading.Event()

        def reconcile(req: Request) -> Result:
            try:
                admin.get("Node", req.name)
            except NotFound:
                if req.name == "n2":
                    deleted.set()
            return Result()

        ctrl = Controller("t", client, "Node", reconcile)
        ctrl.start()
        try:
            assert deleted.wait(timeout=10)
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and any(
                name == "n2" for (_, name) in ctrl._cache
            ):
                time.sleep(0.02)
            assert all(name != "n2" for (_, name) in ctrl._cache)
        finally:
            ctrl.stop()

    def test_watch_streams_live_events(self, api):
        url, _ = api
        client = RestKubeClient(server=url)
        client.create("Node", {"metadata": {"name": "n1"}})
        events = []
        done = threading.Event()

        def consume():
            for event, obj in client.watch("Node", stop=done.is_set):
                if event in ("SYNCED", "RESYNC"):
                    continue
                events.append((event, obj["metadata"]["name"]))
                if len(events) >= 3:
                    done.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        client.patch("Node", "n1", {"metadata": {"annotations": {"x": "1"}}})
        client.create("Node", {"metadata": {"name": "n2"}})
        t.join(timeout=5.0)
        done.set()
        assert events[0] == ("ADDED", "n1")  # synthetic initial ADDED
        assert ("MODIFIED", "n1") in events
        assert ("ADDED", "n2") in events

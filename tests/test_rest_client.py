"""RestKubeClient against a minimal in-process API server.

The stdlib-HTTP fake emulates just enough of the k8s REST surface (CRUD,
merge-patch, label selectors, streaming watch with resourceVersion) to
exercise the client's real wire path.
"""

import threading
import time

import pytest


from walkai_nos_tpu.kube.client import NotFound
from walkai_nos_tpu.kube.rest import RestKubeClient
from walkai_nos_tpu.kube.runtime import Controller, Request, Result


class TestRestKubeClient:
    def test_crud_roundtrip(self, api):
        url = api
        client = RestKubeClient(server=url)
        client.create(
            "Node",
            {"metadata": {"name": "n1", "labels": {"role": "tpu"}}},
        )
        node = client.get("Node", "n1")
        assert node["metadata"]["name"] == "n1"
        client.patch(
            "Node", "n1", {"metadata": {"annotations": {"a": "1"}}}
        )
        assert client.get("Node", "n1")["metadata"]["annotations"] == {"a": "1"}
        assert [o["metadata"]["name"] for o in client.list("Node")] == ["n1"]
        assert client.list("Node", label_selector={"role": "tpu"})
        assert not client.list("Node", label_selector={"role": "gpu"})
        client.delete("Node", "n1")
        with pytest.raises(NotFound):
            client.get("Node", "n1")

    def test_namespaced_pods(self, api):
        url = api
        client = RestKubeClient(server=url)
        client.create(
            "Pod",
            {"metadata": {"name": "p1", "namespace": "ml"}},
            namespace="ml",
        )
        assert client.get("Pod", "p1", "ml")["metadata"]["name"] == "p1"
        with pytest.raises(NotFound):
            client.get("Pod", "p1", "default")

    def test_eviction_subresource_enforces_pdb(self, api):
        """The pods/eviction wire path: a PDB with no disruptions left
        answers 429 (EvictionBlocked); with budget, the pod is deleted."""
        from walkai_nos_tpu.kube.client import EvictionBlocked

        client = RestKubeClient(server=api)
        for i in range(2):
            client.create(
                "Pod",
                {
                    "metadata": {
                        "name": f"p{i}", "namespace": "ml",
                        "labels": {"app": "x"},
                    },
                    "spec": {"nodeName": "n1"},
                    "status": {"phase": "Running"},
                },
                namespace="ml",
            )
        client.create(
            "PodDisruptionBudget",
            {
                "metadata": {"name": "pdb", "namespace": "ml"},
                "spec": {
                    "minAvailable": 1,
                    "selector": {"matchLabels": {"app": "x"}},
                },
            },
            namespace="ml",
        )
        client.evict_pod("p0", "ml", grace_period_seconds=5)
        with pytest.raises(NotFound):
            client.get("Pod", "p0", "ml")
        with pytest.raises(EvictionBlocked):
            client.evict_pod("p1", "ml")
        assert client.get("Pod", "p1", "ml")  # survived

    def test_list_all_namespaces_uses_cluster_path(self, api):
        """namespace=None on a namespaced kind must list ALL namespaces
        (the KubeClient contract) — not silently only 'default'."""
        url = api
        client = RestKubeClient(server=url)
        client.create("Pod", {"metadata": {"name": "p1", "namespace": "ml"}})
        client.create(
            "Pod", {"metadata": {"name": "p2", "namespace": "default"}}
        )
        names = {o["metadata"]["name"] for o in client.list("Pod")}
        assert names == {"p1", "p2"}
        # Single-object addressing still defaults to the default namespace.
        assert client.get("Pod", "p2")["metadata"]["namespace"] == "default"

    def test_watch_all_namespaces(self, api):
        url = api
        client = RestKubeClient(server=url)
        client.create("Pod", {"metadata": {"name": "p1", "namespace": "ml"}})
        client.create("Pod", {"metadata": {"name": "p2", "namespace": "ops"}})
        done = threading.Event()
        seen = []
        for etype, obj in client.watch("Pod", stop=done.is_set):
            seen.append(obj["metadata"]["name"])
            if len(seen) >= 2:
                done.set()
                break
        assert set(seen) == {"p1", "p2"}

    @staticmethod
    def _make_flaky(client, on_outage):
        from tests.helpers import make_flaky_watch

        make_flaky_watch(client, on_outage)

    def test_relist_is_framed_resync_to_synced(self, api):
        """After an outage the relist replay is framed RESYNC…SYNCED and
        names only survivors — that framing is what lets consumers drop
        objects deleted during the outage."""
        url = api
        client = RestKubeClient(server=url)
        admin = RestKubeClient(server=url)
        client.create("Node", {"metadata": {"name": "n1"}})
        client.create("Node", {"metadata": {"name": "n2"}})
        self._make_flaky(client, lambda: admin.delete("Node", "n2"))
        events = []
        done = threading.Event()

        def consume():
            for etype, obj in client.watch("Node", stop=done.is_set):
                events.append((etype, (obj.get("metadata") or {}).get("name")))
                if sum(1 for t, _ in events if t == "SYNCED") >= 2:
                    done.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=5.0)
        done.set()
        assert events[:3] == [
            ("ADDED", "n1"), ("ADDED", "n2"), ("SYNCED", None)
        ]
        resync = events.index(("RESYNC", None))
        replay = [n for (t2, n) in events[resync:] if t2 == "MODIFIED"]
        assert replay == ["n1"]  # n2 is gone, not re-mentioned
        assert events[-1] == ("SYNCED", None)

    def test_controller_prunes_deleted_during_outage(self, api):
        """End-to-end: a Controller on the real wire path reconciles (and
        un-caches) an object deleted while its watch stream was down."""
        url = api
        client = RestKubeClient(server=url)
        admin = RestKubeClient(server=url)
        admin.create("Node", {"metadata": {"name": "n1"}})
        admin.create("Node", {"metadata": {"name": "n2"}})
        self._make_flaky(client, lambda: admin.delete("Node", "n2"))
        deleted = threading.Event()

        def reconcile(req: Request) -> Result:
            try:
                admin.get("Node", req.name)
            except NotFound:
                if req.name == "n2":
                    deleted.set()
            return Result()

        ctrl = Controller("t", client, "Node", reconcile)
        ctrl.start()
        try:
            assert deleted.wait(timeout=10)
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and any(
                name == "n2" for (_, name) in ctrl._cache
            ):
                time.sleep(0.02)
            assert all(name != "n2" for (_, name) in ctrl._cache)
        finally:
            ctrl.stop()

    def test_watch_streams_live_events(self, api):
        url = api
        client = RestKubeClient(server=url)
        client.create("Node", {"metadata": {"name": "n1"}})
        events = []
        done = threading.Event()

        def consume():
            for event, obj in client.watch("Node", stop=done.is_set):
                if event in ("SYNCED", "RESYNC"):
                    continue
                events.append((event, obj["metadata"]["name"]))
                if len(events) >= 3:
                    done.set()
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        client.patch("Node", "n1", {"metadata": {"annotations": {"x": "1"}}})
        client.create("Node", {"metadata": {"name": "n2"}})
        t.join(timeout=5.0)
        done.set()
        assert events[0] == ("ADDED", "n1")  # synthetic initial ADDED
        assert ("MODIFIED", "n1") in events
        assert ("ADDED", "n2") in events

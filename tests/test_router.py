"""Fleet router + slice autoscaler (`walkai_nos_tpu/router`).

Tier-1 surface for the multi-engine serving layer: routing must be
prefix-affine but load-bounded and must NEVER touch a draining
replica; the reconciler's hysteresis + cooldown must turn a flapping
saturation trace into exactly one scale-up and one scale-down; the
engine's graceful-drain seam must reject new work through the error
taxonomy while resident work finishes; and the end-to-end fleet must
serve a Zipf template workload with per-request tokens IDENTICAL to
a single engine (routing changes WHERE a request runs, never WHAT it
emits), survive a mid-run scale-up and a drain-based scale-down with
zero dropped requests, and beat round-robin routing on the fleet
prefix hit rate. Deliberately NOT in conftest's `_SLOW_FILES`: the
routing/reconciler logic runs on scripted fake replicas (no jax at
all), and the engine-backed tests stay on a 1-layer tiny config.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from walkai_nos_tpu.router import (
    FleetRouter,
    PartitionerSliceProvider,
    ScalePolicy,
    StaticSliceProvider,
    prefix_key,
)
from walkai_nos_tpu.router.core import PAGE_ROWS


class FakeReplica:
    """Scripted replica: saturation is set by the test, submits are
    recorded, records complete on the next step — the no-jax seam the
    routing and reconciler tests drive."""

    def __init__(self, name, sat=0.0):
        self.name = name
        self.sat = sat
        self.busy = False  # scripted "resident work" holding a drain
        self.submits = 0
        self.submits_while_draining = 0
        self._rid = 0
        self._pending = {}
        self._draining = False

    def submit(self, prompt, **kwargs):
        if self._draining:
            self.submits_while_draining += 1
            raise ValueError("draining")
        rid = self._rid
        self._rid += 1
        self.submits += 1
        self._pending[rid] = {
            "tokens": [1], "ttft_s": 0.01, "wall_s": 0.02,
            "truncated": False,
        }
        return rid

    def step(self):
        pass

    def drain_done_records(self):
        done, self._pending = self._pending, {}
        return done

    @property
    def saturation(self):
        return self.sat

    slo_ok = None
    slots = 4

    @property
    def queue_depth(self):
        return 0

    @property
    def has_work(self):
        return bool(self._pending) or self.busy

    def drain(self):
        self._draining = True

    @property
    def draining(self):
        return self._draining

    def prefix_stats(self):
        return {}


def _template(seed, extra=8):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 64, PAGE_ROWS + extra).astype(np.int32)


class TestPrefixKey:
    def test_block_granularity_and_stability(self):
        prompt = _template(0)
        assert prefix_key(prompt) == prefix_key(prompt)
        # Same first block, different suffix -> same key (the suffix
        # is not shareable; the template is).
        other = np.concatenate(
            [prompt[:PAGE_ROWS], np.arange(5, dtype=np.int32)]
        )
        assert prefix_key(other) == prefix_key(prompt)
        assert prefix_key(_template(1)) != prefix_key(prompt)
        # No full block -> nothing shareable -> no key.
        assert prefix_key(prompt[: PAGE_ROWS - 1]) is None


class TestRoutingPolicy:
    def test_affinity_sticks_to_one_replica(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = FleetRouter([a, b], seed=0)
        prompt = _template(0)
        for _ in range(6):
            router.submit(prompt, max_new_tokens=4)
        assert sorted((a.submits, b.submits)) == [0, 6]
        assert int(router.obs.routed.value(
            labels={"policy": "affinity"}
        )) == 5  # first pick is p2c, the rest ride the map

    def test_overload_falls_back_to_p2c_and_repoints(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = FleetRouter([a, b], seed=0)
        prompt = _template(0)
        router.submit(prompt, max_new_tokens=4)
        hot = a if a.submits else b
        cold = b if a.submits else a
        hot.sat = 0.95  # past affinity_overload
        router.submit(prompt, max_new_tokens=4)
        assert cold.submits == 1  # p2c picked the cold replica
        hot.sat = 0.0
        router.submit(prompt, max_new_tokens=4)
        # Affinity RE-POINTED to the overflow target.
        assert cold.submits == 2

    def test_overloaded_target_holds_when_no_cooler_destination(self):
        """The imbalance gap gates on the actual two-choice
        DESTINATION, not the fleet minimum: a hot affinity target
        must never migrate its template to a sampled pair that is
        equally or more loaded (uniform saturation, or a lucky cold
        minimum the sample didn't draw) — migration would pay a cold
        prefill for zero balance gain."""
        a, b, c = (
            FakeReplica("a"), FakeReplica("b"), FakeReplica("c"),
        )
        router = FleetRouter([a, b, c], seed=0)
        prompt = _template(0)
        router.submit(prompt, max_new_tokens=4)
        target = next(r for r in (a, b, c) if r.submits)
        # Uniformly saturated fleet: every candidate as hot as the
        # target — affinity holds, every time.
        for replica in (a, b, c):
            replica.sat = 0.97
        for _ in range(8):
            router.submit(prompt, max_new_tokens=4)
        assert target.submits == 9
        assert sum(r.submits for r in (a, b, c)) == 9

    def test_unreachable_replica_reads_as_max_load(self):
        """A failed health probe must read as load 1.0, not 0.0 —
        empty signals would otherwise make a dead HTTP pod the
        fleet's most attractive routing target."""
        from walkai_nos_tpu.router.autoscale import replica_load
        from walkai_nos_tpu.router.replica import HttpReplica

        # Port 9 (discard) refuses instantly — a dead pod.
        dead = HttpReplica("http://127.0.0.1:9", workers=1)
        assert dead.unreachable is True
        assert replica_load(dead) == 1.0

    def test_draining_replica_never_routed(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = FleetRouter([a, b], seed=0)
        handle_a = next(
            h for h in router.active_handles() if h.replica is a
        )
        router.start_drain(handle_a)
        for seed in range(8):
            router.submit(_template(seed), max_new_tokens=4)
        assert a.submits == 0
        assert a.submits_while_draining == 0
        assert b.submits == 8

    def test_no_active_replica_raises_and_counts(self):
        a = FakeReplica("a")
        router = FleetRouter([a], seed=0)
        router.start_drain(router.active_handles()[0])
        with pytest.raises(RuntimeError):
            router.submit(_template(0), max_new_tokens=4)
        assert int(router.obs.failed.value(
            labels={"reason": "no_replica"}
        )) == 1

    def test_round_robin_rotates(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = FleetRouter([a, b], policy="round_robin", seed=0)
        prompt = _template(0)
        for _ in range(6):
            router.submit(prompt, max_new_tokens=4)
        assert a.submits == 3 and b.submits == 3

    def test_records_carry_router_rids_and_replica(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = FleetRouter([a, b], seed=0)
        rids = [
            router.submit(_template(s), max_new_tokens=4)
            for s in range(4)
        ]
        router.step()
        records = router.drain_done_records()
        assert sorted(records) == sorted(rids)
        assert all(
            rec["replica"] in ("a", "b") for rec in records.values()
        )
        assert not router.has_work


class TestReconcilerHysteresis:
    def _router(self, policy):
        base = FakeReplica("base")
        spare = FakeReplica("spare")
        provider = StaticSliceProvider([spare])
        return (
            FleetRouter(
                [base], provider=provider, scale_policy=policy,
                seed=0,
            ),
            base,
            spare,
            provider,
        )

    def test_breach_recover_rebreach_scales_once_each_way(self):
        """The satellite's scripted trace: a sustained breach scales
        up ONCE (after breach_ticks of hysteresis), recovery drains
        ONE replica (after idle_ticks + the up-event's cooldown), and
        a re-breach inside the down-event's cooldown does NOT scale
        up again."""
        policy = ScalePolicy(
            min_replicas=1, max_replicas=2, up_saturation=0.8,
            down_saturation=0.3, breach_ticks=3, idle_ticks=4,
            cooldown_ticks=10,
        )
        router, base, spare, provider = self._router(policy)

        def set_sat(value):
            for replica in router.replicas:
                replica.sat = value

        # Breach: pressured ticks 1..2 accumulate, tick 3 scales up.
        set_sat(0.95)
        for _ in range(5):
            router.step()
        assert router.scale_events()["up"] == 1
        assert len(router.replicas) == 2
        # Recover: idle accumulates, but the up-event's cooldown must
        # pass first; one (and only one) drain then starts, and the
        # drained replica is retired + released once empty.
        set_sat(0.05)
        for _ in range(14):
            router.step()
        events = router.scale_events()
        assert events["down"] == 1
        assert len(router.replicas) == 1
        assert [r.name for r in provider.released] == ["base"]
        # The retired replica's per-replica saturation series is
        # dropped, not left exporting its last value forever; the
        # surviving replica's series stays.
        assert router.obs.replica_saturation.value(
            labels={"replica": "base"}
        ) is None
        assert router.obs.replica_saturation.value(
            labels={"replica": "spare"}
        ) is not None
        # Re-breach INSIDE the down-event's cooldown: no second
        # scale-up fires while it holds.
        down_tick_budget = policy.cooldown_ticks - policy.idle_ticks
        set_sat(0.95)
        for _ in range(max(2, down_tick_budget - 1)):
            router.step()
        assert router.scale_events()["up"] == 1
        assert len(router.replicas) == 1

    def test_mid_drain_replica_receives_nothing(self):
        policy = ScalePolicy(
            min_replicas=1, max_replicas=2, up_saturation=0.8,
            down_saturation=0.3, breach_ticks=1, idle_ticks=1,
            cooldown_ticks=2,
        )
        router, base, spare, provider = self._router(policy)
        base.sat = 0.95
        router.step()  # scale-up admits the spare
        assert len(router.replicas) == 2
        # Scripted resident work holds the drain OPEN so the routed
        # requests below arrive mid-drain, not post-retirement.
        base.sat = spare.sat = 0.0
        base.busy = spare.busy = True
        for _ in range(6):
            router.step()
            if router.draining_handles():
                break
        draining = router.draining_handles()
        assert len(draining) == 1
        # Every request routed while the drain is open lands on the
        # OTHER replica; the draining one sees zero submits.
        victim = draining[0].replica
        before = victim.submits
        for seed in range(6):
            router.submit(_template(seed), max_new_tokens=4)
        assert victim.submits == before
        assert victim.submits_while_draining == 0
        # Releasing the scripted work completes the drain.
        victim.busy = False
        other = next(
            r for r in router.replicas if r is not victim
        )
        other.busy = False
        for _ in range(3):
            router.step()
        assert victim not in router.replicas

    def test_dry_provider_counts_denied(self):
        policy = ScalePolicy(
            min_replicas=1, max_replicas=4, up_saturation=0.8,
            breach_ticks=1, cooldown_ticks=2,
        )
        base = FakeReplica("base", sat=0.95)
        router = FleetRouter(
            [base], provider=StaticSliceProvider([]),
            scale_policy=policy, seed=0,
        )
        router.step()
        assert router.scale_events() == {
            "up": 0, "down": 0, "denied": 1,
        }


class TestPartitionerSliceProvider:
    def _kube_with_node(self, name="host-0", topology="2x2"):
        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.kube.fake import FakeKubeClient

        kube = FakeKubeClient()
        kube.create("Node", {
            "metadata": {
                "name": name,
                "labels": {constants.LABEL_TPU_TOPOLOGY: topology},
            },
        })
        return kube

    def test_acquire_writes_plan_and_release_reverts(self):
        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.kube import objects
        from walkai_nos_tpu.tpu.annotations import (
            parse_node_annotations,
        )

        kube = self._kube_with_node(topology="2x2")  # 4 chips
        provider = PartitionerSliceProvider(
            kube, ["host-0"],
            engine_factory=lambda name: FakeReplica(name),
            profile="1x1",
        )
        replicas = [provider.acquire() for _ in range(4)]
        assert all(r is not None for r in replicas)
        # Capacity: 4 chips / 1-chip profile -> the 5th is denied.
        assert provider.acquire() is None
        node = kube.get("Node", "host-0")
        annotations = objects.annotations(node)
        _, spec = parse_node_annotations(annotations)
        assert [(s.mesh_index, s.profile, s.quantity) for s in spec] \
            == [(0, "1x1", 4)]
        assert constants.ANNOTATION_PARTITIONING_PLAN in annotations
        plan_before = annotations[
            constants.ANNOTATION_PARTITIONING_PLAN
        ]
        # Release one slice: the desired geometry drops to 3 and a
        # NEW plan id is written (the agent must re-actuate).
        provider.release(replicas[0])
        node = kube.get("Node", "host-0")
        annotations = objects.annotations(node)
        _, spec = parse_node_annotations(annotations)
        assert [(s.profile, s.quantity) for s in spec] == [("1x1", 3)]
        assert annotations[
            constants.ANNOTATION_PARTITIONING_PLAN
        ] != plan_before
        # Freed capacity is acquirable again.
        assert provider.acquire() is not None

    def test_writes_merge_with_foreign_spec_entries(self):
        """apply_partitioning REPLACES a node's whole spec-annotation
        set, so every provider write must carry the entries it does
        not own — pod-controller slices on the same mesh and geometry
        on other meshes — or scale-up/down would tear down running
        workloads' slices. Both foreign entries must survive an
        acquire AND a release-to-zero."""
        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.kube import objects
        from walkai_nos_tpu.tpu.annotations import (
            parse_node_annotations,
        )

        kube = self._kube_with_node(topology="2x4")  # 8 chips
        kube.patch("Node", "host-0", {"metadata": {"annotations": {
            # Pod-controller-managed slice on the provider's mesh.
            constants.ANNOTATION_TPU_SPEC_FORMAT.format(
                index=0, profile="2x2"
            ): "1",
            # Another mesh's geometry entirely.
            constants.ANNOTATION_TPU_SPEC_FORMAT.format(
                index=1, profile="1x2"
            ): "2",
        }}})
        provider = PartitionerSliceProvider(
            kube, ["host-0"],
            engine_factory=lambda name: FakeReplica(name),
            profile="1x1",
        )
        replica = provider.acquire()
        assert replica is not None
        _, spec = parse_node_annotations(
            objects.annotations(kube.get("Node", "host-0"))
        )
        entries = sorted(
            (s.mesh_index, s.profile, s.quantity) for s in spec
        )
        assert entries == [
            (0, "1x1", 1), (0, "2x2", 1), (1, "1x2", 2),
        ]
        # Release back to zero: the provider's entry vanishes, the
        # foreign entries remain.
        provider.release(replica)
        _, spec = parse_node_annotations(
            objects.annotations(kube.get("Node", "host-0"))
        )
        entries = sorted(
            (s.mesh_index, s.profile, s.quantity) for s in spec
        )
        assert entries == [(0, "2x2", 1), (1, "1x2", 2)]


# -- engine-backed tests (tiny 1-layer config) -------------------------
# One module-scoped factory (weights + engine shapes) feeds EVERY
# engine test here AND the traffic harness, so the session compile
# cache pays each XLA program exactly once — the tier-1 lane's 870 s
# budget is nearly full, and every extra cold compile counts.

import jax  # noqa: E402,F401 — conftest pins the CPU backend

from walkai_nos_tpu.models.lm import LMConfig  # noqa: E402
from walkai_nos_tpu.sim.trafficbench import (  # noqa: E402
    default_engine_factory,
    run_traffic_benchmark,
)

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def fleet():
    """(params, engine-replica factory): the traffic harness's own
    tiny-engine factory, so test engines and harness engines share
    weights and compiled-program shapes."""
    _, params, make = default_engine_factory(CFG, None, slots=2)
    return params, make


class TestDrainSeam:
    def test_drain_rejects_new_keeps_accepted(self, fleet):
        """drain() flips submit() to the `draining` taxonomy reject
        while everything already ACCEPTED stays owned by the engine.
        No dispatch happens here (cheap); run-to-completion of a
        drained engine is the fleet e2e's drain-down, which finishes
        every resident request of its drained victim."""
        _, make = fleet
        engine = make("drain0").engine
        rid = engine.submit(_template(0), max_new_tokens=5)
        engine.drain()
        assert engine.draining
        with pytest.raises(ValueError):
            engine.submit(_template(2), max_new_tokens=5)
        # The reject landed in the taxonomy, not just the exception.
        assert int(engine.obs.errors.value(
            labels={"reason": "draining"}
        )) == 1
        # The pre-drain request is still queued — accepted work is
        # never dropped by a drain.
        assert engine.has_work
        assert rid in engine._requests
        # drain() is idempotent.
        engine.drain()
        assert engine.draining

    def test_healthz_block_surfaces_draining(self, fleet):
        import importlib.util
        import pathlib
        import sys

        path = (
            pathlib.Path(__file__).resolve().parents[1]
            / "demos" / "tpu-sharing-comparison" / "app" / "main.py"
        )
        spec = importlib.util.spec_from_file_location(
            "walkai_demo_app_router_test", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules["walkai_demo_app_router_test"] = mod
        spec.loader.exec_module(mod)
        _, make = fleet
        engine = make("drain1").engine
        before = mod.engine_health(engine, True)
        assert before["draining"] is False
        assert before["drain"]["draining"] is False
        engine.drain()
        payload = mod.engine_health(engine, True)
        assert payload["draining"] is True
        assert payload["has_work"] is False
        # The drain-progress block (drain_stats): an operator polling
        # /healthz watches these count down to zero.
        assert payload["drain"] == {
            "draining": True,
            "resident_slots": 0,
            "prefilling": 0,
            "queued": 0,
            "blocks_remaining": 0,
        }


class TestFleetEndToEnd:
    # Fixed interleaved template order: t0 x6, t1 x4, t2 x2 — every
    # template recurs, so the miss budgets below are structural, not
    # sampled. Templates t3/t4/t5 appear once each AFTER the
    # scale-up.
    ORDER = [0, 1, 0, 2, 0, 1, 0, 1, 2, 0, 1, 0]

    def _prompts(self, seed=7):
        rng = np.random.default_rng(seed)
        bases = [_template(100 + t, extra=0) for t in range(6)]
        return [
            np.concatenate([
                bases[t],
                rng.integers(0, 64, 6).astype(np.int32),
            ])
            for t in self.ORDER + [3, 4, 5]
        ]

    def test_parity_scale_up_and_drain_down_zero_drop(self, fleet):
        """The acceptance scenario in one run: >=2 in-process
        replicas behind the router serve a template workload
        token-identically to a single engine; a mid-run scale-up
        admits a third replica which serves traffic; a drain-based
        scale-down completes with zero dropped or errored requests;
        and the fleet prefix hit rate beats round-robin routing on
        the SAME trace."""
        _, make = fleet
        prompts = self._prompts()
        # Ground truth: ONE engine serves every prompt (greedy, so
        # batch composition and slot placement cannot change tokens).
        single = make("ref").engine
        rid_of = {
            i: single.submit(p, max_new_tokens=5)
            for i, p in enumerate(prompts)
        }
        single_out = single.run()
        expected = {i: single_out[rid] for i, rid in rid_of.items()}

        replicas = [make(f"r{i}") for i in range(2)]
        router = FleetRouter(replicas, seed=0)
        records = {}
        submitted = {}
        # The recurring-template phase on the 2-replica fleet.
        for i in range(len(self.ORDER)):
            submitted[router.submit(
                prompts[i], max_new_tokens=5
            )] = i
        for _ in range(3):
            router.step()
            records.update(router.drain_done_records())
        # Mid-run scale-up: a third replica joins and is routable;
        # the fresh-template burst that follows load-balances onto
        # the least-loaded candidate — the newcomer.
        spare = make("spare")
        router.add_replica(spare)
        assert len(router.active_handles()) == 3
        for i in range(len(self.ORDER), len(prompts)):
            submitted[router.submit(
                prompts[i], max_new_tokens=5
            )] = i
        # Drain-based scale-down of one ORIGINAL replica mid-run:
        # nothing new lands on it (routing invariant) and everything
        # it owns finishes (the engine seam's let-resident-finish).
        victim = next(
            h for h in router.active_handles()
            if h.replica is replicas[0]
        )
        routed_at_drain = victim.routed
        router.start_drain(victim)
        with pytest.raises(ValueError):
            # The engine-level seam backs the routing invariant.
            victim.replica.engine.submit(
                prompts[0], max_new_tokens=5
            )
        while router.has_work:
            router.step()
            records.update(router.drain_done_records())
        records.update(router.drain_done_records())
        # Zero dropped or errored: every submitted request finished
        # with tokens, and the drained replica took nothing new.
        assert sorted(records) == sorted(submitted)
        assert victim.routed == routed_at_drain
        assert not victim.replica.has_work
        router.retire(victim)
        assert len(router.replicas) == 2
        served_by = {}
        for rid, rec in records.items():
            served_by.setdefault(rec["replica"], 0)
            served_by[rec["replica"]] += 1
            assert rec["tokens"] == expected[submitted[rid]], (
                "fleet routing changed a request's tokens"
            )
        # The admitted replica actually served traffic.
        assert served_by.get("spare", 0) >= 1
        # Round-robin on the SAME trace: every recurring template
        # pays its cold prefill on BOTH replicas (t0/t1/t2: 2 misses
        # each) where affinity pays it once — the fleet-level metric
        # the routing policy exists to win.
        rr = FleetRouter(
            [make("rr0"), make("rr1")],
            policy="round_robin", seed=0,
        )
        for prompt in prompts:
            rr.submit(prompt, max_new_tokens=5)
        rr.run()
        assert router.prefix_hit_rate > rr.prefix_hit_rate
        # Late traffic after retirement still serves.
        late = router.submit(prompts[0], max_new_tokens=5)
        router_out = router.run()
        assert router_out[late] == expected[0]


class TestDisaggregatedFleet:
    """KV block shipping + two-stage prefill/decode placement: the
    router ships CACHED BLOCKS to wherever it routes a request (the
    fleet-global prefix cache), hands first-token streams from
    prefill-role to decode-role replicas, and evacuates a draining
    replica's residents to a peer — all without changing a single
    emitted token."""

    def test_affinity_key_is_the_trie_block_key(self):
        """Satellite pin: the router's affinity key and the engine
        trie's block identity share ONE key function — same block
        granularity (BLOCK_TOKENS == PAGE_ROWS), same sub-block
        None, and `prefix_key` IS `route_key`."""
        from walkai_nos_tpu.models.block_key import (
            BLOCK_TOKENS,
            chain_hashes,
            route_key,
        )

        assert BLOCK_TOKENS == PAGE_ROWS
        p = _template(0)
        assert prefix_key(p) == route_key(p)
        assert prefix_key(p[: PAGE_ROWS - 1]) is None
        # One full block -> one path hash; the router can name an
        # engine's trie blocks from the prompt alone.
        assert len(chain_hashes(p)) == 1

    def test_repoint_ships_blocks_ahead_of_the_request(self, fleet):
        """The global-cache win, pinned deterministically: a template
        warm on r0 whose affinity re-points to r1 has its blocks
        SHIPPED to r1 before the request is submitted there — r1's
        admission hits on a block it never prefilled, and the tokens
        are identical to the warm replica's."""
        _, make = fleet
        router = FleetRouter([make("ship0"), make("ship1")], seed=0)
        p = _template(0)  # 136 tokens -> 1 shareable block
        key = prefix_key(p)
        first = router.submit(p, max_new_tokens=4)
        out0 = router.run()
        home = router._block_home[key]
        cold = next(
            h for h in router.active_handles() if h is not home
        )
        assert cold.replica.engine.prefix_stats()["block_hits"] == 0
        router._affinity[key] = cold  # forced re-point
        second = router.submit(p, max_new_tokens=4)
        out1 = router.run()
        assert out1[second] == out0[first]
        assert int(router.obs.xfer_ships.value(
            labels={"outcome": "ok"}
        )) == 1
        assert int(router.obs.xfer_blocks_shipped.value()) == 1
        # The cold replica hit on a block it never prefilled.
        assert cold.replica.engine.prefix_stats()["block_hits"] == 1

    def test_ship_accounts_wire_bytes_by_dtype(self, fleet):
        """Every shipped payload lands in
        `router_xfer_bytes_total{dtype}` (decoded tile bytes, not
        b64 envelope) and in `router.stats()['xfer_bytes']` — the
        capacity-planning ledger behind the disaggregation plane:
        per-dtype so an int8-KV fleet's wire savings are visible.
        The tally must equal the payload's own decoded tile sizes."""
        _, make = fleet
        router = FleetRouter([make("wb0"), make("wb1")], seed=0)
        assert router.stats()["xfer_bytes"] == {}
        p = _template(9)
        router.submit(p, max_new_tokens=4)
        router.run()
        key = prefix_key(p)
        home = router._block_home[key]
        cold = next(
            h for h in router.active_handles() if h is not home
        )
        # The expected wire size, from the exporter's own payload.
        from walkai_nos_tpu.models.block_key import chain_hashes

        payload = home.replica.export_blocks(chain_hashes(p))
        want: dict = {}
        for t in payload["tiles"] + payload.get("draft_tiles", []):
            dt = str(t["dtype"])
            want[dt] = want.get(dt, 0) + len(t["data"]) * 3 // 4
        assert want and all(v > 0 for v in want.values())
        router._affinity[key] = cold  # forced re-point -> ship
        router.submit(p, max_new_tokens=4)
        router.run()
        got = router.stats()["xfer_bytes"]
        assert got == want
        for dt, nbytes in want.items():
            assert int(router.obs.xfer_bytes.value(
                labels={"dtype": dt}
            )) == nbytes

    def test_transfer_plane_is_noop_for_bare_replicas(self):
        """Replicas without the export/import surface (HTTP pods
        behind old servers, scripted fakes) opt out silently: the
        ship path never fires and routing is unchanged."""
        fakes = [FakeReplica("bare0"), FakeReplica("bare1")]
        router = FleetRouter(fakes, seed=0)
        p = _template(3)
        router.submit(p, max_new_tokens=4)
        h1 = next(
            h for h in router.active_handles()
            if h.replica is fakes[1]
        )
        router._affinity[prefix_key(p)] = h1
        router.submit(p, max_new_tokens=4)
        router.run()
        for outcome in ("ok", "empty", "error"):
            assert router.obs.xfer_ships.value(
                labels={"outcome": outcome}
            ) == 0
        # Drain with migration requested is equally a no-op.
        router.start_drain(h1, migrate=True)
        assert h1.replica.draining

    def test_two_stage_handoff_token_identity(self, fleet):
        """Role-split fleet (1 prefill + 1 decode): every prompt
        lands on the prefill replica, its stream moves to the decode
        replica at the first committed token, and the finished
        records — collected from the DECODE replica under the
        original router rids — are token-identical to one engine."""
        _, make = fleet
        single = make("tsref").engine
        prompts = [_template(40 + i) for i in range(2)]
        expected = {}
        for i, p in enumerate(prompts):
            rid = single.submit(p, max_new_tokens=12)
            expected[i] = single.run()[rid]
        pf, dc = make("pf0"), make("dc0")
        router = FleetRouter(seed=0)
        router.add_replica(pf, role="prefill")
        router.add_replica(dc, role="decode")
        assert router.disaggregated
        rids = {
            router.submit(p, max_new_tokens=12): i
            for i, p in enumerate(prompts)
        }
        records = {}
        while router.has_work:
            router.step()
            records.update(router.drain_done_records())
        records.update(router.drain_done_records())
        assert sorted(records) == sorted(rids)
        assert int(router.obs.xfer_migrations.value(
            labels={"outcome": "decode"}
        )) >= 1
        for rid, rec in records.items():
            assert rec["tokens"] == expected[rids[rid]], (
                "stage handoff changed a request's tokens"
            )
        # At least one stream finished on the decode replica.
        assert any(r["replica"] == "dc0" for r in records.values())

    def test_drain_migration_evacuates_to_peer(self, fleet):
        """start_drain on a replica holding live streams moves them
        to the peer instead of waiting them out: the victim is empty
        IMMEDIATELY after the drain call, and every request finishes
        token-identical to an uninterrupted engine."""
        _, make = fleet
        single = make("dmref").engine
        prompts = [_template(60 + i) for i in range(2)]
        expected = {}
        for i, p in enumerate(prompts):
            rid = single.submit(p, max_new_tokens=12)
            expected[i] = single.run()[rid]
        replicas = [make("dm0"), make("dm1")]
        router = FleetRouter(replicas, seed=0)
        rids = {
            router.submit(p, max_new_tokens=12): i
            for i, p in enumerate(prompts)
        }
        records = {}
        for _ in range(2):
            router.step()
            records.update(router.drain_done_records())
        victim = router._routes[next(iter(rids))][0]
        assert victim.replica.has_work
        router.start_drain(victim)
        assert not victim.replica.has_work  # evacuated, not awaited
        assert int(router.obs.xfer_migrations.value(
            labels={"outcome": "moved"}
        )) >= 1
        while router.has_work:
            router.step()
            records.update(router.drain_done_records())
        records.update(router.drain_done_records())
        assert sorted(records) == sorted(rids)
        for rid, rec in records.items():
            assert rec["tokens"] == expected[rids[rid]], (
                "drain migration changed a request's tokens"
            )

    def test_capture_digests_disagg_equals_colocated(
        self, fleet, tmp_path
    ):
        """The acceptance claim through the PR-15 capture plane: a
        disaggregated fleet (prefill/decode split + a mid-run
        drained-replica migration) serves mixed ragged traffic with
        per-request token digests IDENTICAL to the colocated fleet's
        capture — the replay artifact proves migrated streams
        bit-exact, not just the in-memory records."""
        _, make = fleet
        rng = np.random.default_rng(3)
        bases = [_template(80 + t, extra=0) for t in range(2)]
        prompts = []
        for i in range(6):
            tail = rng.integers(0, 64, 4 + 3 * (i % 3)).astype(
                np.int32
            )
            prompts.append(np.concatenate([bases[i % 2], tail]))
        prompts.append(_prompt_short())

        def digests(capture_dir):
            from walkai_nos_tpu.obs.capture import CaptureLog

            text = CaptureLog(str(capture_dir)).read_text()
            out = {}
            for line in text.splitlines():
                rec = json.loads(line)
                if rec.get("kind") == "done":
                    out[rec["rid"]] = rec["digest"]
            return out

        co_dir = tmp_path / "colocated"
        router = FleetRouter(
            [make("co0"), make("co1")], seed=0,
            capture=str(co_dir),
        )
        for p in prompts:
            router.submit(p, max_new_tokens=12)
        router.run()
        co = digests(co_dir)

        dis_dir = tmp_path / "disagg"
        dis = FleetRouter(seed=0, capture=str(dis_dir))
        dis.add_replica(make("dg0"), role="prefill")
        handles = {}
        for name in ("dg1", "dg2"):
            dis.add_replica(make(name), role="decode")
        for h in dis.active_handles():
            handles[h.name] = h
        for p in prompts:
            dis.submit(p, max_new_tokens=12)
        # Step until a decode replica holds migrated streams, then
        # drain it mid-run — its residents move AGAIN, to a peer.
        drained = False
        for _ in range(40):
            dis.step()
            if not drained:
                busy = next(
                    (
                        h for h in dis.active_handles()
                        if h.role == "decode"
                        and h.replica.has_work
                    ),
                    None,
                )
                if busy is not None:
                    dis.start_drain(busy)
                    drained = True
            if not dis.has_work:
                break
        assert drained, "no decode replica ever held a stream"
        dis.run()
        assert int(dis.obs.xfer_migrations.value(
            labels={"outcome": "decode"}
        )) >= 1
        moved = dis.obs.xfer_migrations.value(
            labels={"outcome": "moved"}
        ) + dis.obs.xfer_migrations.value(
            labels={"outcome": "returned"}
        )
        assert moved >= 1, "the drain never migrated a resident"
        di = digests(dis_dir)
        assert sorted(di) == sorted(co)
        for rid, digest in co.items():
            assert digest is not None
            assert di[rid] == digest, (
                f"rid {rid}: disaggregated digest diverged"
            )


def _prompt_short(seed=9, n=20):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 64, n).astype(np.int32)


@pytest.mark.slow
class TestTrafficBench:
    """The full traffic-replay harness (diurnal + flash-crowd +
    Zipf): slow lane — the tier-1 budget holds only the e2e above,
    which already pins the affinity-beats-round-robin claim; this
    exercises the surge/steady split and the bench-key plumbing on
    larger sizes."""

    def test_harness_emits_keys_and_beats_round_robin(self, fleet):
        params, _ = fleet
        result = run_traffic_benchmark(
            n_replicas=2, requests=24, templates=4, ticks=12,
            slots=2, max_new=4, seed=0, cfg=CFG, params=params,
        )
        assert result.completed == result.requests == 24
        assert result.errored == 0
        assert result.prefix_hit_rate is not None
        assert result.rr_prefix_hit_rate is not None
        assert result.prefix_hit_rate > result.rr_prefix_hit_rate
        keys = result.bench_keys()
        assert keys["router_prefix_hit_rate"] == pytest.approx(
            result.prefix_hit_rate, abs=1e-4
        )
        assert "router_ttft_p99_under_surge" in keys
        assert keys["router_scale_events_total"] == 0
        assert len(result.per_request_tokens) == 24

    def test_disagg_arm_token_identical_and_wins_hit_rate(
        self, fleet
    ):
        """The disaggregation arm of the SAME replay: a role-split
        prefill/decode fleet with block shipping completes every
        request token-identical to the colocated arm, and the
        fleet-global cache beats both round-robin and
        per-replica-cache (ship_blocks=False) affinity on the Zipf
        trace's prefix hit rate."""
        params, _ = fleet
        result = run_traffic_benchmark(
            n_replicas=2, requests=24, templates=4, ticks=12,
            slots=2, max_new=4, seed=0, cfg=CFG, params=params,
            compare_disaggregated=True,
        )
        assert result.disagg_completed == result.requests == 24
        assert (
            result.disagg_per_request_tokens
            == result.per_request_tokens
        ), "disaggregation changed request tokens"
        assert (
            result.disagg_prefix_hit_rate > result.rr_prefix_hit_rate
        )
        assert (
            result.disagg_prefix_hit_rate
            >= result.noship_prefix_hit_rate
        )
        keys = result.bench_keys()
        assert "router_disagg_ttft_p99" in keys
        assert keys["router_disagg_prefix_hit_rate"] == pytest.approx(
            result.disagg_prefix_hit_rate, abs=1e-4
        )
        assert "router_noship_prefix_hit_rate" in keys


class TestServerouterEndpoints:
    @pytest.fixture()
    def server(self):
        from walkai_nos_tpu.cmd.serverouter import (
            RouterDriver,
            RouterServer,
            make_handler,
        )
        from walkai_nos_tpu.obs.router import RouterObs

        obs = RouterObs()
        router = FleetRouter(
            [FakeReplica("a"), FakeReplica("b")], obs=obs, seed=0,
        )
        driver = RouterDriver(router, idle_tick_s=0.01)
        httpd = RouterServer(
            ("127.0.0.1", 0), make_handler(driver, obs)
        )
        thread = threading.Thread(
            target=httpd.serve_forever, daemon=True
        )
        thread.start()
        try:
            yield f"http://127.0.0.1:{httpd.server_address[1]}"
        finally:
            httpd.shutdown()
            driver.stop()

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()

    def test_generate_healthz_metrics(self, server):
        body = json.dumps({
            "prompt": list(range(1, 10)), "max_new_tokens": 4,
        }).encode()
        req = urllib.request.Request(
            f"{server}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == [1]  # the fake replica's record
        assert out["replica"] in ("a", "b")
        status, payload = self._get(f"{server}/healthz")
        health = json.loads(payload)
        assert status == 200 and health["ok"] is True
        assert health["fleet"]["active"] == 2
        assert {r["name"] for r in health["fleet"]["replicas"]} == {
            "a", "b",
        }
        status, payload = self._get(f"{server}/metrics")
        text = payload.decode()
        assert status == 200
        assert "router_requests_total 1" in text
        assert "router_replicas" in text

    def test_bad_request_is_400(self, server):
        req = urllib.request.Request(
            f"{server}/generate", data=b'{"prompt": []}',
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400

    def test_parse_args_http_mode(self):
        from walkai_nos_tpu.cmd.serverouter import parse_args

        args = parse_args([
            "--replica", "http://r0:8000",
            "--replica", "http://r1:8000",
            "--port", "9000",
        ])
        assert args.replica == [
            "http://r0:8000", "http://r1:8000",
        ]
        assert args.port == 9000

    def test_spares_rejected_in_http_mode(self):
        """HTTP mode has no slice provider — silently ignoring an
        autoscaling flag would read as autoscaling-enabled."""
        from walkai_nos_tpu.cmd.serverouter import parse_args

        for flags in (
            ["--spares", "1"],
            ["--min-replicas", "2"],
            ["--max-replicas", "4"],
        ):
            with pytest.raises(SystemExit):
                parse_args(["--replica", "http://r0:8000", *flags])

    def test_respawning_provider_restores_capacity(self):
        """Each release rebuilds a WARMED standby (a drained engine
        is one-way), so a diurnal scale-down never permanently eats
        fleet capacity — the static CI provider would ratchet the
        binary down to min_replicas forever."""
        from walkai_nos_tpu.cmd.serverouter import (
            RespawningSliceProvider,
        )

        class _Warmable(FakeReplica):
            warmed = 0

            def warm(self):
                _Warmable.warmed += 1

        provider = RespawningSliceProvider(
            lambda name: _Warmable(name), spares=1
        )
        assert _Warmable.warmed == 1  # the standby pre-warms
        first = provider.acquire()
        assert first is not None
        assert provider.acquire() is None  # cap honored
        provider.release(first)
        assert _Warmable.warmed == 2  # fresh standby, warmed at release
        second = provider.acquire()
        assert second is not None and second is not first

    def test_replica_stepping_contract(self):
        """The driver loop spins only for replicas whose work needs
        step() (in-process engines); an HTTP replica's work advances
        remotely, so a pure-HTTP fleet must let the driver sleep
        between collection ticks instead of pinning a core."""
        from walkai_nos_tpu.router.replica import (
            EngineReplica,
            HttpReplica,
        )

        assert EngineReplica.steps_locally is True
        assert HttpReplica.steps_locally is False


# -- fleet observability plane (tracing / federation / anomaly) --------


from walkai_nos_tpu.obs.anomaly import FlightRecorder  # noqa: E402
from walkai_nos_tpu.obs.federation import (  # noqa: E402
    parse_exposition,
)


class FleetFake(FakeReplica):
    """FakeReplica plus scripted fleet-plane surfaces: windowed
    straggler signals and a tiny real exposition (rendered shape, so
    the federator parses exactly what an engine would serve)."""

    def __init__(self, name, sat=0.0, dispatch_p99=0.01):
        super().__init__(name, sat)
        self.dispatch_p99 = dispatch_p99

    def obs_signals(self):
        return {
            "dispatch_p99_s": self.dispatch_p99,
            "device_step_ms": None,
            "roofline_fraction": None,
        }

    def metrics_text(self):
        return (
            "# TYPE cb_requests_submitted_total counter\n"
            f"cb_requests_submitted_total {self.submits}\n"
            "# TYPE cb_slo_dispatch_p99 gauge\n"
            f"cb_slo_dispatch_p99 {self.dispatch_p99}\n"
        )


class TestScrapeErrorAccounting:
    def test_dead_pod_errors_counted_not_swallowed(self):
        """Every failed HttpReplica scrape lands in a labeled counter
        and in `router.stats()` per handle (`last_error`,
        `last_ok_age_s`) — a flapping pod used to read only as
        'unreachable right now' with no history."""
        from walkai_nos_tpu.router.replica import HttpReplica

        # Port 9 (discard) refuses instantly — a dead pod.
        dead = HttpReplica("http://127.0.0.1:9", workers=1,
                           refresh_s=0.0)
        router = FleetRouter([dead], seed=0, fleet_refresh_s=0.0)
        router.step()
        stats = dead.scrape_error_stats()
        # One step touches all three endpoints (healthz via the load
        # read, stats via the prefix tallies, metrics via the
        # straggler signals).
        assert all(
            stats["counts"][kind] >= 1
            for kind in ("healthz", "stats", "metrics")
        )
        assert stats["last_error"]
        assert stats["last_ok_age_s"] is None  # never succeeded
        for kind in ("healthz", "stats", "metrics"):
            assert router.obs.scrape_errors.value(labels={
                "replica": dead.name, "kind": kind,
            }) >= 1
        per_replica = router.stats()["replicas"][0]
        assert per_replica["scrape"]["counts"]["healthz"] >= 1
        assert per_replica["scrape"]["last_error"]

    def test_engine_replica_has_no_scrape_block(self, fleet):
        _, make = fleet
        router = FleetRouter([make("noscrape")], seed=0)
        assert router.stats()["replicas"][0]["scrape"] is None


class TestStragglerDetection:
    def _router(self, tmp_path, **kwargs):
        good0 = FleetFake("good0", dispatch_p99=0.01)
        good1 = FleetFake("good1", dispatch_p99=0.011)
        bad = FleetFake("bad", dispatch_p99=0.1)
        recorder = FlightRecorder(
            str(tmp_path), keep=4, min_interval_s=0.0
        )
        router = FleetRouter(
            [good0, good1, bad], seed=0, fleet_refresh_s=0.0,
            flight=recorder, **kwargs,
        )
        return router, (good0, good1, bad), recorder

    def test_straggler_flips_gauge_loses_share_dumps_flight(
        self, tmp_path
    ):
        """The acceptance scenario on scripted fakes: a replica with
        ~10x the fleet's dispatch p99 flips
        `router_replica_anomaly{replica="bad"}` after a few refresh
        ticks, measurably loses routing share vs the healthy
        replicas (the score becomes a load penalty), and produces a
        flight bundle readable from the recorder."""
        router, (good0, good1, bad), recorder = self._router(tmp_path)
        for _ in range(6):
            router.step()
            if router.anomaly_flagged_names():
                break
        assert router.anomaly_flagged_names() == ["bad"]
        assert router.obs.replica_anomaly.value(
            labels={"replica": "bad"}
        ) == 1.0
        assert router.obs.replica_anomaly.value(
            labels={"replica": "good0"}
        ) == 0.0
        assert router.obs.replica_anomaly_score.value(
            labels={"replica": "bad"}
        ) >= 3.0
        # Routing share: short prompts carry no affinity key, so
        # every pick is a two-choice sample over penalized loads —
        # the flagged straggler loses every pairing it is drawn
        # into.
        before = bad.submits
        for seed in range(30):
            router.submit([1 + seed % 8], max_new_tokens=2)
        assert bad.submits == before  # sheds ALL p2c share
        assert good0.submits + good1.submits >= 30
        # The flip dumped exactly one bundle, with the evidence an
        # operator needs after the fact.
        bundles = recorder.bundles()
        assert len(bundles) == 1
        bundle = bundles[0]
        assert bundle["trigger"] == "anomaly"
        assert bundle["replica"] == "bad"
        assert bundle["window_signals"]["bad"]["dispatch_p99_s"] == (
            0.1
        )
        assert bundle["anomaly"]["bad"]["flagged"] is True
        assert any(
            r["name"] == "bad"
            for r in bundle["fleet"]["replicas"]
        )
        assert isinstance(bundle["trace_ring"], list)
        assert int(router.obs.flight_dumps.value(
            labels={"trigger": "anomaly"}
        )) == 1
        # Per-replica stats carry the verdict.
        per = {
            r["name"]: r for r in router.stats()["replicas"]
        }
        assert per["bad"]["anomaly"]["flagged"] is True
        assert per["good0"]["anomaly"]["flagged"] is False

    def test_recovery_clears_flag_and_restores_share(self, tmp_path):
        router, (good0, good1, bad), _ = self._router(tmp_path)
        for _ in range(6):
            router.step()
            if router.anomaly_flagged_names():
                break
        bad.dispatch_p99 = 0.01  # replica recovers
        for _ in range(12):
            router.step()
            if not router.anomaly_flagged_names():
                break
        assert router.anomaly_flagged_names() == []
        before = bad.submits
        for seed in range(30):
            router.submit([1 + seed % 8], max_new_tokens=2)
        assert bad.submits > before  # share restored

    def test_reconciler_drains_flagged_victim_first(self, tmp_path):
        """The drain hint: an idle scale-down rotates the flagged
        straggler out (not the least-loaded healthy replica), and
        the decision lands on the trace ring with reason
        'anomaly'."""
        from walkai_nos_tpu.router.autoscale import (
            ScalePolicy,
            StaticSliceProvider,
        )

        good0 = FleetFake("good0", dispatch_p99=0.01)
        good1 = FleetFake("good1", dispatch_p99=0.011)
        bad = FleetFake("bad", dispatch_p99=0.1)
        provider = StaticSliceProvider([])
        router = FleetRouter(
            [good0, good1, bad], seed=0, fleet_refresh_s=0.0,
            provider=provider,
            flight=FlightRecorder(
                str(tmp_path), min_interval_s=0.0
            ),
            scale_policy=ScalePolicy(
                min_replicas=1, max_replicas=3,
                idle_ticks=8, cooldown_ticks=2,
            ),
        )
        for _ in range(20):
            router.step()
            if bad.draining:
                break
        assert bad.draining is True
        assert not good0.draining and not good1.draining
        events = {
            e["name"]: e for e in router.trace.ring.snapshot()
        }
        drain = events["drain_start"]
        assert drain["args"]["replica"] == "bad"
        assert drain["args"]["reason"] == "anomaly"
        assert "loads" in drain["args"]["signals"]
        # Drain completes -> retire + release land on the ring too,
        # and every per-replica series of the retired member drops.
        for _ in range(5):
            router.step()
        assert bad not in [h.replica for h in router._handles]
        names = {e["name"] for e in router.trace.ring.snapshot()}
        assert {"release", "retire"} <= names
        assert router.obs.replica_anomaly.value(
            labels={"replica": "bad"}
        ) is None
        assert router.obs.replica_anomaly_score.value(
            labels={"replica": "bad"}
        ) is None


    def test_anomaly_evacuation_fires_without_idle_window(
        self, tmp_path
    ):
        """A flagged replica is auto-drained NOW — the reconciler's
        evacuation step, not the idle scale-down: the fleet sits at
        moderate load (neither idle nor pressured, so neither
        hysteresis counter can ever fire) and the drain must still
        start within a few ticks of the flag, migrate-first through
        the normal `start_drain` seam, with reason='anomaly' on the
        trace ring."""
        from walkai_nos_tpu.router.autoscale import (
            ScalePolicy,
            StaticSliceProvider,
        )

        good0 = FleetFake("good0", sat=0.5, dispatch_p99=0.01)
        good1 = FleetFake("good1", sat=0.5, dispatch_p99=0.011)
        bad = FleetFake("bad", sat=0.5, dispatch_p99=0.1)
        router = FleetRouter(
            [good0, good1, bad], seed=0, fleet_refresh_s=0.0,
            provider=StaticSliceProvider([]),
            flight=FlightRecorder(
                str(tmp_path), min_interval_s=0.0
            ),
            # idle_ticks far beyond the loop below: if the drain
            # fires, it can only be the evacuation step.
            scale_policy=ScalePolicy(
                min_replicas=1, max_replicas=3,
                idle_ticks=10_000, breach_ticks=10_000,
                cooldown_ticks=2,
            ),
        )
        for _ in range(20):
            router.step()
            if bad.draining:
                break
        assert bad.draining is True
        assert not good0.draining and not good1.draining
        events = {
            e["name"]: e for e in router.trace.ring.snapshot()
        }
        drain = events["drain_start"]
        assert drain["args"]["replica"] == "bad"
        assert drain["args"]["reason"] == "anomaly"
        assert router.scale_events()["down"] == 1

    def test_anomaly_evacuation_respects_min_replicas(
        self, tmp_path
    ):
        """min_replicas floors the evacuation exactly like a
        scale-down: with the whole fleet at the floor, a flagged
        replica keeps serving (the detector still penalizes its
        routing share) rather than shrinking the fleet below
        policy."""
        from walkai_nos_tpu.router.autoscale import (
            ScalePolicy,
            StaticSliceProvider,
        )

        good0 = FleetFake("good0", sat=0.5, dispatch_p99=0.01)
        good1 = FleetFake("good1", sat=0.5, dispatch_p99=0.011)
        bad = FleetFake("bad", sat=0.5, dispatch_p99=0.1)
        router = FleetRouter(
            [good0, good1, bad], seed=0, fleet_refresh_s=0.0,
            provider=StaticSliceProvider([]),
            flight=FlightRecorder(
                str(tmp_path), min_interval_s=0.0
            ),
            scale_policy=ScalePolicy(
                min_replicas=3, max_replicas=3,
                idle_ticks=10_000, breach_ticks=10_000,
                cooldown_ticks=2,
            ),
        )
        for _ in range(12):
            router.step()
        assert router.anomaly_flagged_names() == ["bad"]
        assert not bad.draining
        assert router.scale_events()["down"] == 0


class TestReconcilerTraceEvents:
    def test_scale_up_event_carries_reason_and_signals(self):
        from walkai_nos_tpu.router.autoscale import (
            ScalePolicy,
            StaticSliceProvider,
        )

        base = FleetFake("base", sat=0.95)
        spare = FleetFake("spare")
        router = FleetRouter(
            [base], seed=0, fleet_refresh_s=0.0,
            provider=StaticSliceProvider([spare]),
            scale_policy=ScalePolicy(
                min_replicas=1, max_replicas=2, breach_ticks=2,
                cooldown_ticks=2,
            ),
        )
        for _ in range(4):
            router.step()
        events = [
            e for e in router.trace.ring.snapshot()
            if e["name"] == "scale_up"
        ]
        assert len(events) == 1
        args = events[0]["args"]
        assert args["replica"] == "spare"
        assert args["reason"] == "saturation"
        assert args["signals"]["loads"]["base"] == 0.95


class TestMetricsFederation:
    def test_replica_series_federated_and_dropped_on_retire(self):
        a = FleetFake("a")
        b = FleetFake("b")
        router = FleetRouter([a, b], seed=0, fleet_refresh_s=0.0)
        for seed in range(4):
            router.submit(_template(seed), max_new_tokens=2)
        router.step()
        text = router.federated_metrics()
        # Router's own series AND both replicas' engine series under
        # distinct replica labels, in one exposition.
        assert "router_requests_total 4" in text
        assert 'cb_requests_submitted_total{replica="a"}' in text
        assert 'cb_requests_submitted_total{replica="b"}' in text
        families = parse_exposition(text)
        assert families["cb_requests_submitted_total"]["kind"] == (
            "counter"
        )
        values = {
            labels["replica"]: value
            for _, labels, value in families[
                "cb_requests_submitted_total"
            ]["samples"]
        }
        assert values == {"a": float(a.submits), "b": float(b.submits)}
        # Retire one: its federated series AND per-replica gauges
        # drop from the very next render.
        victim = next(
            h for h in router.active_handles() if h.replica is a
        )
        router.start_drain(victim)
        router.step()
        router.retire(victim)
        text = router.federated_metrics()
        assert 'replica="a"' not in text
        assert 'cb_requests_submitted_total{replica="b"}' in text
        assert router.obs.replica_saturation.value(
            labels={"replica": "a"}
        ) is None

    def test_obs_disabled_plane_is_off(self):
        """obs=False disables the WHOLE fleet plane (the off arm of
        router_obs_overhead_pct): no-op registry, disabled trace, no
        detector, no flight recorder — and routing still works."""
        a, b = FleetFake("a"), FleetFake("b")
        router = FleetRouter([a, b], seed=0, obs=False)
        router.submit(_template(0), max_new_tokens=2)
        router.step()
        assert router.federated_metrics() == "\n"
        assert router.trace.enabled is False
        assert router.flight is None
        assert router._anomaly is None
        stats = router.stats()
        assert stats["obs_disabled"] is True
        assert stats["replicas"][0]["anomaly"] is None


class TestFleetTraceEndToEnd:
    """The acceptance e2e: requests through a ≥2-replica fleet yield
    ONE merged /debug/trace whose router spans and engine lifecycle
    spans share the request's trace id, with span-derived TTFT equal
    to `drain_done_records()` TTFT EXACTLY (the PR 3 convention,
    surviving the merge); the federated /metrics carries both
    replicas' cb_* series and drops them on retire."""

    def test_merged_trace_and_exact_ttft(self, fleet):
        _, make = fleet
        r0, r1 = make("tr0"), make("tr1")
        for replica in (r0, r1):
            replica.warm()
        router = FleetRouter([r0, r1], seed=0)
        prompts = [_template(200 + t) for t in range(3)]
        rids = [
            router.submit(p, max_new_tokens=4) for p in prompts
        ]
        records = {}
        while router.has_work:
            router.step()
            records.update(router.drain_done_records())
        records.update(router.drain_done_records())
        assert sorted(records) == sorted(rids)
        merged = router.fleet_trace()
        assert set(
            merged["otherData"]["processes"].values()
        ) == {"router", "replica tr0", "replica tr1"}
        events = [
            e for e in merged["traceEvents"] if e.get("ph") != "M"
        ]
        # One merged timeline: strictly ordered timestamps.
        assert [e["ts"] for e in events] == sorted(
            e["ts"] for e in events
        )
        for rid, rec in records.items():
            trace_id = rec["trace_id"]
            assert trace_id  # router-minted, on the record
            route = next(
                e for e in events if e["name"] == "route"
                and e["args"]["trace_id"] == trace_id
            )
            decode = next(
                e for e in events if e["name"] == "decode"
                and e["args"].get("trace_id") == trace_id
            )
            queued = next(
                e for e in events if e["name"] == "queued"
                and e["args"].get("trace_id") == trace_id
            )
            # Router route -> engine queued -> engine decode, in
            # order on the merged clock; the engine process the
            # spans landed in is the replica that served it.
            assert route["ts"] <= queued["ts"] <= decode["ts"]
            served = merged["otherData"]["processes"][
                str(decode["pid"])
            ]
            assert served == f"replica {rec['replica']}"
            # Span-derived TTFT == record-derived TTFT, EXACTLY.
            assert decode["args"]["ttft_s"] == rec["ttft_s"]
            assert decode["args"]["wall_s"] == rec["wall_s"]

    def test_serverouter_merged_endpoints(self, fleet):
        """The same plane over the real binary surface: POST
        /generate returns the trace id (header + field), GET
        /debug/trace serves the merged timeline containing that id
        in both the router's and the engine's spans, GET /metrics
        federates both replicas' engine series, GET /debug/flight
        answers."""
        from walkai_nos_tpu.cmd.serverouter import (
            RouterDriver,
            RouterServer,
            make_handler,
        )
        from walkai_nos_tpu.obs.router import RouterObs

        _, make = fleet
        replicas = [make("sr0"), make("sr1")]
        for replica in replicas:
            replica.warm()
        obs = RouterObs()
        router = FleetRouter(replicas, obs=obs, seed=0)
        driver = RouterDriver(router, idle_tick_s=0.01)
        httpd = RouterServer(
            ("127.0.0.1", 0), make_handler(driver, obs)
        )
        threading.Thread(
            target=httpd.serve_forever, daemon=True
        ).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            body = json.dumps({
                "prompt": [int(t) for t in _template(300)],
                "max_new_tokens": 3,
            }).encode()
            req = urllib.request.Request(
                f"{base}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
                header_id = resp.headers.get("X-Walkai-Trace")
            trace_id = out["trace_id"]
            assert trace_id and header_id == trace_id
            assert out["tokens"]
            with urllib.request.urlopen(
                f"{base}/debug/trace", timeout=30
            ) as resp:
                merged = json.loads(resp.read())
            names_with_id = {
                e["name"]
                for e in merged["traceEvents"]
                if e.get("args", {}).get("trace_id") == trace_id
            }
            # Router spans (route + queue_wait from the driver's
            # enqueue) AND engine lifecycle spans under ONE id.
            assert {
                "queue_wait", "route", "replica_roundtrip",
                "queued", "decode",
            } <= names_with_id
            assert len(
                merged["otherData"]["processes"]
            ) == 3  # router + both replicas
            with urllib.request.urlopen(
                f"{base}/metrics", timeout=30
            ) as resp:
                text = resp.read().decode()
            assert "router_requests_total 1" in text
            # Both replicas' engine series under distinct labels
            # (warm() traffic guarantees both have series).
            assert 'cb_requests_submitted_total{replica="sr0"}' in (
                text
            )
            assert 'cb_requests_submitted_total{replica="sr1"}' in (
                text
            )
            with urllib.request.urlopen(
                f"{base}/debug/flight", timeout=30
            ) as resp:
                flight = json.loads(resp.read())
            assert flight["dir"]
            assert isinstance(flight["bundles"], list)
        finally:
            httpd.shutdown()
            driver.stop()

"""Decoder LM: forward shapes, training, sequence-parallel ring path."""

import jax
import jax.numpy as jnp
import numpy as np

from walkai_nos_tpu.models.lm import (
    LM_TINY,
    DecoderLM,
    LMConfig,
    init_lm_state,
    make_lm_train_step,
)
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh


def _tokens(cfg, b=8, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, cfg.vocab_size, (b, cfg.max_seq_len)), jnp.int32
    )


class TestDecoderLM:
    def test_forward_shapes(self):
        cfg = LM_TINY
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        logits = model.apply({"params": params}, _tokens(cfg, b=2))
        assert logits.shape == (2, cfg.max_seq_len, cfg.vocab_size)

    def test_causality(self):
        """Future tokens must not affect earlier logits."""
        cfg = LM_TINY
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        toks = _tokens(cfg, b=1)
        logits_a = model.apply({"params": params}, toks)
        toks_b = toks.at[0, -1].set((int(toks[0, -1]) + 1) % cfg.vocab_size)
        logits_b = model.apply({"params": params}, toks_b)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :-1]),
            np.asarray(logits_b[0, :-1]),
            atol=1e-5,
        )
        assert not np.allclose(
            np.asarray(logits_a[0, -1]), np.asarray(logits_b[0, -1])
        )

    def test_train_step_decreases_loss_on_mesh(self):
        cfg = LM_TINY
        mesh = build_mesh(jax.devices())
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0), lr=1e-2)
        step = make_lm_train_step(cfg, mesh, lr=1e-2)
        toks = _tokens(cfg)
        losses = []
        for _ in range(5):
            state, loss = step(state, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_ring_attention_path_matches_local(self):
        """Sequence-parallel (ring) training loss == local-kernel loss."""
        cfg_local = LM_TINY
        cfg_ring = LMConfig(**{**cfg_local.__dict__, "use_ring_attention": True})
        mesh_ring = build_mesh(jax.devices(), axes=MeshAxes(data=2, seq=4))
        mesh_local = build_mesh(jax.devices(), axes=MeshAxes(data=2, model=4))

        state_l = init_lm_state(cfg_local, mesh_local, jax.random.PRNGKey(0))
        state_r = init_lm_state(cfg_ring, mesh_ring, jax.random.PRNGKey(0))
        toks = _tokens(cfg_local)
        _, loss_l = make_lm_train_step(cfg_local, mesh_local)(state_l, toks)
        _, loss_r = make_lm_train_step(cfg_ring, mesh_ring)(state_r, toks)
        np.testing.assert_allclose(
            float(loss_l), float(loss_r), rtol=2e-4
        )


class TestRemat:
    def test_remat_matches_stored_activations(self):
        """jax.checkpoint must not change the math: same params, same
        tokens -> identical loss and gradients, remat on or off."""
        base = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
            max_seq_len=16,
        )
        tokens = _tokens(base, b=4)
        from dataclasses import replace

        from walkai_nos_tpu.models.lm import lm_loss

        losses, grads = [], []
        for remat in (False, True):
            cfg = replace(base, remat=remat)
            model = DecoderLM(cfg)
            params = model.init_params(jax.random.PRNGKey(0))

            def loss_fn(p, model=model):
                return lm_loss(model.apply({"params": p}, tokens), tokens)

            loss, grad = jax.value_and_grad(loss_fn)(params)
            losses.append(float(loss))
            grads.append(grad)
        assert abs(losses[0] - losses[1]) < 1e-6, losses
        for a, b in zip(
            jax.tree_util.tree_leaves(grads[0]),
            jax.tree_util.tree_leaves(grads[1]),
        ):
            assert jnp.allclose(a, b, atol=1e-5)

    def test_pipelined_remat_trains(self):
        from dataclasses import replace

        from walkai_nos_tpu.models.pipelined_lm import (
            init_pipelined_lm_state,
            make_pipelined_lm_train_step,
        )

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
            max_seq_len=16, remat=True,
        )
        mesh = build_mesh(jax.devices(), axes=MeshAxes(pipe=2, data=4))
        state = init_pipelined_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_pipelined_lm_train_step(cfg, mesh, n_microbatches=2)
        tokens = _tokens(cfg, b=8)
        state, loss0 = step(state, tokens)
        state, loss1 = step(state, tokens)
        assert float(loss1) < float(loss0)


class TestUlyssesLM:
    def test_ulysses_matches_local_forward(self):
        """The Ulysses sequence-parallel LM must produce the same logits
        as the single-device forward (same params, same tokens)."""
        cfg_local = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=4,
            max_seq_len=32, dtype="float32",
        )
        from dataclasses import replace

        mesh = build_mesh(jax.devices(), axes=MeshAxes(data=2, seq=4))
        cfg_u = replace(cfg_local, use_ulysses_attention=True)
        model_local = DecoderLM(cfg_local)
        params = model_local.init_params(jax.random.PRNGKey(0))
        tokens = _tokens(cfg_local, b=2)
        expected = model_local.apply({"params": params}, tokens)
        model_u = DecoderLM(cfg_u, mesh)
        got = model_u.apply({"params": params}, tokens)
        assert jnp.allclose(got, expected, atol=2e-3), (
            float(jnp.max(jnp.abs(got - expected)))
        )

    def test_ulysses_lm_trains(self):
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=4,
            max_seq_len=32, use_ulysses_attention=True,
        )
        mesh = build_mesh(jax.devices(), axes=MeshAxes(data=2, seq=4))
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_lm_train_step(cfg, mesh)
        tokens = _tokens(cfg, b=8)
        state, loss0 = step(state, tokens)
        state, loss1 = step(state, tokens)
        assert float(loss1) < float(loss0)


class TestGroupedQueryAttention:
    """GQA (num_kv_heads < num_heads): smaller KV projections + cache,
    same semantics. kv_heads == num_heads must stay byte-identical to
    the default config (checkpoint compatibility)."""

    def test_explicit_full_kv_heads_is_default_layout(self):
        from dataclasses import replace

        cfg = replace(LM_TINY, num_kv_heads=LM_TINY.num_heads)
        a = DecoderLM(LM_TINY).init_params(jax.random.PRNGKey(0))
        b = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        assert jax.tree_util.tree_all(
            jax.tree.map(lambda x, y: bool(jnp.array_equal(x, y)), a, b)
        )

    def test_gqa_forward_and_causality(self):
        from dataclasses import replace

        cfg = replace(LM_TINY, num_kv_heads=2)  # 4 heads -> group 2
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        toks = _tokens(cfg, b=2)
        logits = model.apply({"params": params}, toks)
        assert logits.shape == (2, cfg.max_seq_len, cfg.vocab_size)
        toks_b = toks.at[0, -1].set((int(toks[0, -1]) + 1) % cfg.vocab_size)
        logits_b = model.apply({"params": params}, toks_b)
        np.testing.assert_allclose(
            np.asarray(logits[0, :-1]),
            np.asarray(logits_b[0, :-1]),
            atol=1e-5,
        )

    def test_gqa_shrinks_kv_projection(self):
        from dataclasses import replace

        cfg = replace(LM_TINY, num_kv_heads=1)  # multi-query
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        d = cfg.hidden_dim
        kv_dim = d // cfg.num_heads
        kernel = params["block0"]["attn"]["qkv"]["kernel"]
        assert kernel.shape == (d, d + 2 * kv_dim)

    def test_gqa_trains(self):
        from dataclasses import replace

        cfg = replace(LM_TINY, num_kv_heads=2)
        mesh = build_mesh(jax.devices())
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0), lr=1e-2)
        step = make_lm_train_step(cfg, mesh, lr=1e-2)
        toks = _tokens(cfg)
        losses = []
        for _ in range(5):
            state, loss = step(state, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_bad_kv_heads_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="num_kv_heads"):
            LMConfig(num_heads=8, num_kv_heads=3)
        with pytest.raises(ValueError, match="num_kv_heads"):
            LMConfig(num_heads=8, num_kv_heads=0)


class TestLlamaFamilyConfig:
    """RMSNorm + RoPE + SwiGLU + no-bias (the llama layout) as pure
    model knobs, independent of checkpoint import (tests/test_hf.py
    pins exact parity against transformers)."""

    def _cfg(self):
        from dataclasses import replace

        return replace(
            LM_TINY, norm="rmsnorm", mlp="swiglu", mlp_dim=96,
            rope=True, use_bias=False, head_bias=False, num_kv_heads=2,
        )

    def test_forward_and_causality(self):
        cfg = self._cfg()
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        toks = _tokens(cfg, b=2)
        logits = model.apply({"params": params}, toks)
        assert logits.shape == (2, cfg.max_seq_len, cfg.vocab_size)
        toks_b = toks.at[0, -1].set((int(toks[0, -1]) + 1) % cfg.vocab_size)
        logits_b = model.apply({"params": params}, toks_b)
        np.testing.assert_allclose(
            np.asarray(logits[0, :-1]), np.asarray(logits_b[0, :-1]),
            atol=1e-5,
        )

    def test_no_pos_embed_and_no_biases(self):
        params = DecoderLM(self._cfg()).init_params(jax.random.PRNGKey(0))
        assert "pos_embed" not in params
        block = params["block0"]
        assert "bias" not in block["attn"]["qkv"]
        assert "bias" not in block["gate"] and "bias" not in block["fc2"]
        assert "bias" not in params["norm"]  # RMSNorm is scale-only
        assert block["gate"]["kernel"].shape == (LM_TINY.hidden_dim, 96)

    def test_trains(self):
        cfg = self._cfg()
        mesh = build_mesh(jax.devices())
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0), lr=1e-2)
        step = make_lm_train_step(cfg, mesh, lr=1e-2)
        toks = _tokens(cfg)
        losses = []
        for _ in range(5):
            state, loss = step(state, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_bad_knobs_rejected(self):
        import pytest
        from dataclasses import replace

        with pytest.raises(ValueError, match="norm"):
            replace(LM_TINY, norm="batchnorm")
        with pytest.raises(ValueError, match="mlp"):
            replace(LM_TINY, mlp="relu")

    def test_rope_properties(self):
        """apply_rope is a rotation (norm-preserving), identity at
        position 0, and relative: q.k after rotation depends only on
        the position DIFFERENCE — the property that makes rotary
        embeddings a position encoding at all."""
        from walkai_nos_tpu.models.lm import apply_rope

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((1, 2, 6, 32)), jnp.float32)
        pos = jnp.arange(6)
        rot = apply_rope(x, pos, 10000.0)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(rot), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(rot[:, :, 0]), np.asarray(x[:, :, 0]), atol=1e-6
        )
        q = jnp.asarray(rng.standard_normal((1, 1, 1, 32)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 1, 1, 32)), jnp.float32)

        def dot_at(pq, pk):
            rq = apply_rope(q, jnp.array([pq]), 10000.0)
            rk = apply_rope(k, jnp.array([pk]), 10000.0)
            return float(jnp.sum(rq * rk))

        assert abs(dot_at(5, 3) - dot_at(9, 7)) < 1e-3  # same offset
        assert abs(dot_at(5, 3) - dot_at(5, 4)) > 1e-3  # different

"""Decoder LM: forward shapes, training, sequence-parallel ring path."""

import jax
import jax.numpy as jnp
import numpy as np

from walkai_nos_tpu.models.lm import (
    LM_TINY,
    DecoderLM,
    LMConfig,
    init_lm_state,
    make_lm_train_step,
)
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh


def _tokens(cfg, b=8, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, cfg.vocab_size, (b, cfg.max_seq_len)), jnp.int32
    )


class TestDecoderLM:
    def test_forward_shapes(self):
        cfg = LM_TINY
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        logits = model.apply({"params": params}, _tokens(cfg, b=2))
        assert logits.shape == (2, cfg.max_seq_len, cfg.vocab_size)

    def test_causality(self):
        """Future tokens must not affect earlier logits."""
        cfg = LM_TINY
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        toks = _tokens(cfg, b=1)
        logits_a = model.apply({"params": params}, toks)
        toks_b = toks.at[0, -1].set((int(toks[0, -1]) + 1) % cfg.vocab_size)
        logits_b = model.apply({"params": params}, toks_b)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :-1]),
            np.asarray(logits_b[0, :-1]),
            atol=1e-5,
        )
        assert not np.allclose(
            np.asarray(logits_a[0, -1]), np.asarray(logits_b[0, -1])
        )

    def test_train_step_decreases_loss_on_mesh(self):
        cfg = LM_TINY
        mesh = build_mesh(jax.devices())
        state = init_lm_state(cfg, mesh, jax.random.PRNGKey(0), lr=1e-2)
        step = make_lm_train_step(cfg, mesh, lr=1e-2)
        toks = _tokens(cfg)
        losses = []
        for _ in range(5):
            state, loss = step(state, toks)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_ring_attention_path_matches_local(self):
        """Sequence-parallel (ring) training loss == local-kernel loss."""
        cfg_local = LM_TINY
        cfg_ring = LMConfig(**{**cfg_local.__dict__, "use_ring_attention": True})
        mesh_ring = build_mesh(jax.devices(), axes=MeshAxes(data=2, seq=4))
        mesh_local = build_mesh(jax.devices(), axes=MeshAxes(data=2, model=4))

        state_l = init_lm_state(cfg_local, mesh_local, jax.random.PRNGKey(0))
        state_r = init_lm_state(cfg_ring, mesh_ring, jax.random.PRNGKey(0))
        toks = _tokens(cfg_local)
        _, loss_l = make_lm_train_step(cfg_local, mesh_local)(state_l, toks)
        _, loss_r = make_lm_train_step(cfg_ring, mesh_ring)(state_r, toks)
        np.testing.assert_allclose(
            float(loss_l), float(loss_r), rtol=2e-4
        )

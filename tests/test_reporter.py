"""Reporter suite — the reporter_int_test analogue
(`internal/controllers/migagent/reporter_int_test.go:56`,
`reporter.go:34-123`)."""

from __future__ import annotations

from tests.test_actuator import NODE, RecordingPlugin, advertise  # noqa: F401
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.controllers.tpuagent.reporter import Reporter
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.runtime import Request
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpu.tiling.client import TilingClient
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient


def build(annotations=None, interval=0.25):
    kube = FakeKubeClient()
    kube.create(
        "Node",
        {"metadata": {"name": NODE, "annotations": dict(annotations or {})}},
    )
    tpudev = FakeTpudevClient()
    resources = FakeResourceClient()
    shared = SharedState()
    reporter = Reporter(
        kube,
        TilingClient(resources, tpudev),
        shared,
        NODE,
        refresh_interval=interval,
    )
    return reporter, kube, tpudev, resources, shared


def node_annotations(kube):
    return objects.annotations(kube.get("Node", NODE))


class TestReporter:
    def test_reports_free_and_used_devices(self):
        reporter, kube, tpudev, resources, _ = build()
        tpudev.create_slices(
            [
                Placement("2x2", (0, 0), (2, 2)),
                Placement("2x2", (0, 2), (2, 2)),
            ]
        )
        advertise(resources, tpudev)
        resources.mark_used(tpudev.list_slices()[0].slice_id)
        result = reporter.reconcile(Request(name=NODE))
        annos = node_annotations(kube)
        assert annos[f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-used"] == "1"
        assert annos[f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2-free"] == "1"
        assert result.requeue_after == 0.25

    def test_replaces_all_stale_status_annotations(self):
        # A status annotation for a profile that no longer exists must be
        # nulled, not merged around (`reporter.go:89-103` replace-all).
        stale = {f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1-free": "4"}
        reporter, kube, tpudev, resources, _ = build(annotations=stale)
        tpudev.create_slices([Placement("2x4", (0, 0), (2, 4))])
        advertise(resources, tpudev)
        reporter.reconcile(Request(name=NODE))
        annos = node_annotations(kube)
        assert f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1-free" not in annos
        assert annos[f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x4-free"] == "1"

    def test_echoes_plan_ack(self):
        reporter, kube, _, _, shared = build()
        shared.last_parsed_plan_id = "plan-42"
        reporter.reconcile(Request(name=NODE))
        annos = node_annotations(kube)
        assert annos[constants.ANNOTATION_REPORTED_PARTITIONING_PLAN] == "plan-42"

    def test_no_patch_when_nothing_changed(self):
        reporter, kube, tpudev, resources, _ = build()
        tpudev.create_slices([Placement("2x4", (0, 0), (2, 4))])
        advertise(resources, tpudev)
        reporter.reconcile(Request(name=NODE))
        rv_after_first = kube.get("Node", NODE)["metadata"]["resourceVersion"]
        reporter.reconcile(Request(name=NODE))
        assert (
            kube.get("Node", NODE)["metadata"]["resourceVersion"]
            == rv_after_first
        ), "unchanged state must not patch the node (watch-churn discipline)"

    def test_sharing_reporter_reuses_with_shared_extractor(self):
        # The sharing agent is this same Reporter with the shared-profile
        # extractor (`gpuagent/reporter.go` is structurally the migagent
        # reporter; `cmd/tpusharingagent.py:77-83`).
        from walkai_nos_tpu.tpu.device import Device, DeviceStatus
        from walkai_nos_tpu.tpu.sharing.client import SharingClient
        from walkai_nos_tpu.tpu.sharing.profile import (
            extract_shared_profile_name,
        )

        kube = FakeKubeClient()
        kube.create("Node", {"metadata": {"name": NODE}})
        resources = FakeResourceClient()
        resources.set_allocatable(
            [
                Device(
                    resource_name=constants.RESOURCE_TPU_SHARED_PREFIX + "2c",
                    device_id="share-0",
                    status=DeviceStatus.UNKNOWN,
                    mesh_index=0,
                )
            ]
        )
        reporter = Reporter(
            kube,
            SharingClient(resources),
            SharedState(),
            NODE,
            profile_extractor=extract_shared_profile_name,
        )
        reporter.reconcile(Request(name=NODE))
        annos = node_annotations(kube)
        assert annos[f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2c-free"] == "1"

    def test_report_latch_set_even_on_failure(self):
        # The actuator gate only needs *a* report attempt
        # (`reporter.go:60-62`): a reporter crash must still set the latch.
        reporter, kube, _, _, shared = build()
        kube.delete("Node", NODE)
        try:
            reporter.reconcile(Request(name=NODE))
        except Exception:
            pass
        assert shared.at_least_one_report_since_last_apply()

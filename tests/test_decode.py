"""KV-cache decoding: exact greedy equivalence with the full forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2, max_seq_len=32
)


def _prompt(b=2, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (b, n)), jnp.int32)


class TestGreedyDecode:
    def test_matches_full_forward_argmax(self):
        """Every cached step must produce exactly the token a full
        (uncached) forward pass would pick — the KV cache is an
        optimization, never a semantic change."""
        model = DecoderLM(CFG)
        params = model.init_params(jax.random.PRNGKey(0))
        generate = make_generate_fn(CFG)
        prompt = _prompt()
        out = generate(params, prompt, max_new_tokens=6)
        assert out.shape == (2, 6)
        seq = prompt
        for t in range(6):
            logits = model.apply({"params": params}, seq)
            expect = jnp.argmax(logits[:, -1], axis=-1)
            assert jnp.array_equal(expect, out[:, t]), t
            seq = jnp.concatenate([seq, out[:, t : t + 1]], axis=1)

    def test_single_token(self):
        model = DecoderLM(CFG)
        params = model.init_params(jax.random.PRNGKey(0))
        generate = make_generate_fn(CFG)
        out = generate(params, _prompt(), max_new_tokens=1)
        assert out.shape == (2, 1)

    def test_bucketed_cache_matches_full_forward(self):
        """A short generation under a LONG context must still match the
        uncached forward exactly: the length-bucketed cache (128 wide
        here, not the model's 512) is an optimization, never a semantic
        change — and the full-context pos_embed params are used as-is."""
        from walkai_nos_tpu.models.decode import cache_bucket

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
            max_seq_len=512,
        )
        assert cache_bucket(4 + 6, cfg.max_seq_len) == 128  # < 512
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        generate = make_generate_fn(cfg)
        rng = np.random.default_rng(1)
        prompt = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32
        )
        out = generate(params, prompt, max_new_tokens=6)
        seq = prompt
        for t in range(6):
            logits = model.apply({"params": params}, seq)
            expect = jnp.argmax(logits[:, -1], axis=-1)
            assert jnp.array_equal(expect, out[:, t]), t
            seq = jnp.concatenate([seq, out[:, t : t + 1]], axis=1)

    def test_decode_kernel_flag_matches_default_path(self):
        """The optional fused-kernel route (LMConfig.decode_kernel)
        must be a pure dispatch decision — identical tokens to the
        default XLA path (on CPU the kernel wrapper falls back to the
        same reference math; hardware parity is pinned by
        tests/test_ops.py::TestDecodeAttention)."""
        import dataclasses

        model = DecoderLM(CFG)
        params = model.init_params(jax.random.PRNGKey(0))
        prompt = _prompt()
        base = make_generate_fn(CFG)(params, prompt, max_new_tokens=6)
        kcfg = dataclasses.replace(CFG, decode_kernel=True)
        out = make_generate_fn(kcfg)(params, prompt, max_new_tokens=6)
        assert jnp.array_equal(base, out)

    def test_moe_model_decodes(self):
        """Decoding composes with MoE blocks (routing is per-token)."""
        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
            max_seq_len=32, num_experts=2, moe_every=2,
        )
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        out = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=3)
        assert out.shape == (2, 3)
        assert bool(jnp.all((0 <= out) & (out < cfg.vocab_size)))


class TestSampling:
    def test_temperature_sampling_is_seed_deterministic(self):
        model = DecoderLM(CFG)
        params = model.init_params(jax.random.PRNGKey(0))
        generate = make_generate_fn(CFG, temperature=1.0)
        a = generate(
            params, _prompt(), max_new_tokens=8, rng=jax.random.PRNGKey(7)
        )
        b = generate(
            params, _prompt(), max_new_tokens=8, rng=jax.random.PRNGKey(7)
        )
        c = generate(
            params, _prompt(), max_new_tokens=8, rng=jax.random.PRNGKey(8)
        )
        assert jnp.array_equal(a, b)
        assert not jnp.array_equal(a, c)  # 64^16 collision: negligible
        assert bool(jnp.all((0 <= a) & (a < CFG.vocab_size)))


class TestGuards:
    def test_overflowing_cache_rejected(self):
        model = DecoderLM(CFG)
        params = model.init_params(jax.random.PRNGKey(0))
        generate = make_generate_fn(CFG)
        with pytest.raises(ValueError, match="max_seq_len"):
            generate(params, _prompt(n=30), max_new_tokens=6)

    def test_ring_attention_config_rejected(self):
        from dataclasses import replace

        with pytest.raises(ValueError, match="ring"):
            make_generate_fn(replace(CFG, use_ring_attention=True))


class TestTruncatedSampling:
    def _params(self):
        model = DecoderLM(CFG)
        return model.init_params(jax.random.PRNGKey(0))

    def test_top_k_one_equals_greedy(self):
        """top_k=1 collapses sampling to argmax at any temperature."""
        params = self._params()
        greedy = make_generate_fn(CFG)(
            params, _prompt(), max_new_tokens=6
        )
        topk1 = make_generate_fn(CFG, temperature=1.0, top_k=1)(
            params, _prompt(), max_new_tokens=6,
            rng=jax.random.PRNGKey(5),
        )
        assert jnp.array_equal(greedy, topk1)

    def test_top_p_tiny_equals_greedy(self):
        """A nucleus smaller than the top token's mass keeps only it."""
        params = self._params()
        greedy = make_generate_fn(CFG)(
            params, _prompt(), max_new_tokens=6
        )
        nucleus = make_generate_fn(CFG, temperature=1.0, top_p=1e-6)(
            params, _prompt(), max_new_tokens=6,
            rng=jax.random.PRNGKey(6),
        )
        assert jnp.array_equal(greedy, nucleus)

    def test_truncated_sampling_stays_in_vocab(self):
        params = self._params()
        out = make_generate_fn(CFG, temperature=1.0, top_k=8, top_p=0.9)(
            params, _prompt(), max_new_tokens=8,
            rng=jax.random.PRNGKey(7),
        )
        assert out.shape == (2, 8)
        assert bool(jnp.all((0 <= out) & (out < CFG.vocab_size)))

    def test_bad_knobs_rejected(self):
        with pytest.raises(ValueError, match="top_p"):
            make_generate_fn(CFG, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            make_generate_fn(CFG, top_k=-1)


class TestGroupedQueryDecode:
    """GQA decoding: the kv_heads-wide cache + grouped einsum must be
    a pure optimization — exact greedy equivalence with the full
    (uncached, repeat-KV flash) forward, like every other decode path."""

    def _roundtrip(self, kv_heads: int):
        import dataclasses

        cfg = dataclasses.replace(CFG, num_kv_heads=kv_heads)
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        out = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        seq = _prompt()
        for t in range(6):
            logits = model.apply({"params": params}, seq)
            expect = jnp.argmax(logits[:, -1], axis=-1)
            assert jnp.array_equal(expect, out[:, t]), (kv_heads, t)
            seq = jnp.concatenate([seq, out[:, t : t + 1]], axis=1)

    def test_gqa_matches_full_forward(self):
        self._roundtrip(kv_heads=1)  # CFG has 2 heads -> group 2 (MQA)

    def test_cache_holds_only_kv_heads(self):
        import dataclasses

        cfg = dataclasses.replace(CFG, num_kv_heads=1, cache_len=16)
        model = DecoderLM(cfg)
        cache = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((2, 1), jnp.int32),
            decode=True,
        )["cache"]
        k = cache["block0"]["attn"]["cached_key"]
        head_dim = cfg.hidden_dim // cfg.num_heads
        assert k.shape == (2, 1, 16, head_dim)


class TestLlamaFamilyDecode:
    def test_rope_rmsnorm_swiglu_matches_full_forward(self):
        """RoPE decode (rotate at cache index, cache stores rotated
        keys) + RMSNorm + SwiGLU must keep the exact-greedy-equivalence
        property of every other decode path."""
        import dataclasses

        cfg = dataclasses.replace(
            CFG, norm="rmsnorm", mlp="swiglu", rope=True,
            use_bias=False, head_bias=False, num_kv_heads=1,
        )
        model = DecoderLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        out = make_generate_fn(cfg)(params, _prompt(), max_new_tokens=6)
        seq = _prompt()
        for t in range(6):
            logits = model.apply({"params": params}, seq)
            expect = jnp.argmax(logits[:, -1], axis=-1)
            assert jnp.array_equal(expect, out[:, t]), t
            seq = jnp.concatenate([seq, out[:, t : t + 1]], axis=1)

"""Batcher windows, pod predicates, checkpoint/resume, factory builders."""

import queue
import time

import jax
import numpy as np
import pytest

from tests.factory import NodeBuilder, PodBuilder
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.utils.batcher import Batcher


class TestBatcher:
    def test_idle_window_flushes(self):
        b = Batcher(timeout=5.0, idle=0.15, buffer_size=10)
        b.start()
        try:
            b.add(1)
            b.add(2)
            batch = b.get_batch(timeout=2.0)
            assert batch == [1, 2]
        finally:
            b.stop()

    def test_timeout_window_caps_batch(self):
        """Items arriving faster than idle: timeout closes the batch
        (`batcher_test.go:36` timing semantics)."""
        b = Batcher(timeout=0.4, idle=0.3, buffer_size=100)
        b.start()
        try:
            stop_feeding = time.monotonic() + 1.0
            fed = 0
            batch = None
            while time.monotonic() < stop_feeding:
                b.add(fed)
                fed += 1
                try:
                    batch = b.get_batch(timeout=0.0)
                    break
                except queue.Empty:
                    time.sleep(0.05)
            assert batch is not None, "timeout window never flushed"
            assert 1 <= len(batch) < fed + 1
        finally:
            b.stop()

    def test_no_empty_batches(self):
        b = Batcher(timeout=0.2, idle=0.1)
        b.start()
        try:
            with pytest.raises(queue.Empty):
                b.get_batch(timeout=0.5)
        finally:
            b.stop()

    def test_rejects_bad_windows(self):
        with pytest.raises(ValueError):
            Batcher(timeout=0, idle=1)


class TestPodPredicates:
    def test_extra_resources_could_help(self):
        pod = (
            PodBuilder("p").with_slice_request("2x2").unschedulable().build()
        )
        assert objects.extra_resources_could_help_scheduling(pod)

    @pytest.mark.parametrize(
        "builder",
        [
            lambda p: p.scheduled_on("n1"),
            lambda p: p.with_phase("Running"),
            lambda p: p.preempting(),
            lambda p: p.owned_by("DaemonSet"),
            lambda p: p.owned_by("Node"),
        ],
        ids=["scheduled", "running", "preempting", "daemonset", "static"],
    )
    def test_extra_resources_cannot_help(self, builder):
        pod = builder(
            PodBuilder("p").with_slice_request("2x2").unschedulable()
        ).build()
        assert not objects.extra_resources_could_help_scheduling(pod)

    def test_priority_compare(self):
        high = PodBuilder("a").with_priority(100).build()
        low = PodBuilder("b").with_priority(1).build()
        none = PodBuilder("c").build()
        assert objects.pod_is_more_important(high, low)
        assert not objects.pod_is_more_important(none, low)


class TestFactory:
    def test_node_builder(self):
        node = (
            NodeBuilder("n1")
            .with_tpu_model()
            .with_tiling_enabled()
            .with_allocatable("walkai.io/tpu-2x2", "2")
            .build()
        )
        assert node["metadata"]["labels"][
            "cloud.google.com/gke-tpu-accelerator"
        ] == "tpu-v5-lite-podslice"
        assert node["status"]["allocatable"]["walkai.io/tpu-2x2"] == "2"


@pytest.mark.slow
class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        from walkai_nos_tpu.models.checkpoint import CheckpointManager
        from walkai_nos_tpu.models.train import init_train_state, make_train_step
        from walkai_nos_tpu.models.vit import VIT_TINY
        from walkai_nos_tpu.parallel.mesh import build_mesh

        cfg = VIT_TINY
        mesh = build_mesh(jax.devices())
        state = init_train_state(cfg, mesh, jax.random.PRNGKey(0))
        step = make_train_step(cfg, mesh)
        rng = np.random.default_rng(0)
        batch = {
            "images": jax.numpy.asarray(
                rng.standard_normal((8, cfg.image_size, cfg.image_size, 3)),
                jax.numpy.float32,
            ),
            "labels": jax.numpy.asarray(
                rng.integers(0, cfg.num_classes, (8, cfg.num_det_tokens))
            ),
            "boxes": jax.numpy.asarray(
                rng.uniform(0, 1, (8, cfg.num_det_tokens, 4)),
                jax.numpy.float32,
            ),
        }
        state, _ = step(state, batch)
        state, loss_at_2 = step(state, batch)

        manager = CheckpointManager(tmp_path / "ckpt")
        assert manager.save(state, force=True)
        assert manager.latest_step() == 2

        template = init_train_state(cfg, mesh, jax.random.PRNGKey(1))
        restored = manager.restore(template)
        manager.close()
        assert restored is not None
        assert int(restored.step) == 2
        qkv_a = np.asarray(state.params["block0"]["attn"]["qkv"]["kernel"])
        qkv_b = np.asarray(restored.params["block0"]["attn"]["qkv"]["kernel"])
        np.testing.assert_array_equal(qkv_a, qkv_b)
        # resumed training continues from the same loss trajectory
        _, loss_resumed = step(restored, batch)
        state, loss_orig = step(state, batch)
        np.testing.assert_allclose(
            float(loss_resumed), float(loss_orig), rtol=1e-5
        )

    def test_elastic_restore_across_mesh_shapes(self, tmp_path):
        """Save on one mesh layout, resume on another — the re-tiled
        slice scenario this control plane creates: a pod trained on a
        2x4 slice gets rescheduled onto a 2x2-equivalent layout. The
        checkpoint must land on the new mesh's shardings bit-identical."""
        from walkai_nos_tpu.models.checkpoint import CheckpointManager
        from walkai_nos_tpu.models.lm import (
            LMConfig,
            init_lm_state,
            make_lm_train_step,
        )
        from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
            max_seq_len=16,
        )
        tokens = jax.numpy.asarray(
            np.random.default_rng(0).integers(0, 64, (8, 16))
        )

        mesh_a = build_mesh(jax.devices(), axes=MeshAxes(data=2, model=4))
        state = init_lm_state(cfg, mesh_a, jax.random.PRNGKey(0))
        state, _ = make_lm_train_step(cfg, mesh_a)(state, tokens)
        manager = CheckpointManager(tmp_path / "ckpt")
        manager.save(state, force=True, wait=True)
        manager.close()

        mesh_b = build_mesh(jax.devices(), axes=MeshAxes(data=4, model=2))
        template = init_lm_state(cfg, mesh_b, jax.random.PRNGKey(1))
        manager_b = CheckpointManager(tmp_path / "ckpt")
        restored = manager_b.restore(template)
        manager_b.close()
        assert restored is not None and int(restored.step) == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(state.params),
            jax.tree_util.tree_leaves(restored.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # The restored params carry mesh_b shardings and keep training.
        qkv = restored.params["block0"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.mesh.shape["model"] == 2
        _, loss = make_lm_train_step(cfg, mesh_b)(restored, tokens)
        assert bool(jax.numpy.isfinite(loss))

"""Multi-host pool partitioning: topology, planning model, and e2e.

The TPU-native extension of `node_controller.go:56`'s premise (every
labeled node is managed) to pools whose slice spans hosts — VERDICT r2's
top capability gap. Unit tables over `topology.get_pool_topology` /
`tiling.pool.PoolNode`, then the sim-harness e2e: a 2-host v5p pool
initializes, re-tiles for pending pods, and binds gangs, with per-host
agents actuating their own share.
"""

from tests.helpers import eventually
from walkai_nos_tpu.api import constants
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.sim import SimCluster
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.tiling.pool import (
    PoolNode,
    block_orientations,
    group_pool_members,
    is_pool_profile,
    pool_profiles,
)


def _labels(
    acc="tpu-v5p-slice", topo="2x2x2", pool="pool-a", worker=None
):
    labels = {
        constants.LABEL_TPU_ACCELERATOR: acc,
        constants.LABEL_TPU_TOPOLOGY: topo,
        constants.LABEL_TPU_PARTITIONING: "tiling",
    }
    if pool:
        labels[constants.LABEL_TPU_NODEPOOL] = pool
    if worker is not None:
        labels[constants.LABEL_TPU_WORKER_ID] = str(worker)
    return labels


def _member(name, worker, annotations=None, **kw):
    return {
        "metadata": {
            "name": name,
            "labels": _labels(worker=worker, **kw),
            "annotations": annotations or {},
        }
    }


class TestPoolTopology:
    def test_v5p_two_host_pool(self):
        topo = topology.get_pool_topology(_labels(topo="2x2x2"))
        assert topo is not None
        assert topo.host_mesh == (2, 2, 1)
        assert topo.host_grid == (1, 1, 2)
        assert topo.num_hosts == 2
        assert topo.pool_profile == "2x2x2"
        assert topo.hosts_per_slice("2x2x2") == 2

    def test_v5e_four_host_pool(self):
        topo = topology.get_pool_topology(
            _labels(acc="tpu-v5-lite-podslice", topo="4x8")
        )
        assert topo is not None
        assert topo.num_hosts == 4
        assert topo.host_grid in ((2, 2),)

    def test_single_host_is_not_a_pool(self):
        assert topology.get_pool_topology(
            _labels(acc="tpu-v5-lite-podslice", topo="2x4")
        ) is None

    def test_indivisible_topology_refused(self):
        # 3x4 = 12 chips > 8 per host, but no host-mesh orientation
        # divides it: not coordinatable.
        assert topology.get_pool_topology(
            _labels(acc="tpu-v5-lite-podslice", topo="3x4")
        ) is None

    def test_pool_profiles_v5p_pair(self):
        topo = topology.get_pool_topology(_labels(topo="2x2x2"))
        assert pool_profiles(topo) == ["2x2x2"]

    def test_pool_profiles_v5e_quad(self):
        topo = topology.get_pool_topology(
            _labels(acc="tpu-v5-lite-podslice", topo="4x8")
        )
        profiles = pool_profiles(topo)
        # 2-host (16 chips) and 4-host (32 chips) blocks.
        assert "4x8" in profiles
        assert any(
            topology.shape_chip_count(topology.parse_shape(p)) == 16
            for p in profiles
        )

    def test_block_orientations(self):
        topo = topology.get_pool_topology(_labels(topo="2x2x2"))
        assert is_pool_profile("2x2x2", topo)
        assert not is_pool_profile("1x2x2", topo)
        orients = block_orientations("2x2x2", topo)
        assert ((2, 2, 2), (1, 1, 2)) in orients


class TestGroupPoolMembers:
    def test_split(self):
        single = {
            "metadata": {
                "name": "s1",
                "labels": _labels(acc="tpu-v5-lite-podslice", topo="2x4"),
            }
        }
        orphan = {  # multi-host but no pool label: refusal path
            "metadata": {"name": "o1", "labels": _labels(pool=None)}
        }
        m0, m1 = _member("p-0", 0), _member("p-1", 1)
        singles, pools = group_pool_members([single, orphan, m0, m1])
        assert [objects.name(n) for n in singles] == ["s1"]
        assert set(pools) == {"pool-a"}
        assert len(pools["pool-a"]) == 2


class TestPoolNode:
    def _pool(self, annotations_by_worker=None):
        annotations_by_worker = annotations_by_worker or {}
        members = [
            _member(f"p-{i}", i, annotations=annotations_by_worker.get(i))
            for i in range(2)
        ]
        pool = PoolNode.from_nodes("pool-a", members)
        assert pool is not None
        return pool

    def test_incomplete_pool_not_planned(self):
        assert PoolNode.from_nodes("pool-a", [_member("p-0", 0)]) is None

    def test_duplicate_worker_ids_rejected(self):
        assert PoolNode.from_nodes(
            "pool-a", [_member("p-0", 0), _member("p-1", 0)]
        ) is None

    def test_fresh_pool_retiles_to_pool_slice(self):
        pool = self._pool()
        assert pool.has_free_capacity()
        assert not pool.provides_profiles({"2x2x2": 1})
        assert pool.update_geometry_for({"2x2x2": 1})
        assert pool.provides_profiles({"2x2x2": 1})
        # Every member's share is the pool profile x1.
        for _node_obj, part in pool.build_partitionings():
            assert part.per_mesh_geometry() == {0: {"2x2x2": 1}}

    def test_add_pod_consumes_one_share_per_gang_pod(self):
        # Pool-profile quantities are SHARES: each gang pod consumes
        # one; a 2-host instance serves a 2-pod gang.
        pool = self._pool()
        pool.update_geometry_for({"2x2x2": 1})
        pool.add_pod({"2x2x2": 1})
        assert pool.provides_profiles({"2x2x2": 1})  # one share left
        pool.add_pod({"2x2x2": 1})
        assert not pool.provides_profiles({"2x2x2": 1})

    def test_batched_gang_carves_one_instance(self):
        # A 2-pod gang planned in one batch must carve ONE instance,
        # not one per pod (the over-partitioning bug class).
        pool = self._pool()
        assert pool.update_geometry_for({"2x2x2": 2})
        for _node_obj, part in pool.build_partitionings():
            assert part.per_mesh_geometry() == {0: {"2x2x2": 1}}
        pool.add_pod({"2x2x2": 2})
        assert not pool.provides_profiles({"2x2x2": 1})

    def test_missing_worker_id_not_planned(self):
        members = [
            _member("p-0", 0),
            {
                "metadata": {
                    "name": "p-1",
                    "labels": _labels(worker=None),
                    "annotations": {},
                }
            },
        ]
        assert PoolNode.from_nodes("pool-a", members) is None

    def test_host_local_profile_reclaims_free_share(self):
        free_share = {
            f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2x2-free": "1"
        }
        pool = self._pool({0: dict(free_share), 1: dict(free_share)})
        assert pool.update_geometry_for({"1x1x2": 1})
        assert pool.provides_profiles({"1x1x2": 1})
        # The reclaimed host dropped its share: no full gang remains.
        assert not pool.provides_profiles({"2x2x2": 1})

    def test_used_host_never_reassigned_to_pool_slice(self):
        pool = self._pool(
            {
                0: {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-used": "1",
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-free": "1",
                },
                1: {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-free": "2"
                },
            }
        )
        # host 0 has a used slice: no 2-host block is free.
        assert not pool.update_geometry_for({"2x2x2": 1})
        assert not pool.provides_profiles({"2x2x2": 1})

    def test_stranded_share_not_promised(self):
        # Snapshot between planning and actuation: host 0 still reports
        # a free pool share but host 1's mate is gone (used by a
        # host-local slice). The share is stranded — no complete
        # instance backs it — so provides_profiles must not promise it
        # and add_pod must refuse rather than place half a gang
        # (ADVICE r3: _free_shares counted it, selection couldn't
        # take it, and the pod was silently marked satisfied).
        import pytest

        from walkai_nos_tpu.tpu.errors import GenericError

        pool = self._pool(
            {
                0: {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2x2-free": "1"
                },
                1: {
                    f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-used": "1"
                },
            }
        )
        assert not pool.provides_profiles({"2x2x2": 1})
        with pytest.raises(GenericError):
            pool.add_pod({"2x2x2": 1})

    def test_stranded_share_retile_sweep(self):
        """The event-driven janitor (`stranded_share_retiles`): a
        reported free share whose mate was re-tiled away (spec AND
        status) is retired to the host-local default — the race the
        in-pass drop cannot see (the strand surfaces only after the
        pass that created it, when nothing is pending)."""
        from walkai_nos_tpu.tpu.tiling.pool import stranded_share_retiles

        spec_share = {
            f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x2": "1"
        }
        members = [
            _member("p-0", 0, annotations={  # re-tiled host-locally
                f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-used": "1",
                f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-1x1x2": "1",
            }),
            _member("p-1", 1, annotations={  # stranded share
                f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2x2-free": "1",
                **spec_share,
            }),
        ]
        writes = stranded_share_retiles("pool-a", members)
        assert [obj["metadata"]["name"] for obj, _ in writes] == ["p-1"]
        (_obj, part), = writes
        geom = part.per_mesh_geometry()[0]
        assert "2x2x2" not in geom and geom  # host-local default

    def test_sweep_leaves_initializing_pool_alone(self):
        """Mid-initialization — the mate's spec already carries the
        share but its report is still in flight — is NOT a strand: the
        janitor must never fight pool setup."""
        from walkai_nos_tpu.tpu.tiling.pool import stranded_share_retiles

        members = [
            _member("p-0", 0, annotations={  # reported first
                f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2x2-free": "1",
                f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x2": "1",
            }),
            _member("p-1", 1, annotations={  # planned, not yet reported
                f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x2": "1",
            }),
        ]
        assert stranded_share_retiles("pool-a", members) == []

    def test_sweep_never_touches_used_shares(self):
        """A USED share is a running gang member — even with its mate
        gone, eviction is never the janitor's call."""
        from walkai_nos_tpu.tpu.tiling.pool import stranded_share_retiles

        members = [
            _member("p-0", 0, annotations={
                f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-used": "1",
                f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-1x1x2": "1",
            }),
            _member("p-1", 1, annotations={
                f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-2x2x2-used": "1",
                f"{constants.ANNOTATION_TPU_SPEC_PREFIX}-0-2x2x2": "1",
            }),
        ]
        assert stranded_share_retiles("pool-a", members) == []

    def test_free_hosts_reassigned_from_local_tilings(self):
        # Both hosts fully host-locally tiled but free: a pending pool
        # slice reclaims them (the VERDICT "re-tiles for a pending
        # multi-host slice pod" core).
        free_local = {
            f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-1x1x2-free": "2"
        }
        pool = self._pool({0: dict(free_local), 1: dict(free_local)})
        assert pool.update_geometry_for({"2x2x2": 1})
        assert pool.provides_profiles({"2x2x2": 1})


class TestPoolInvariants:
    """Property sweep: random plan/place sequences never violate the
    pool invariants — used slices never evicted, every free pool share
    backed by a complete contiguous block, share counts consistent."""

    def _fresh_pool(self, n_hosts=4, topo="4x8",
                    acc="tpu-v5-lite-podslice"):
        members = [
            _member(f"p-{i}", i, acc=acc, topo=topo, pool="pool-a")
            for i in range(n_hosts)
        ]
        pool = PoolNode.from_nodes("pool-a", members)
        assert pool is not None
        return pool

    def _check_invariants(self, pool):
        topo = pool.topo
        for p in pool_profiles(topo):
            per = topo.hosts_per_slice(p)
            free = [h for h in pool.hosts if h.mesh.free_count(p) > 0]
            used = [h for h in pool.hosts if p in h.mesh.used]
            # Shares exist in whole-instance multiples.
            assert (len(free) + len(used)) % per == 0, (
                p, len(free), len(used),
            )

    def test_random_operation_sequences(self):
        import random

        rng = random.Random(7)
        profiles = ["4x8", "4x4", "2x4"]  # pool, pool, host-local
        for trial in range(30):
            pool = self._fresh_pool()
            totals: dict[str, int] = {}
            for _ in range(rng.randint(2, 8)):
                p = rng.choice(profiles)
                wanted = {p: rng.randint(1, 2)}
                if pool.provides_profiles(wanted):
                    pool.add_pod(wanted)
                else:
                    pool.update_geometry_for(wanted)
                    if pool.provides_profiles(wanted):
                        pool.add_pod(wanted)
                self._check_invariants(pool)
                # Used slices never evicted: per-profile used totals may
                # only grow or stay across every operation.
                new_totals: dict[str, int] = {}
                for h in pool.hosts:
                    for prof, q in h.mesh.used.items():
                        new_totals[prof] = new_totals.get(prof, 0) + q
                for prof, q in totals.items():
                    assert new_totals.get(prof, 0) >= q, (
                        trial, prof, totals, new_totals,
                    )
                totals = new_totals
            # Geometry writes are renderable for every member.
            for _node_obj, part in pool.build_partitionings():
                for _idx, geom in part.per_mesh_geometry().items():
                    assert all(q > 0 for q in geom.values())

    def test_multi_instance_demand_carves_distinct_blocks(self):
        """{'4x4': 4} on a 4-host pool needs TWO instances; the carving
        loop must claim distinct blocks, not re-carve the first."""
        pool = self._fresh_pool()
        assert pool.update_geometry_for({"4x4": 4})
        assert pool.provides_profiles({"4x4": 4})
        assert sum(
            h.mesh.free_count("4x4") for h in pool.hosts
        ) == 4

    def test_mixed_request_keeps_earmarked_instance(self):
        """A request satisfied partly by an existing free instance must
        not retile that instance for its host-local part."""
        free_share = {
            f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-4x4-free": "1"
        }
        members = [
            _member(
                f"p-{i}", i, acc="tpu-v5-lite-podslice", topo="4x8",
                pool="pool-a",
                annotations=dict(free_share) if i in (0, 2) else None,
            )
            for i in range(4)
        ]
        pool = PoolNode.from_nodes("pool-a", members)
        assert pool is not None
        assert pool.provides_profiles({"4x4": 2})
        pool.update_geometry_for({"4x4": 2, "2x4": 1})
        assert pool.provides_profiles({"4x4": 2, "2x4": 1})

    def test_surplus_instance_serves_mixed_request(self):
        """Two free 4x4 instances + a request needing only one of them
        plus a host-local slice: the surplus instance must be retiled
        for the host-local part, not earmarked into a dead end."""
        free_share = {
            f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-4x4-free": "1"
        }
        members = [
            _member(
                f"p-{i}", i, acc="tpu-v5-lite-podslice", topo="4x8",
                pool="pool-a", annotations=dict(free_share),
            )
            for i in range(4)
        ]
        pool = PoolNode.from_nodes("pool-a", members)
        assert pool is not None
        assert pool.update_geometry_for({"4x4": 2, "2x4": 1})
        assert pool.provides_profiles({"4x4": 2, "2x4": 1})

    def test_fresh_gang_stays_within_one_instance(self):
        """add_pod of one gang's worth of shares on a pool with two
        whole free instances must consume ONE instance whole — never
        one share in each (half a slice has no ICI torus behind it)."""
        free_share = {
            f"{constants.ANNOTATION_TPU_STATUS_PREFIX}-0-4x4-free": "1"
        }
        members = [
            _member(
                f"p-{i}", i, acc="tpu-v5-lite-podslice", topo="4x8",
                pool="pool-a", annotations=dict(free_share),
            )
            for i in range(4)
        ]
        pool = PoolNode.from_nodes("pool-a", members)
        assert pool is not None
        pool.add_pod({"4x4": 2})
        used = {h.index for h in pool.hosts if "4x4" in h.mesh.used}
        # Instances are column blocks of the 2x2 host grid: {0, 2} and
        # {1, 3}. The gang must land on exactly one of them.
        assert used in ({0, 2}, {1, 3}), used
        # And a second gang takes the OTHER whole instance.
        pool.add_pod({"4x4": 2})
        assert all("4x4" in h.mesh.used for h in pool.hosts)

    def test_used_totals_never_shrink(self):
        import random

        rng = random.Random(11)
        pool = self._fresh_pool()
        totals: dict[str, int] = {}
        for _ in range(12):
            p = rng.choice(["4x8", "4x4", "2x4", "1x4"])
            wanted = {p: 1}
            if not pool.provides_profiles(wanted):
                pool.update_geometry_for(wanted)
            if pool.provides_profiles(wanted):
                pool.add_pod(wanted)
            new_totals: dict[str, int] = {}
            for h in pool.hosts:
                for prof, q in h.mesh.used.items():
                    new_totals[prof] = new_totals.get(prof, 0) + q
            for prof, q in totals.items():
                assert new_totals.get(prof, 0) >= q, (
                    prof, totals, new_totals,
                )
            totals = new_totals


class TestPoolEndToEnd:
    def test_pool_init_gang_binds(self):
        """Fresh 2-host v5p pool: members initialize to the whole-pool
        share, agents materialize full-host slices advertised under the
        pool profile, and a 2-pod gang binds one pod per host."""
        cluster = SimCluster()
        cluster.add_pool("pool-a", n_hosts=2)
        with cluster:
            def initialized():
                for i in range(2):
                    node = cluster.kube.get("Node", f"pool-a-{i}")
                    _, spec = parse_node_annotations(
                        objects.annotations(node)
                    )
                    if not any(
                        s.profile == "2x2x2" and s.quantity == 1
                        for s in spec
                    ):
                        return False
                return True

            eventually(initialized, msg="pool members initialize to pool share")

            def reported_free():
                for i in range(2):
                    node = cluster.kube.get("Node", f"pool-a-{i}")
                    status, _ = parse_node_annotations(
                        objects.annotations(node)
                    )
                    if not any(
                        s.profile == "2x2x2" and s.status.value == "free"
                        for s in status
                    ):
                        return False
                return True

            eventually(reported_free, msg="agents report free pool shares")
            # The device layer materialized one full-host share per host.
            for i in range(2):
                slices = cluster.nodes[f"pool-a-{i}"].tpudev.list_slices()
                assert [s.profile for s in slices] == ["2x2x2"]
                assert len(slices[0].chip_ids) == 4  # whole 2x2x1 host

            # The gang: one pod per host, each consuming one share.
            cluster.create_slice_pod("gang-0", "2x2x2")
            cluster.create_slice_pod("gang-1", "2x2x2")

            def gang_bound():
                hosts = set()
                for name in ("gang-0", "gang-1"):
                    pod = cluster.kube.get("Pod", name, "default")
                    if not objects.pod_is_scheduled(pod):
                        return False
                    hosts.add(pod["spec"]["nodeName"])
                return hosts == {"pool-a-0", "pool-a-1"}

            eventually(gang_bound, msg="gang binds one pod per member host")

    def test_pool_retile_for_pending_pool_pod(self):
        """The VERDICT done-criterion: a pool re-tiled into host-local
        slices re-tiles BACK for a pending pool-slice gang and binds it;
        host-local pods keep working first."""
        cluster = SimCluster()
        cluster.add_pool("pool-b", n_hosts=2)
        with cluster:
            # Host-local demand first: a 2-chip slice forces one host
            # out of the pool-share layout.
            cluster.create_slice_pod("local-1", "1x1x2")

            def local_bound():
                pod = cluster.kube.get("Pod", "local-1", "default")
                return objects.pod_is_scheduled(pod)

            eventually(local_bound, msg="host-local pod binds on a pool host")

            # The other member's share is now STRANDED (its instance-mate
            # was reclaimed); the planner's same pass re-tiled it to the
            # host-local default — no host may keep advertising a share no
            # complete block backs.
            def no_stranded_share():
                for i in range(2):
                    if any(
                        s.profile == "2x2x2"
                        for s in cluster.nodes[
                            f"pool-b-{i}"
                        ].tpudev.list_slices()
                    ):
                        return False
                return True

            eventually(no_stranded_share, msg="stranded share re-tiled away")

            # Terminate the pod and release its device (what the kubelet
            # does when a pod ends); the pod may have landed on either
            # host, so release everywhere.
            cluster.kube.delete("Pod", "local-1", "default")
            for i in range(2):
                host = cluster.nodes[f"pool-b-{i}"]
                for dev in host.resources.get_used_devices():
                    host.resources.mark_free(dev.device_id)

            # Now the pool gang.
            cluster.create_slice_pod("gang-0", "2x2x2")
            cluster.create_slice_pod("gang-1", "2x2x2")

            def gang_bound():
                hosts = set()
                for name in ("gang-0", "gang-1"):
                    pod = cluster.kube.get("Pod", name, "default")
                    if not objects.pod_is_scheduled(pod):
                        return False
                    hosts.add(pod["spec"]["nodeName"])
                return hosts == {"pool-b-0", "pool-b-1"}

            eventually(
                gang_bound, timeout=30.0,
                msg="pool re-tiles back and the gang binds",
            )

    def test_lifecycle_churn_gang_reforms(self):
        """Full churn cycle through the real controllers: a gang binds,
        tears down, host-local pods take the hosts, tear down, and a
        NEW gang re-forms the pool — no stranded shares or stuck state
        at any stage."""
        cluster = SimCluster()
        cluster.add_pool("pool-c", n_hosts=2)
        with cluster:
            def bound(*names):
                def check():
                    for n in names:
                        pod = cluster.kube.get("Pod", n, "default")
                        if not objects.pod_is_scheduled(pod):
                            return False
                    return True
                return check

            def release(*names):
                for n in names:
                    pod = cluster.kube.get("Pod", n, "default")
                    host = cluster.nodes[pod["spec"]["nodeName"]]
                    cluster.kube.delete("Pod", n, "default")
                    for dev in host.resources.get_used_devices():
                        host.resources.mark_free(dev.device_id)

            # Cycle 1: gang.
            cluster.create_slice_pod("g1-0", "2x2x2")
            cluster.create_slice_pod("g1-1", "2x2x2")
            eventually(bound("g1-0", "g1-1"), timeout=30.0,
                       msg="first gang binds")
            release("g1-0", "g1-1")

            # Cycle 2: host-local demand takes both hosts.
            cluster.create_slice_pod("l-0", "1x1x2")
            cluster.create_slice_pod("l-1", "1x1x2")
            eventually(bound("l-0", "l-1"), timeout=30.0,
                       msg="host-local pods bind after gang teardown")
            release("l-0", "l-1")

            # Cycle 3: a new gang re-forms the pool slice.
            cluster.create_slice_pod("g2-0", "2x2x2")
            cluster.create_slice_pod("g2-1", "2x2x2")
            eventually(bound("g2-0", "g2-1"), timeout=30.0,
                       msg="pool re-forms for the second gang")
            hosts = {
                cluster.kube.get("Pod", n, "default")["spec"]["nodeName"]
                for n in ("g2-0", "g2-1")
            }
            assert hosts == {"pool-c-0", "pool-c-1"}

    def test_unpoolable_multi_host_node_still_refused(self):
        """A multi-host node without the nodepool label keeps the round-2
        refusal path (event + schedulable whole)."""
        cluster = SimCluster()
        # Hand-create: multi-host labels, no pool membership.
        cluster.kube.create(
            "Node",
            {
                "metadata": {
                    "name": "orphan-mh",
                    "labels": _labels(pool=None),
                },
                "status": {},
            },
        )
        with cluster:
            def refused():
                events = cluster.kube.list("Event", namespace="default")
                return any(
                    e.get("reason") == "MultiHostTopology" for e in events
                )

            eventually(refused, msg="refusal event emitted")

"""`make bench-check` (hack/bench_check.py): the headline-key
regression gate must pass on the repo's own current artifacts and
fail on a synthetic >25% regression — a broken comparator would wave
real regressions through silently, so the logic itself is tier-1."""

import importlib.util
import json
import pathlib

_ROOT = pathlib.Path(__file__).resolve().parents[1]

spec = importlib.util.spec_from_file_location(
    "bench_check", _ROOT / "hack" / "bench_check.py"
)
bench_check = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench_check)

BASELINE = {
    "published": {
        "cb_serving_capacity_tokens_per_s": {
            "value": 1000.0, "direction": "higher", "tolerance": 0.25,
        },
        "cb_ttft_p99": {
            "value": 0.4, "direction": "lower", "tolerance": 0.25,
        },
        "decode_gqa_roofline_fraction": {
            "value": None, "direction": "higher",
        },
    }
}


class TestCheckLogic:
    def test_within_band_passes(self):
        failures, notes = bench_check.check(
            {"cb_serving_capacity_tokens_per_s": 800.0,
             "cb_ttft_p99": 0.49},
            BASELINE,
        )
        assert failures == []
        # The unrecorded baseline is skipped with a note, not failed.
        assert any("no recorded baseline" in n for n in notes)

    def test_regression_past_band_fails(self):
        failures, _ = bench_check.check(
            {"cb_serving_capacity_tokens_per_s": 700.0,  # -30%
             "cb_ttft_p99": 0.1},
            BASELINE,
        )
        assert len(failures) == 1
        assert "cb_serving_capacity_tokens_per_s" in failures[0]

    def test_lower_is_better_direction(self):
        failures, _ = bench_check.check(
            {"cb_serving_capacity_tokens_per_s": 1200.0,
             "cb_ttft_p99": 0.51},  # +27.5% latency
            BASELINE,
        )
        assert len(failures) == 1
        assert "cb_ttft_p99" in failures[0]

    def test_missing_key_fails(self):
        failures, _ = bench_check.check(
            {"cb_ttft_p99": 0.3}, BASELINE
        )
        assert any(
            "cb_serving_capacity_tokens_per_s" in f and "missing" in f
            for f in failures
        )

    def test_absent_ok_budget_key(self):
        """A budget key (absolute ceiling, e.g. obs_overhead_pct < 2%)
        ships before the recorded artifact emits it: missing-from-bench
        is a skip note, but once emitted the band is enforced with
        tolerance 0."""
        base = {
            "published": {
                "obs_overhead_pct": {
                    "value": 2.0, "direction": "lower",
                    "tolerance": 0.0, "absent_ok": True,
                },
            }
        }
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert any("absent" in n for n in notes)
        failures, _ = bench_check.check({"obs_overhead_pct": 1.4}, base)
        assert failures == []
        # Negative overhead (noise floor: obs-on measured faster) is
        # fine — the budget only caps the upside.
        failures, _ = bench_check.check({"obs_overhead_pct": -0.3}, base)
        assert failures == []
        failures, _ = bench_check.check({"obs_overhead_pct": 2.6}, base)
        assert failures and "obs_overhead_pct" in failures[0]

    def test_repo_baseline_gates_obs_overhead(self):
        """The committed BASELINE.json actually carries the obs
        overhead budget the observability PR promises."""
        with open(_ROOT / "BASELINE.json") as f:
            spec = json.load(f)["published"]["obs_overhead_pct"]
        assert spec["value"] == 2.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True

    def test_repo_baseline_gates_router_obs_overhead(self):
        """The fleet observability plane is held to the SAME absolute
        < 2% budget as the engine's obs bundle
        (`router_obs_overhead_pct`, trafficbench A/B): absent from
        the bench output is a skip note; once emitted, above-budget
        fails and the noise floor (negative overhead) passes."""
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        spec = baseline["published"]["router_obs_overhead_pct"]
        assert spec["value"] == 2.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        failures, notes = bench_check.check({}, baseline)
        assert not any(
            "router_obs_overhead_pct" in f for f in failures
        )
        assert any(
            "router_obs_overhead_pct" in n and "absent" in n
            for n in notes
        )
        failures, _ = bench_check.check(
            {"router_obs_overhead_pct": 1.1}, baseline
        )
        assert not any(
            "router_obs_overhead_pct" in f for f in failures
        )
        failures, _ = bench_check.check(
            {"router_obs_overhead_pct": 2.7}, baseline
        )
        assert any(
            "router_obs_overhead_pct" in f for f in failures
        )

    def test_repo_baseline_gates_disagg_ttft(self):
        """The disaggregated serving arm is held to the SAME loose
        TTFT ceiling as the colocated surge key
        (`router_disagg_ttft_p99`, trafficbench's role-split
        prefill/decode replay): absent is a skip note; once emitted,
        a p99 past the band (value 2.0, lower-better, tolerance 1.0
        => fail above 4.0 s) fails — the first-token stage handoff
        must not cost the fleet its TTFT envelope."""
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        spec = baseline["published"]["router_disagg_ttft_p99"]
        assert spec["value"] == 2.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 1.0
        assert spec["absent_ok"] is True
        failures, notes = bench_check.check({}, baseline)
        assert not any(
            "router_disagg_ttft_p99" in f for f in failures
        )
        assert any(
            "router_disagg_ttft_p99" in n and "absent" in n
            for n in notes
        )
        failures, _ = bench_check.check(
            {"router_disagg_ttft_p99": 0.8}, baseline
        )
        assert not any(
            "router_disagg_ttft_p99" in f for f in failures
        )
        failures, _ = bench_check.check(
            {"router_disagg_ttft_p99": 4.5}, baseline
        )
        assert any(
            "router_disagg_ttft_p99" in f for f in failures
        )

    def test_repo_baseline_gates_capture_keys(self):
        """The capture plane is held to the SAME absolute < 2%
        budget as the obs bundle (`capture_overhead_pct`,
        engine-direct interleaved A/B with capture armed vs unarmed),
        and `cb_capture_bytes_per_request` (disk cost at production
        request rates) is declared null-until-recorded so the next
        chip round anchors it. Specs must PARSE through the
        comparator: absent is a skip note, above-budget fails once
        emitted, the null key never fails."""
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        spec = baseline["published"]["capture_overhead_pct"]
        assert spec["value"] == 2.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        bytes_spec = baseline["published"]["cb_capture_bytes_per_request"]
        assert bytes_spec["value"] is None
        assert bytes_spec["direction"] == "lower"
        failures, notes = bench_check.check({}, baseline)
        assert not any("capture_overhead_pct" in f for f in failures)
        assert any(
            "capture_overhead_pct" in n and "absent" in n
            for n in notes
        )
        assert any(
            "cb_capture_bytes_per_request" in n
            and "no recorded baseline" in n
            for n in notes
        )
        failures, _ = bench_check.check(
            {"capture_overhead_pct": 1.1,
             "cb_capture_bytes_per_request": 4096.0},
            baseline,
        )
        assert not any("capture" in f for f in failures)
        failures, _ = bench_check.check(
            {"capture_overhead_pct": 2.7}, baseline
        )
        assert any("capture_overhead_pct" in f for f in failures)

    def test_repo_baseline_gates_prefix_cache_keys(self):
        """BASELINE.json carries the shared-prefix cache's two
        headline keys as absent_ok acceptance floors, and the specs
        PARSE through the comparator: absent from the bench output is
        a skip note, a value below the floor fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        keys = ("cb_prefix_hit_rate", "cb_prefill_tokens_saved_frac")
        for key in keys:
            spec = published[key]
            assert spec["direction"] == "higher"
            assert spec["tolerance"] == 0.0
            assert spec["absent_ok"] is True
            assert spec["value"] >= 0.5
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        failures, _ = bench_check.check(
            {"cb_prefix_hit_rate": 0.9,
             "cb_prefill_tokens_saved_frac": 0.8},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_prefix_hit_rate": 0.2,
             "cb_prefill_tokens_saved_frac": 0.8},
            base,
        )
        assert len(failures) == 1
        assert "cb_prefix_hit_rate" in failures[0]

    def test_repo_baseline_gates_spec_serving_keys(self):
        """BASELINE.json carries the speculative-serving keys and
        they PARSE through the comparator: the capacity key is an
        absent_ok 5% band against the r5 spec-OFF capacity (the
        controller may disable drafting but must never cost more),
        the accepted-per-round key is null-until-recorded — absent
        or unanchored is a skip note, a capacity below the band
        fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        cap = published["cb_spec_capacity_tokens_per_s"]
        assert cap["direction"] == "higher"
        assert cap["tolerance"] == 0.05
        assert cap["absent_ok"] is True
        # The gate anchors to the r5 spec-off capacity baseline.
        assert cap["value"] == published[
            "cb_serving_capacity_tokens_per_s"
        ]["value"]
        acc = published["cb_spec_accepted_per_round"]
        assert acc["value"] is None  # pending the next chip run
        keys = (
            "cb_spec_capacity_tokens_per_s",
            "cb_spec_accepted_per_round",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert len(notes) == 2
        failures, _ = bench_check.check(
            {"cb_spec_capacity_tokens_per_s": cap["value"] * 0.96},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_spec_capacity_tokens_per_s": cap["value"] * 0.94},
            base,
        )
        assert len(failures) == 1
        assert "cb_spec_capacity_tokens_per_s" in failures[0]

    def test_repo_baseline_gates_quant_keys(self):
        """BASELINE.json carries the quantized-serving keys and they
        PARSE through the comparator: the capacity key is an
        absent_ok floor at the r5 quant-off capacity anchor
        (tolerance 0 — halving bytes/step must never cost capacity),
        the perplexity delta an absent_ok <= 0.05 upper bound.
        Absent from the bench output is a skip note; a capacity
        under the anchor or a delta past the budget fails once
        emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        cap = published["cb_quant_capacity_tokens_per_s"]
        assert cap["direction"] == "higher"
        assert cap["tolerance"] == 0.0
        assert cap["absent_ok"] is True
        # Anchored to the r5 quant-off capacity baseline.
        assert cap["value"] == published[
            "cb_serving_capacity_tokens_per_s"
        ]["value"]
        ppl = published["lm_quality_delta_ppl"]
        assert ppl["direction"] == "lower"
        assert ppl["tolerance"] == 0.0
        assert ppl["absent_ok"] is True
        assert ppl["value"] == 0.05
        keys = (
            "cb_quant_capacity_tokens_per_s", "lm_quality_delta_ppl",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        failures, _ = bench_check.check(
            {"cb_quant_capacity_tokens_per_s": cap["value"] * 1.8,
             "lm_quality_delta_ppl": 0.01},
            base,
        )
        assert failures == []
        # A slightly NEGATIVE delta (quantization noise measured
        # faster-than-fp) passes — the budget caps only the upside.
        failures, _ = bench_check.check(
            {"lm_quality_delta_ppl": -0.02}, base
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_quant_capacity_tokens_per_s": cap["value"] * 0.9,
             "lm_quality_delta_ppl": 0.2},
            base,
        )
        assert len(failures) == 2
        assert any(
            "cb_quant_capacity_tokens_per_s" in f for f in failures
        )
        assert any("lm_quality_delta_ppl" in f for f in failures)

    def test_repo_baseline_gates_tp_serving_keys(self):
        """BASELINE.json carries the tensor-parallel serving keys and
        they PARSE through the comparator: the capacity key is an
        absent_ok floor at the r5 single-chip capacity anchor
        (tolerance 0 — adding chips must never cost capacity), the
        scaling-efficiency key an absent_ok >= 0.7 floor. Absent from
        the bench output is a skip note; a capacity under the anchor
        or an efficiency under the floor fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        cap = published["cb_tp_capacity_tokens_per_s"]
        assert cap["direction"] == "higher"
        assert cap["tolerance"] == 0.0
        assert cap["absent_ok"] is True
        # Anchored to the r5 single-chip capacity baseline.
        assert cap["value"] == published[
            "cb_serving_capacity_tokens_per_s"
        ]["value"]
        eff = published["tp_scaling_efficiency"]
        assert eff["direction"] == "higher"
        assert eff["tolerance"] == 0.0
        assert eff["absent_ok"] is True
        assert eff["value"] == 0.7
        keys = (
            "cb_tp_capacity_tokens_per_s", "tp_scaling_efficiency",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        failures, _ = bench_check.check(
            {"cb_tp_capacity_tokens_per_s": cap["value"] * 3.1,
             "tp_scaling_efficiency": 0.82},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_tp_capacity_tokens_per_s": cap["value"] * 0.9,
             "tp_scaling_efficiency": 0.5},
            base,
        )
        assert len(failures) == 2
        assert any(
            "cb_tp_capacity_tokens_per_s" in f for f in failures
        )
        assert any("tp_scaling_efficiency" in f for f in failures)

    def test_repo_baseline_gates_attribution_keys(self):
        """BASELINE.json carries the device-time attribution keys as
        absent_ok lower-is-better bands and they PARSE through the
        comparator: absent from the bench output is a skip note; a
        device step past the band or a host-overhead fraction past
        the 0.15 loop-era budget fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        step = published["cb_device_step_ms"]
        assert step["direction"] == "lower"
        assert step["absent_ok"] is True
        assert step["value"] > 0
        frac = published["cb_host_overhead_frac"]
        assert frac["direction"] == "lower"
        assert frac["tolerance"] == 0.0
        assert frac["absent_ok"] is True
        # Tightened from 0.5 by the device-resident-loop PR: with
        # loop_steps chunks folded per host sync, assembly must stay
        # under 0.15 of step time.
        assert frac["value"] == 0.15
        # The windowed SLO p99 rides the same absent_ok pattern,
        # anchored like-for-like to the r5 record-derived cb_ttft_p99.
        slo = published["cb_slo_ttft_p99"]
        assert slo["direction"] == "lower"
        assert slo["absent_ok"] is True
        assert slo["value"] == published["cb_ttft_p99"]["value"]
        keys = (
            "cb_device_step_ms", "cb_host_overhead_frac",
            "cb_slo_ttft_p99",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 3
        ceiling = step["value"] * (1 + step["tolerance"])
        failures, _ = bench_check.check(
            {"cb_device_step_ms": ceiling * 0.9,
             "cb_host_overhead_frac": 0.12},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_device_step_ms": ceiling * 1.1,
             "cb_host_overhead_frac": 0.62},
            base,
        )
        assert len(failures) == 2
        assert any("cb_device_step_ms" in f for f in failures)
        assert any("cb_host_overhead_frac" in f for f in failures)
        # The r5 per-chunk measurement (0.31) must now FAIL the
        # tightened budget — the loop is the only way back to green.
        failures, _ = bench_check.check(
            {"cb_host_overhead_frac": 0.31},
            {"published": {
                "cb_host_overhead_frac": published[
                    "cb_host_overhead_frac"
                ],
            }},
        )
        assert len(failures) == 1

    def test_repo_baseline_gates_router_keys(self):
        """BASELINE.json carries the fleet router's two headline keys
        as absent_ok specs and they PARSE through the comparator:
        `router_ttft_p99_under_surge` is a lower-is-better band (the
        surge-window serving quality the autoscaler defends),
        `router_prefix_hit_rate` a >= 0.5 acceptance floor (fleet
        sharing must not degrade below the single-engine floor).
        Absent from the bench output is a skip note; a value past its
        band fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        surge = published["router_ttft_p99_under_surge"]
        assert surge["direction"] == "lower"
        assert surge["absent_ok"] is True
        assert surge["value"] > 0
        rate = published["router_prefix_hit_rate"]
        assert rate["direction"] == "higher"
        assert rate["tolerance"] == 0.0
        assert rate["absent_ok"] is True
        assert rate["value"] >= 0.5
        keys = (
            "router_ttft_p99_under_surge", "router_prefix_hit_rate",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        ceiling = surge["value"] * (1 + surge["tolerance"])
        failures, _ = bench_check.check(
            {"router_ttft_p99_under_surge": ceiling * 0.9,
             "router_prefix_hit_rate": 0.8},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"router_ttft_p99_under_surge": ceiling * 1.1,
             "router_prefix_hit_rate": 0.2},
            base,
        )
        assert len(failures) == 2
        assert any(
            "router_ttft_p99_under_surge" in f for f in failures
        )
        assert any("router_prefix_hit_rate" in f for f in failures)

    def test_repo_baseline_gates_long_context_keys(self):
        """BASELINE.json carries the bimodal long-context arm's two
        headline keys (sequence-parallel prefill lane,
        run_long_context_benchmark) as absent_ok lower-is-better
        bands and they PARSE through the comparator:
        `cb_prefill_100k_ttft_s` is the long prompt's TTFT with sp
        ON, `cb_short_p99_under_long_load` the short-prompt p99
        beside it (the fairness half). Absent from the bench output
        is a skip note; a value past its band (value 2.0, tolerance
        1.0 => fail above 4.0 s) fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        keys = (
            "cb_prefill_100k_ttft_s", "cb_short_p99_under_long_load",
        )
        for key in keys:
            spec = published[key]
            assert spec["direction"] == "lower"
            assert spec["tolerance"] == 1.0
            assert spec["absent_ok"] is True
            assert spec["value"] == 2.0
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        failures, _ = bench_check.check(
            {"cb_prefill_100k_ttft_s": 0.7,
             "cb_short_p99_under_long_load": 0.3},
            base,
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_prefill_100k_ttft_s": 4.5,
             "cb_short_p99_under_long_load": 4.2},
            base,
        )
        assert len(failures) == 2
        assert any("cb_prefill_100k_ttft_s" in f for f in failures)
        assert any(
            "cb_short_p99_under_long_load" in f for f in failures
        )

    def test_repo_baseline_activates_roofline_gate(self):
        """The device-resident-loop PR activates the long-deferred
        decode_gqa_roofline_fraction gate: an absent_ok acceptance
        FLOOR at 0.8 (tolerance 0) instead of the old
        null-until-recorded placeholder — absent from the bench
        output is still a skip note, but a chip run landing under
        the floor fails."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        spec = published["decode_gqa_roofline_fraction"]
        assert spec["direction"] == "higher"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        assert spec["value"] == 0.8
        base = {"published": {"decode_gqa_roofline_fraction": spec}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert any("absent" in n for n in notes)
        failures, _ = bench_check.check(
            {"decode_gqa_roofline_fraction": 0.85}, base
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"decode_gqa_roofline_fraction": 0.46}, base
        )
        assert len(failures) == 1
        assert "decode_gqa_roofline_fraction" in failures[0]

    def test_bare_number_baseline_defaults_higher(self):
        failures, _ = bench_check.check(
            {"x": 70.0}, {"published": {"x": 100.0}}
        )
        assert failures and "x" in failures[0]
        failures, _ = bench_check.check(
            {"x": 80.0}, {"published": {"x": 100.0}}
        )
        assert failures == []


class TestRepoArtifacts:
    def test_repo_baseline_vs_last_bench_passes(self):
        """The committed bench_last.json must satisfy the committed
        BASELINE.json published bands — the gate ships green (the
        baselines ARE the r5 numbers bench_last records)."""
        with open(_ROOT / "bench_last.json") as f:
            bench = json.load(f)
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        assert baseline.get("published"), "BASELINE.json published empty"
        failures, _ = bench_check.check(bench, baseline)
        assert failures == [], failures

    def test_main_exit_codes(self, tmp_path):
        good = tmp_path / "good.json"
        bad = tmp_path / "bad.json"
        base = tmp_path / "base.json"
        base.write_text(json.dumps(BASELINE))
        good.write_text(json.dumps(
            {"cb_serving_capacity_tokens_per_s": 1000.0,
             "cb_ttft_p99": 0.4}
        ))
        bad.write_text(json.dumps(
            {"cb_serving_capacity_tokens_per_s": 100.0,
             "cb_ttft_p99": 0.4}
        ))
        assert bench_check.main(
            ["--bench", str(good), "--baseline", str(base)]
        ) == 0
        assert bench_check.main(
            ["--bench", str(bad), "--baseline", str(base)]
        ) == 1

    def test_makefile_has_bench_check_target(self):
        assert "bench-check:" in (_ROOT / "Makefile").read_text()

    def test_makefile_has_replay_check_target(self):
        # The capture/replay determinism gate (hack/replay_check.py)
        # — pinned fast in tests/test_capture_replay.py.
        text = (_ROOT / "Makefile").read_text()
        assert "replay-check:" in text
        assert "hack/replay_check.py" in text

    def test_repo_baseline_gates_lora_serving_keys(self):
        """BASELINE.json carries the multi-LoRA serving keys and they
        PARSE through the comparator: the capacity key is an
        absent_ok floor at 0.9x the r5 base capacity anchor
        (tolerance 0), the overhead key an absent_ok <= 10% budget —
        the Punica/S-LoRA near-base-throughput bar for K=4 resident
        adapters with mixed-tenant traffic. Absent from the bench
        output is a skip note; a capacity under the floor or an
        overhead past the budget fails once emitted."""
        with open(_ROOT / "BASELINE.json") as f:
            published = json.load(f)["published"]
        cap = published["cb_lora_capacity_tokens_per_s"]
        assert cap["direction"] == "higher"
        assert cap["tolerance"] == 0.0
        assert cap["absent_ok"] is True
        # Anchored at 0.9x the r5 base capacity (the 10% budget).
        base_cap = published[
            "cb_serving_capacity_tokens_per_s"
        ]["value"]
        assert abs(cap["value"] - 0.9 * base_cap) < 0.1
        ovh = published["cb_lora_overhead_pct"]
        assert ovh["direction"] == "lower"
        assert ovh["tolerance"] == 0.0
        assert ovh["absent_ok"] is True
        assert ovh["value"] == 10.0
        keys = (
            "cb_lora_capacity_tokens_per_s", "cb_lora_overhead_pct",
        )
        base = {"published": {k: published[k] for k in keys}}
        failures, notes = bench_check.check({}, base)
        assert failures == []
        assert sum("absent" in n for n in notes) == 2
        failures, _ = bench_check.check(
            {"cb_lora_capacity_tokens_per_s": cap["value"] * 1.05,
             "cb_lora_overhead_pct": 4.2},
            base,
        )
        assert failures == []
        # A NEGATIVE overhead (noise floor: the armed arm measured
        # faster) passes — the budget only caps the upside.
        failures, _ = bench_check.check(
            {"cb_lora_overhead_pct": -0.8}, base
        )
        assert failures == []
        failures, _ = bench_check.check(
            {"cb_lora_capacity_tokens_per_s": cap["value"] * 0.9,
             "cb_lora_overhead_pct": 14.0},
            base,
        )
        assert len(failures) == 2
        assert any(
            "cb_lora_capacity_tokens_per_s" in f for f in failures
        )
        assert any("cb_lora_overhead_pct" in f for f in failures)

    def test_makefile_has_replay_corpus_check_target(self):
        # The rotating-corpus determinism gate (hack/replay_corpus.py)
        # — pinned fast in tests/test_replay_corpus.py.
        text = (_ROOT / "Makefile").read_text()
        assert "replay-corpus-check:" in text
        assert "hack/replay_corpus.py" in text

    def test_makefile_has_canary_check_target(self):
        # The shadow/canary plane gate (hack/canary_check.py) —
        # pinned fast in tests/test_canary.py.
        text = (_ROOT / "Makefile").read_text()
        assert "canary-check:" in text
        assert "hack/canary_check.py" in text

    def test_repo_baseline_gates_canary_keys(self):
        """The shadow plane's two bench keys: the router-side tax is
        held to the SAME absolute < 2% budget as
        `router_obs_overhead_pct`, and a same-config mirror must
        produce ZERO digest divergences — a nonzero count means the
        mirror seam itself changes tokens, which would invalidate
        every real canary verdict."""
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        spec = baseline["published"]["router_canary_overhead_pct"]
        assert spec["value"] == 2.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        spec = baseline["published"]["router_canary_divergence_total"]
        assert spec["value"] == 0.0
        assert spec["direction"] == "lower"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        failures, notes = bench_check.check({}, baseline)
        assert not any("router_canary" in f for f in failures)
        assert any(
            "router_canary_divergence_total" in n and "absent" in n
            for n in notes
        )
        failures, _ = bench_check.check(
            {
                "router_canary_overhead_pct": 1.3,
                "router_canary_divergence_total": 0,
            },
            baseline,
        )
        assert not any("router_canary" in f for f in failures)
        failures, _ = bench_check.check(
            {
                "router_canary_overhead_pct": 2.4,
                "router_canary_divergence_total": 3,
            },
            baseline,
        )
        assert any(
            "router_canary_overhead_pct" in f for f in failures
        )
        assert any(
            "router_canary_divergence_total" in f for f in failures
        )

    def test_repo_baseline_gates_autotune_gain(self):
        """The replay autotune seed's headline
        (`autotune_capacity_gain_pct`, sim/autotune.py): floored at 0
        by construction (keeping the captured config is always on the
        menu), higher-better, absent is a skip note."""
        with open(_ROOT / "BASELINE.json") as f:
            baseline = json.load(f)
        spec = baseline["published"]["autotune_capacity_gain_pct"]
        assert spec["value"] == 0.0
        assert spec["direction"] == "higher"
        assert spec["tolerance"] == 0.0
        assert spec["absent_ok"] is True
        failures, notes = bench_check.check({}, baseline)
        assert not any(
            "autotune_capacity_gain_pct" in f for f in failures
        )
        assert any(
            "autotune_capacity_gain_pct" in n and "absent" in n
            for n in notes
        )
        failures, _ = bench_check.check(
            {"autotune_capacity_gain_pct": 7.5}, baseline
        )
        assert not any(
            "autotune_capacity_gain_pct" in f for f in failures
        )
        failures, _ = bench_check.check(
            {"autotune_capacity_gain_pct": -1.0}, baseline
        )
        assert any(
            "autotune_capacity_gain_pct" in f for f in failures
        )

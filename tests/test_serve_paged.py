"""Paged KV slot pool + chunked-prefill lane (`models/serve.py`).

Tier-1 surface for the serving memory/admission rework: paged-cache
greedy decode must be TOKEN-IDENTICAL to the dense cache and to
standalone generation for mixed ragged lengths crossing 128-row block
boundaries; the streaming feed must agree with the completion records
(including mid-chunk EOS and budget exhaustion); the block allocator
must recycle and bound the pool. Deliberately NOT in conftest's
`_SLOW_FILES` (tests/test_serve.py is) — the fast control-plane loop
must exercise the serving engine's correctness surface, so the shapes
here stay tiny.
"""

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


def _expected(params, prompt, max_new):
    gen = make_generate_fn(CFG)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


class TestPagedDenseParity:
    def test_mixed_ragged_lengths_crossing_block_boundaries(self, params):
        """Prompts of 3/20/100/140 tokens with budgets that cross the
        128-row block edge mid-prefill (140 > 128, streamed in
        32-token lane chunks) and mid-decode (100 + 40 crosses at
        step 28), sharing 2 slots: the paged engine, the dense engine,
        and standalone generation must agree token for token."""
        specs = [(3, 9), (20, 17), (100, 40), (140, 11)]
        outs = {}
        for paged in (True, False):
            engine = ContinuousBatcher(
                CFG, params, slots=2, cache_len=384, prompt_bucket=16,
                chunk_steps=3, paged=paged, prefill_chunk=32,
                prefill_lanes=2,
            )
            rids = {
                engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
                for n, m in specs
            }
            res = engine.run()
            outs[paged] = {rids[r]: toks for r, toks in res.items()}
        for n, m in specs:
            want = _expected(params, _prompt(n, seed=n), m)
            assert outs[True][(n, m)] == want, (n, m)
            assert outs[False][(n, m)] == want, (n, m)

    def test_sampled_request_identical_across_cache_layouts(self, params):
        """(prompt, knobs, seed) fully determines sampled output in
        BOTH cache layouts — the lane's finishing scatter must seed
        the slot's PRNG key exactly like the dense admit program."""
        p = _prompt(11, seed=42)
        toks = {}
        for paged in (True, False):
            engine = ContinuousBatcher(
                CFG, params, slots=2, cache_len=256, chunk_steps=4,
                paged=paged, prefill_chunk=8,
            )
            rid = engine.submit(
                p, max_new_tokens=8, temperature=0.9, top_k=16,
                top_p=0.95, seed=123,
            )
            toks[paged] = engine.run()[rid]
        assert toks[True] == toks[False]
        assert len(toks[True]) == 8


class TestStreamingParity:
    def test_drain_new_tokens_accumulates_to_done_output(self, params):
        """The streaming feed, accumulated across manual step() turns,
        must equal each request's completion record — including a
        request ending on mid-chunk EOS and one exhausting its budget."""
        full = _expected(params, _prompt(6, seed=6), 10)
        # An EOS token whose first occurrence is mid-generation forces
        # the early-exit path (same construction as test_serve.py).
        eos, cut = next(
            (t, i) for i, t in enumerate(full)
            if 1 <= i < 9 and t not in full[:i]
        )
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=128, chunk_steps=4,
            prefill_chunk=8,
        )
        r_eos = engine.submit(_prompt(6, seed=6), max_new_tokens=10,
                              eos_id=eos)
        r_budget = engine.submit(_prompt(5, seed=8), max_new_tokens=9)
        streamed: dict[int, list[int]] = {r_eos: [], r_budget: []}
        records: dict[int, dict] = {}
        while engine.has_work:
            engine.step()
            for rid, delta in engine.drain_new_tokens().items():
                streamed[rid].extend(delta)
            records.update(engine.drain_done_records())
        records.update(engine.drain_done_records())
        assert streamed[r_eos] == records[r_eos]["tokens"] == full[:cut + 1]
        assert streamed[r_budget] == records[r_budget]["tokens"]
        assert records[r_budget]["tokens"] == _expected(
            params, _prompt(5, seed=8), 9
        )
        for rec in records.values():
            assert 0 < rec["ttft_s"] <= rec["wall_s"]


class TestBlockAllocator:
    def test_pool_exhaustion_queues_then_recycles(self, params):
        """A pool sized for ONE resident request at a time: the second
        request waits for the first's blocks, both decode exactly, and
        every block returns to the free list afterward."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=256, chunk_steps=4,
            pool_blocks=3, prefill_chunk=8,
        )
        p0, p1 = _prompt(4, seed=1), _prompt(7, seed=2)
        r0 = engine.submit(p0, max_new_tokens=130)  # 134 rows -> 2 blocks
        r1 = engine.submit(p1, max_new_tokens=126)  # 133 rows -> 2 blocks
        res = engine.run()
        assert res[r0] == _expected(params, p0, 130)
        assert res[r1] == _expected(params, p1, 126)
        assert sorted(engine._free_blocks) == [1, 2]
        assert not engine._table.any()

    def test_request_larger_than_pool_rejected(self, params):
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=256, pool_blocks=2,
            prefill_chunk=8,
        )
        with pytest.raises(ValueError, match="pool"):
            engine.submit(_prompt(4, seed=3), max_new_tokens=130)

    def test_pending_queue_is_a_deque(self, params):
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=128)
        assert isinstance(engine._pending, deque)

"""Paged KV slot pool + chunked-prefill lane (`models/serve.py`).

Tier-1 surface for the serving memory/admission rework: paged-cache
greedy decode must be TOKEN-IDENTICAL to the dense cache and to
standalone generation for mixed ragged lengths crossing 128-row block
boundaries; the streaming feed must agree with the completion records
(including mid-chunk EOS and budget exhaustion); the block allocator
must recycle and bound the pool. The shared-prefix KV cache
(`models/prefix_cache.py`) adds its own surface: cache-hit outputs
must be identical to cold serving (greedy AND sampled), refcounts
must pin shared blocks exactly as long as a holder lives, eviction
must be LRU and must never break a surviving prefix, and prompts
that diverge inside a block must never share. Deliberately NOT in
conftest's `_SLOW_FILES` (tests/test_serve.py is) — the fast
control-plane loop must exercise the serving engine's correctness
surface, so the shapes here stay tiny.
"""

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


def _expected(params, prompt, max_new):
    gen = make_generate_fn(CFG)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


class TestPagedDenseParity:
    def test_mixed_ragged_lengths_crossing_block_boundaries(self, params):
        """Prompts of 3/20/100/140 tokens with budgets that cross the
        128-row block edge mid-prefill (140 > 128, streamed in
        32-token lane chunks) and mid-decode (100 + 40 crosses at
        step 28), sharing 2 slots: the paged engine, the dense engine,
        and standalone generation must agree token for token."""
        specs = [(3, 9), (20, 17), (100, 40), (140, 11)]
        outs = {}
        for paged in (True, False):
            engine = ContinuousBatcher(
                CFG, params, slots=2, cache_len=384, prompt_bucket=16,
                chunk_steps=3, paged=paged, prefill_chunk=32,
                prefill_lanes=2,
            )
            rids = {
                engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
                for n, m in specs
            }
            res = engine.run()
            outs[paged] = {rids[r]: toks for r, toks in res.items()}
        for n, m in specs:
            want = _expected(params, _prompt(n, seed=n), m)
            assert outs[True][(n, m)] == want, (n, m)
            assert outs[False][(n, m)] == want, (n, m)

    def test_sampled_request_identical_across_cache_layouts(self, params):
        """(prompt, knobs, seed) fully determines sampled output in
        BOTH cache layouts — the lane's finishing scatter must seed
        the slot's PRNG key exactly like the dense admit program."""
        p = _prompt(11, seed=42)
        toks = {}
        for paged in (True, False):
            engine = ContinuousBatcher(
                CFG, params, slots=2, cache_len=256, chunk_steps=4,
                paged=paged, prefill_chunk=8,
            )
            rid = engine.submit(
                p, max_new_tokens=8, temperature=0.9, top_k=16,
                top_p=0.95, seed=123,
            )
            toks[paged] = engine.run()[rid]
        assert toks[True] == toks[False]
        assert len(toks[True]) == 8


class TestStreamingParity:
    def test_drain_new_tokens_accumulates_to_done_output(self, params):
        """The streaming feed, accumulated across manual step() turns,
        must equal each request's completion record — including a
        request ending on mid-chunk EOS and one exhausting its budget."""
        full = _expected(params, _prompt(6, seed=6), 10)
        # An EOS token whose first occurrence is mid-generation forces
        # the early-exit path (same construction as test_serve.py).
        eos, cut = next(
            (t, i) for i, t in enumerate(full)
            if 1 <= i < 9 and t not in full[:i]
        )
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=128, chunk_steps=4,
            prefill_chunk=8,
        )
        r_eos = engine.submit(_prompt(6, seed=6), max_new_tokens=10,
                              eos_id=eos)
        r_budget = engine.submit(_prompt(5, seed=8), max_new_tokens=9)
        streamed: dict[int, list[int]] = {r_eos: [], r_budget: []}
        records: dict[int, dict] = {}
        while engine.has_work:
            engine.step()
            for rid, delta in engine.drain_new_tokens().items():
                streamed[rid].extend(delta)
            records.update(engine.drain_done_records())
        records.update(engine.drain_done_records())
        assert streamed[r_eos] == records[r_eos]["tokens"] == full[:cut + 1]
        assert streamed[r_budget] == records[r_budget]["tokens"]
        assert records[r_budget]["tokens"] == _expected(
            params, _prompt(5, seed=8), 9
        )
        for rec in records.values():
            assert 0 < rec["ttft_s"] <= rec["wall_s"]


class TestBlockAllocator:
    def test_pool_exhaustion_queues_then_recycles(self, params):
        """A pool sized for ONE resident request at a time: the second
        request waits for the first's blocks, both decode exactly, and
        every block returns to the free list afterward."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=256, chunk_steps=4,
            pool_blocks=3, prefill_chunk=8,
        )
        p0, p1 = _prompt(4, seed=1), _prompt(7, seed=2)
        r0 = engine.submit(p0, max_new_tokens=130)  # 134 rows -> 2 blocks
        r1 = engine.submit(p1, max_new_tokens=126)  # 133 rows -> 2 blocks
        res = engine.run()
        assert res[r0] == _expected(params, p0, 130)
        assert res[r1] == _expected(params, p1, 126)
        assert sorted(engine._free_blocks) == [1, 2]
        assert not engine._table.any()

    def test_request_larger_than_pool_rejected(self, params):
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=256, pool_blocks=2,
            prefill_chunk=8,
        )
        with pytest.raises(ValueError, match="pool"):
            engine.submit(_prompt(4, seed=3), max_new_tokens=130)

    def test_pending_queue_is_a_deque(self, params):
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=128)
        assert isinstance(engine._pending, deque)


class TestSubmitValidation:
    def test_nonpositive_max_new_rejected(self, params):
        """A degenerate budget must fail through the bad_request
        taxonomy, not admit a request that can never emit a token."""
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=128)
        for bad in (0, -3):
            with pytest.raises(ValueError, match="max_new_tokens"):
                engine.submit(_prompt(4), max_new_tokens=bad)
        assert engine.obs.errors.value(
            labels={"reason": "bad_request"}
        ) == 2
        assert not engine.has_work

    def test_empty_prompt_rejected(self, params):
        engine = ContinuousBatcher(CFG, params, slots=1, cache_len=128)
        with pytest.raises(ValueError, match="empty prompt"):
            engine.submit([], max_new_tokens=4)
        assert engine.obs.errors.value(
            labels={"reason": "bad_request"}
        ) == 1
        assert not engine.has_work


class TestPrefixReuse:
    def test_shared_prefix_parity_and_park_reuse(self, params):
        """A cache-hit request (prefix blocks parked by an earlier
        completion) must emit exactly the tokens cold serving emits —
        with and without the cache — and the hit must actually skip
        the shared prefix's prefill."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=3,
            prefill_chunk=32, prefill_lanes=2,
        )
        p = _prompt(300, seed=31)  # 2 full shareable 128-token blocks
        want = _expected(params, p, 10)
        r0 = engine.submit(p, max_new_tokens=10)
        assert engine.run()[r0] == want  # cold fill
        r1 = engine.submit(p, max_new_tokens=10)
        assert engine.run()[r1] == want  # served from parked blocks
        st = engine.prefix_stats()
        assert st["block_hits"] == 2
        assert st["prefill_tokens_saved"] == 256
        assert st["hit_rate"] == 0.5  # 2 hits / (2 + 2 cold misses)
        # Divergent tail on a shared 256-token prefix.
        p2 = np.concatenate([p[:256], _prompt(30, seed=77)])
        r2 = engine.submit(p2, max_new_tokens=8)
        assert engine.run()[r2] == _expected(params, p2, 8)
        assert engine.prefix_stats()["block_hits"] == 4
        # The cache-off engine agrees and never indexes anything.
        cold = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=3,
            prefill_chunk=32, prefill_lanes=2, prefix_cache=False,
        )
        rc = cold.submit(p, max_new_tokens=10)
        assert cold.run()[rc] == want
        st = cold.prefix_stats()
        assert st["enabled"] is False and st["cached_blocks"] == 0

    @pytest.mark.slow
    def test_sampled_seeds_on_shared_prefix(self, params):
        """Two sampled requests sharing a cached prefix but carrying
        different seeds must each match their own cold-start output:
        sharing K/V must not couple PRNG streams. Slow lane (~13 s,
        three cold-start reference runs): greedy shared-prefix parity
        incl. park-reuse stays tier-1 in
        test_shared_prefix_parity_and_park_reuse."""
        p = _prompt(280, seed=90)
        outs = {}
        for prefix_cache in (True, False):
            engine = ContinuousBatcher(
                CFG, params, slots=2, cache_len=384, chunk_steps=4,
                prefill_chunk=32, prefix_cache=prefix_cache,
            )
            warm = engine.submit(p, max_new_tokens=2)
            engine.run()
            rids = {
                engine.submit(
                    p, max_new_tokens=8, temperature=0.9, top_k=16,
                    top_p=0.95, seed=seed,
                ): seed
                for seed in (5, 6)
            }
            res = engine.run()
            outs[prefix_cache] = {
                rids[r]: toks for r, toks in res.items() if r != warm
            }
            if prefix_cache:
                assert engine.prefix_stats()["block_hits"] >= 4
        assert outs[True] == outs[False]
        assert outs[True][5] != outs[True][6]  # seeds still diverge

    def test_mid_prefill_sharer_matches_only_ready_blocks(self, params):
        """A second sharer admitted while the writer is still
        mid-prefill may reuse exactly the blocks whose writing chunks
        have already been DISPATCHED (`ready`), must prefill the rest
        privately (the writer's registered-but-unready nodes dedup the
        insert), and both outputs stay exact."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=2,
            prefill_chunk=64, prefill_lanes=2,
        )
        p = _prompt(300, seed=201)
        ra = engine.submit(p, max_new_tokens=6)
        for _ in range(3):  # 64-token chunks: block 0 ready, block 1 not
            engine.step()
        rb = engine.submit(p, max_new_tokens=6)
        res: dict[int, list[int]] = {}
        while engine.has_work:
            engine.step()
            res.update(engine.drain_done())
        want = _expected(params, p, 6)
        assert res[ra] == want
        assert res[rb] == want
        st = engine.prefix_stats()
        assert st["block_hits"] == 1  # only the dispatched block
        assert st["block_misses"] == 3  # A's 2 cold + B's unready one

    def test_partial_block_divergence_never_shares(self, params):
        """Prompts agreeing on only PART of a block share nothing: the
        index is keyed by full-block content, so a 100-token common
        prefix inside a 128-token block must miss (the trie-corruption
        guard)."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=256, chunk_steps=3,
            prefill_chunk=32,
        )
        a = _prompt(150, seed=1)
        b = np.concatenate([a[:100], _prompt(50, seed=2)])
        ra = engine.submit(a, max_new_tokens=6)
        assert engine.run()[ra] == _expected(params, a, 6)
        rb = engine.submit(b, max_new_tokens=6)
        assert engine.run()[rb] == _expected(params, b, 6)
        st = engine.prefix_stats()
        assert st["block_hits"] == 0 and st["block_misses"] == 2

    def test_refcount_lifecycle(self, params):
        """Admit two sharers of a parked block: refcount 2 while both
        live, 1 after the first releases (block pinned, NOT freed),
        0 + parked after the second — then it is evictable."""
        engine = ContinuousBatcher(
            CFG, params, slots=2, cache_len=384, chunk_steps=2,
            prefill_chunk=64,
        )
        p = _prompt(200, seed=9)  # 1 shareable block
        engine.submit(p, max_new_tokens=2)
        engine.run()
        node = engine._prefix.match(p)[0]
        assert node.refcount == 0
        assert engine._prefix.parked_blocks == 1
        r_short = engine.submit(p, max_new_tokens=2)
        r_long = engine.submit(p, max_new_tokens=24)
        records: dict[int, dict] = {}
        while engine.has_work and r_short not in records:
            engine.step()
            records.update(engine.drain_done_records())
        assert r_long not in records  # still holding the block
        assert node.refcount == 1
        assert node.block not in engine._free_blocks
        while engine.has_work:
            engine.step()
            records.update(engine.drain_done_records())
        assert records[r_long]["tokens"] == _expected(params, p, 24)
        assert node.refcount == 0
        assert node.block not in engine._free_blocks  # parked, not freed
        assert engine._prefix.parked_blocks == 1
        assert engine._prefix.evict_lru() == node.block  # evictable
        assert engine._prefix.match(p) == []

    def test_eviction_under_pressure_is_lru(self, params):
        """With the free list dry, a mid-flight decode grab evicts the
        LEAST recently used parked prefix — the older cached template
        goes first, the newer one survives and still hits."""
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=384, chunk_steps=4,
            prefill_chunk=32,
        )  # pool: 3 allocatable blocks
        p_old = _prompt(130, seed=101)
        p_new = _prompt(130, seed=102)
        for p in (p_old, p_new):
            rid = engine.submit(p, max_new_tokens=2)
            assert engine.run()[rid] == _expected(params, p, 2)
        assert engine._prefix.parked_blocks == 2
        # 250-row footprint: 1 free block for the prompt, the decode
        # block must come from evicting exactly one parked prefix.
        big = engine.submit(_prompt(10, seed=103), max_new_tokens=240)
        assert len(engine.run()[big]) == 240
        st = engine.prefix_stats()
        assert st["evictions"] == 1
        assert engine._prefix.match(p_old) == []  # LRU victim
        assert len(engine._prefix.match(p_new)) == 1  # survivor
        follow = np.concatenate([p_new[:128], _prompt(20, seed=104)])
        rf = engine.submit(follow, max_new_tokens=4)
        assert engine.run()[rf] == _expected(params, follow, 4)
        assert engine.prefix_stats()["block_hits"] == 1


class TestLazyDecodeAllocation:
    def test_residency_grows_at_block_boundaries(self, params):
        """Admission allocates only the prompt's blocks; decode blocks
        appear as the write head crosses 128-row boundaries, and the
        pool drains fully on completion — headroom reports actual
        residency, not worst-case budgets."""
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=384, chunk_steps=4,
            prefill_chunk=32,
        )
        p = _prompt(4, seed=55)
        rid = engine.submit(p, max_new_tokens=300)  # 304 rows, 3 blocks
        seen: set[int] = set()
        out: dict[int, list[int]] = {}
        while engine.has_work:
            engine.step()
            seen.add(engine.kv_stats()["kv_blocks_in_use"])
            out.update(engine.drain_done())
        assert out[rid] == _expected(params, p, 300)
        assert {1, 2, 3} <= seen  # grew one boundary at a time
        kv = engine.kv_stats()
        assert kv["kv_blocks_in_use"] == 0
        assert kv["kv_blocks_reserved"] == 0
        assert sorted(engine._free_blocks) == [1, 2, 3]

    def test_dry_pool_truncates_with_pool_overflow(self, params):
        """The defensive valve: if a mid-flight grab finds the pool
        truly dry (the reservation invariant broken from outside),
        the request finishes AT ITS BACKED BOUNDARY — the emitted
        prefix is still exact, the completion is labeled
        pool_overflow, and the record carries truncated=True."""
        engine = ContinuousBatcher(
            CFG, params, slots=1, cache_len=384, chunk_steps=4,
            prefill_chunk=32,
        )
        p = _prompt(4, seed=66)
        rid = engine.submit(p, max_new_tokens=260)  # 3-block footprint
        while not any(r is not None for r in engine._slot_req):
            engine.step()
        engine._free_blocks.clear()  # simulate external pool theft
        records: dict[int, dict] = {}
        while engine.has_work:
            engine.step()
            records.update(engine.drain_done_records())
        rec = records[rid]
        assert rec["truncated"] is True
        # One 128-row block backs the 4-token prompt + 124 tokens.
        assert len(rec["tokens"]) == 124
        assert rec["tokens"] == _expected(params, p, 260)[:124]
        assert engine.obs.completed.value(
            labels={"reason": "pool_overflow"}
        ) == 1

"""Quantized serving (int8 paged KV + int8 weights, `LMConfig.
kv_dtype` / `w_dtype`).

Tier-1 surface for the quantization PR, in three layers:

1. **fp32-sim exact parity**: `kv_dtype="int8-sim"` + `w_dtype=
   "int8-sim"` runs the COMPLETE quantized machinery — parallel
   scale pools written at emit and read at every fold, QuantDense
   kernels with scale rows, the scale-carrying cache pytree through
   the spec round and the device-resident loop carry — with identity
   quantization and unit scales, so serving output must be
   TOKEN-IDENTICAL to quant-off serving (and to standalone
   generation) across greedy/sampled x spec on/off x prefix on/off x
   loop 1/8. Real int8 can never be token-exact (rounding is the
   point); the sim arm is how CI proves the data flow — scale
   indexing, emit/fold seams, sharing, rollback — adds exactly
   nothing.
2. **Pool accounting**: scale-pool residency mirrors data residency —
   scales are nonzero exactly for committed rows of a slot's backed
   blocks (== ceil(committed/128) blocks) and nowhere else off the
   scratch block.
3. **The roofline move**: the dtype-aware attribution cost model
   (`obs/attrib.py`) must report >= 40% lower HBM bytes per decode
   step for int8 KV+weights than for the bf16 configuration at
   identical residency — the PR's acceptance criterion, pinned
   through the same `cb_device_hbm_bytes_per_step` gauge the live
   engine maintains.

Deliberately NOT in conftest's `_SLOW_FILES`; shapes stay tiny.
"""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import (
    DecoderLM,
    LMConfig,
    QuantDense,
    quantize_lm_params,
)
from walkai_nos_tpu.models.serve import ContinuousBatcher
from walkai_nos_tpu.obs.attrib import (
    DispatchAttribution,
    kv_hbm_bytes_per_token,
    params_hbm_bytes,
)
from walkai_nos_tpu.obs.serving import ServingObs
from walkai_nos_tpu.ops.decode_attention import PAGE_ROWS

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)
SIM = dataclasses.replace(CFG, kv_dtype="int8-sim", w_dtype="int8-sim")
INT8 = dataclasses.replace(CFG, kv_dtype="int8", w_dtype="int8")

# Mixed ragged workload crossing 128-row block boundaries mid-prefill
# (140 > 128) and mid-decode (120 + 12 crosses at step 8).
GREEDY_SPECS = [(3, 9), (20, 12), (120, 12), (140, 8)]


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, n).astype(np.int32)


@pytest.fixture(scope="module")
def expected_greedy(params):
    """Standalone-generation expectation per (prompt_len, max_new) —
    the ONE greedy truth every engine variant (quant on/off, spec,
    prefix, loop) must reproduce token for token."""
    gen = make_generate_fn(CFG)
    out = {}
    for n, m in GREEDY_SPECS:
        toks = gen(
            params, jnp.asarray(_prompt(n, seed=n)[None]),
            max_new_tokens=m,
        )
        out[(n, m)] = [int(t) for t in np.asarray(toks)[0]]
    return out


def _engine(cfg, params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("cache_len", 384)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("chunk_steps", 3)
    kw.setdefault("prefill_chunk", 32)
    kw.setdefault("prefill_lanes", 2)
    if kw.pop("self_draft", False):
        kw.update(
            spec=True, spec_k=2, spec_min_accept=0.0,
            draft_cfg=cfg, draft_params=params,
        )
    return ContinuousBatcher(cfg, params, **kw)


def _serve_greedy(cfg, params, **kw):
    engine = _engine(cfg, params, **kw)
    rids = {
        engine.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
        for n, m in GREEDY_SPECS
    }
    res = engine.run()
    return {rids[r]: toks for r, toks in res.items()}


SAMPLED = dict(max_new_tokens=10, temperature=0.9, top_k=16,
               top_p=0.9, seed=123)


def _serve_sampled(cfg, params, **kw):
    engine = _engine(cfg, params, **kw)
    rid_a = engine.submit(_prompt(11, seed=42), **SAMPLED)
    rid_b = engine.submit(
        _prompt(130, seed=7), max_new_tokens=8, temperature=0.7,
        seed=99,
    )
    res = engine.run()
    return res[rid_a], res[rid_b]


class TestFp32SimExactParity:
    """quant-on (sim) serving == quant-off serving, token for token,
    across the engine's whole feature matrix."""

    def test_greedy_mixed_ragged(self, params, expected_greedy):
        got = _serve_greedy(SIM, params)
        assert got == expected_greedy

    def test_sampled_identical_to_quant_off(self, params):
        want = _serve_sampled(CFG, params)
        got = _serve_sampled(SIM, params)
        assert got == want

    def test_spec_self_draft_greedy(self, params, expected_greedy):
        """Speculative rounds over sim-quantized target AND draft
        pools (the draft mirrors the same scale-pool machinery):
        still the standalone greedy stream."""
        got = _serve_greedy(SIM, params, self_draft=True)
        assert got == expected_greedy

    def test_prefix_shared_greedy(self, params):
        """Two requests sharing a 140-token prefix: the second maps
        the first's sim-quantized blocks — scales ride the shared
        physical block ids — and both must equal standalone
        generation."""
        shared = _prompt(140, seed=140)
        tail = _prompt(6, seed=9)
        p2 = np.concatenate([shared[:128], tail])
        gen = make_generate_fn(CFG)
        engine = _engine(SIM, params)
        engine.submit(shared, max_new_tokens=8)
        engine.run()
        r2 = engine.submit(p2, max_new_tokens=8)
        res2 = engine.run()
        hits = engine.prefix_stats()["block_hits"]
        assert hits >= 1, "second prompt should reuse shared blocks"
        want = gen(
            params, jnp.asarray(p2[None]), max_new_tokens=8
        )
        assert res2[r2] == [int(t) for t in np.asarray(want)[0]]

    @pytest.mark.parametrize("sampled", [False, True])
    def test_loop8(self, params, expected_greedy, sampled):
        """The device-resident loop folds chunks with the scale
        pools riding the donated carry: loop 8 sim == quant-off."""
        if sampled:
            want = _serve_sampled(CFG, params)
            got = _serve_sampled(SIM, params, loop_steps=8)
            assert got == want
        else:
            got = _serve_greedy(SIM, params, loop_steps=8)
            assert got == expected_greedy

    def test_spec_loop_combined(self, params, expected_greedy):
        """The deepest corner: speculative rounds folded by the
        device-resident loop, both caches quantized-sim."""
        got = _serve_greedy(
            SIM, params, self_draft=True, loop_steps=4
        )
        assert got == expected_greedy


class TestInt8Serving:
    """Real int8 serving: not token-exact by design, but it must run
    the full engine feature set and keep its books straight."""

    def test_serves_full_budgets(self, params):
        got = _serve_greedy(INT8, params)
        assert {k: len(v) for k, v in got.items()} == {
            (n, m): m for n, m in GREEDY_SPECS
        }

    def test_scale_pool_residency_tracks_committed_rows(self, params):
        """Scale-pool accounting: after prefill of a 130-token
        prompt, the slot holds ceil(130/128) == 2 blocks; block 0 of
        the slot has all 128 scale rows nonzero, block 1 exactly
        rows 0..1, and no other non-scratch block carries a scale.
        Residency == ceil(committed/128), row for row."""
        engine = _engine(INT8, params, slots=1, prefill_lanes=1)
        engine.submit(_prompt(130, seed=130), max_new_tokens=64)
        # Drive until the slot flips live (prefill chunks dispatched)
        # but before any decode chunk advances the write head.
        for _ in range(32):
            engine.step()
            if engine._slot_req[0] is not None:
                break
        assert engine._slot_req[0] is not None
        pos = int(engine._slot_pos[0])
        blocks = list(engine._slot_blocks[0])
        assert len(blocks) == -(-pos // PAGE_ROWS)
        # One representative layer's K scale pool from device state.
        cache = engine._state[0]

        def find_scale(tree):
            for name, sub in tree.items():
                if name == "cached_key_scale":
                    return sub
                if hasattr(sub, "keys"):
                    found = find_scale(sub)
                    if found is not None:
                        return found
            return None

        scales = np.asarray(find_scale(cache))
        assert scales is not None
        written = scales > 0
        for i, blk in enumerate(blocks):
            rows_in_block = min(max(pos - i * PAGE_ROWS, 0), PAGE_ROWS)
            assert written[blk, :, :rows_in_block].all(), (i, blk)
            assert not written[blk, :, rows_in_block:].any(), (i, blk)
        others = [
            b for b in range(engine.pool_blocks)
            if b != 0 and b not in blocks
        ]
        assert not written[others].any(), "scales leaked off-slot"
        engine.run()

    def test_views_and_disabled_shapes(self, params):
        engine = _engine(INT8, params, obs=False)
        qs = engine.quant_stats()
        assert qs["obs_disabled"] is True
        assert qs["enabled"] is True
        assert qs["kv_dtype"] == "int8"
        assert qs["w_dtype"] == "int8"
        assert engine.debug_state()["quant"]["kv_storage_dtype"] == "int8"
        on = _engine(INT8, params)
        qs_on = on.quant_stats()
        assert "obs_disabled" not in qs_on
        assert qs_on["kv_cache_bytes"].get("int8", 0) > 0
        assert qs_on["kv_cache_bytes"].get("scale-f32", 0) > 0
        assert qs_on["weight_quant_seconds"] > 0
        assert qs_on["kv_bytes_per_token"] == kv_hbm_bytes_per_token(
            on.cfg
        )


class TestRooflineMove:
    """The acceptance criterion: int8 KV + int8 weights cut the
    analytic HBM bytes per decode step by >= 40% vs the bf16
    configuration at identical residency — measured through the same
    dtype-aware cost model and `cb_device_hbm_bytes_per_step` gauge
    the live engine maintains."""

    # A serving-shaped config: head_dim 64 (the scale row's 4 bytes
    # amortize over real rows), GQA, modest vocab.
    ROOF_CFG = LMConfig(
        vocab_size=512, hidden_dim=128, num_layers=2, num_heads=2,
        num_kv_heads=2, max_seq_len=512, dtype="bfloat16",
    )

    def _bytes_per_step(self, cfg, resident=4096):
        base = DecoderLM(
            dataclasses.replace(cfg, kv_dtype="model", w_dtype="model")
        ).init_params(jax.random.PRNGKey(0))
        served_cfg = dataclasses.replace(
            cfg, ragged_decode=True, paged_decode=True, paged_blocks=64,
        )
        tree = quantize_lm_params(base, served_cfg)
        obs = ServingObs(enabled=True)
        attrib = DispatchAttribution(
            obs,
            param_bytes=params_hbm_bytes(tree),
            kv_bytes_per_token=kv_hbm_bytes_per_token(served_cfg),
            hbm_bytes_per_s=1e12,
        )
        attrib.record(
            kind="decode", steps=1, host_s=0.0, device_s=1e-3,
            resident_tokens=resident,
        )
        return obs.hbm_step_bytes.value()

    def test_int8_cuts_hbm_bytes_per_step_40pct(self):
        bf16 = self._bytes_per_step(self.ROOF_CFG)
        int8 = self._bytes_per_step(
            dataclasses.replace(
                self.ROOF_CFG, kv_dtype="int8", w_dtype="int8"
            )
        )
        assert int8 <= 0.6 * bf16, (int8, bf16)

    def test_kv_bytes_per_token_dtype_aware(self):
        c = self.ROOF_CFG
        hd = c.hidden_dim // c.num_heads
        assert kv_hbm_bytes_per_token(c) == (
            c.num_layers * 2 * c.kv_heads * hd * 2
        )
        q = dataclasses.replace(c, kv_dtype="int8")
        assert kv_hbm_bytes_per_token(q) == (
            c.num_layers * 2 * c.kv_heads * (hd + 4)
        )
        f32 = dataclasses.replace(c, dtype="float32")
        assert kv_hbm_bytes_per_token(f32) == (
            c.num_layers * 2 * c.kv_heads * hd * 4
        )


class TestQuantDenseAndParams:
    """Module- and tree-level properties the parity suite rests on."""

    def test_quant_dense_sim_bit_exact_vs_dense(self):
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((3, 32)),
            jnp.bfloat16,
        )
        dense = nn.Dense(16, dtype=jnp.bfloat16, name="d")
        dp = dense.init(jax.random.PRNGKey(1), x)
        want = dense.apply(dp, x)
        qp = {
            "params": {
                **dp["params"],
                "scale": jnp.ones((16,), jnp.float32),
            }
        }
        got = QuantDense(
            16, dtype=jnp.bfloat16, use_bias=True, sim=True, name="d"
        ).apply(qp, x)
        np.testing.assert_array_equal(
            np.asarray(got, np.float32), np.asarray(want, np.float32)
        )

    def test_quantize_lm_params_targets_and_idempotence(self, params):
        q = quantize_lm_params(params, INT8)
        qkv = q["block0"]["attn"]["qkv"]
        assert qkv["kernel"].dtype == jnp.int8
        assert qkv["scale"].shape == (qkv["kernel"].shape[-1],)
        # Embedding / head / norms untouched.
        assert (
            q["embed"]["embedding"].dtype
            == params["embed"]["embedding"].dtype
        )
        assert q["head"]["kernel"].dtype == params["head"]["kernel"].dtype
        # Idempotent: re-quantizing is a no-op (numpy compare — no
        # per-leaf jit dispatches).
        q2 = quantize_lm_params(q, INT8)
        for a, b in zip(
            jax.tree_util.tree_leaves(q), jax.tree_util.tree_leaves(q2)
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        # Dequantized kernel tracks the original within int8 steps.
        want = np.asarray(params["block0"]["attn"]["qkv"]["kernel"])
        deq = np.asarray(qkv["kernel"], np.float64) * np.asarray(
            qkv["scale"]
        )
        tol = np.abs(want).max(axis=0) / 127 + 1e-9
        assert (np.abs(deq - want) <= tol[None, :]).all()

    def test_unknown_dtype_rejected_at_construction(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            LMConfig(kv_dtype="fp4")
        with pytest.raises(ValueError, match="w_dtype"):
            LMConfig(w_dtype="int4")

    def test_kv_quant_requires_paged_engine(self, params):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(
                INT8, params, slots=2, cache_len=256, paged=False
            )

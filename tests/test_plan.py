"""Diff-planner tests — port of the semantics covered by
`internal/controllers/migagent/plan/plan_test.go` (617 LoC)."""

from walkai_nos_tpu.controllers.tpuagent.plan import (
    CreateOperation,
    TilingState,
    new_tiling_plan,
)
from walkai_nos_tpu.tpu.annotations import SpecAnnotation
from walkai_nos_tpu.tpu.device import Device, DeviceList, DeviceStatus


def dev(profile, device_id, status=DeviceStatus.FREE, mesh=0):
    return Device(
        resource_name=f"walkai.io/tpu-{profile}",
        device_id=device_id,
        status=status,
        mesh_index=mesh,
    )


def state(*devices):
    return TilingState.from_devices(DeviceList(devices))


def spec(*entries):
    return [SpecAnnotation(mesh, profile, qty) for mesh, profile, qty in entries]


class TestEmptyCases:
    def test_empty_state_empty_spec(self):
        plan = new_tiling_plan(state(), [])
        assert plan.is_empty()

    def test_state_matches_spec_no_ops(self):
        s = state(dev("2x2", "a"), dev("2x2", "b"))
        plan = new_tiling_plan(s, spec((0, "2x2", 2)))
        assert plan.is_empty()

    def test_matches_spec_helper(self):
        s = state(dev("2x2", "a"), dev("2x2", "b", DeviceStatus.USED))
        assert s.matches_spec(spec((0, "2x2", 2)))
        assert not s.matches_spec(spec((0, "2x2", 1)))
        assert not s.matches_spec(spec((0, "1x1", 2)))


class TestCreates:
    def test_create_missing_profile(self):
        plan = new_tiling_plan(state(), spec((0, "2x2", 2)))
        assert plan.create_ops == [CreateOperation(0, "2x2", 2)]
        assert plan.delete_ops == []

    def test_create_additional_quantity(self):
        s = state(dev("2x2", "a", DeviceStatus.USED))
        plan = new_tiling_plan(s, spec((0, "2x2", 2)))
        assert plan.create_ops == [CreateOperation(0, "2x2", 1)]
        # the used device is never recreated
        assert plan.delete_ops == []


class TestDeletes:
    def test_delete_profile_not_in_spec(self):
        s = state(dev("2x2", "a"), dev("2x2", "b"))
        plan = new_tiling_plan(s, [])
        assert len(plan.delete_ops) == 1
        op = plan.delete_ops[0]
        assert op.quantity == 2
        assert {d.device_id for d in op.candidates} == {"a", "b"}

    def test_delete_excess_quantity(self):
        s = state(dev("1x1", "a"), dev("1x1", "b"), dev("1x1", "c"))
        plan = new_tiling_plan(s, spec((0, "1x1", 1)))
        assert plan.delete_ops[0].quantity == 2

    def test_deletion_candidates_prefer_free(self):
        # `plan_test.go`: free devices are preferred deletion candidates.
        s = state(
            dev("1x1", "used-1", DeviceStatus.USED),
            dev("1x1", "free-1"),
            dev("1x1", "free-2"),
        )
        plan = new_tiling_plan(s, spec((0, "1x1", 1)))
        op = plan.delete_ops[0]
        assert op.quantity == 2
        assert [d.device_id for d in op.candidates[:2]] == ["free-1", "free-2"]


class TestRecreateSemantics:
    def test_creating_new_profiles_recreates_existing_free(self):
        # "Creating new profiles on a GPU should delete and re-create all
        # the existing free MIG profiles" (`plan_test.go:204` analogue):
        # gives the packer the whole free area.
        s = state(
            dev("2x2", "free-2x2"),
            dev("1x1", "used-1x1", DeviceStatus.USED),
        )
        plan = new_tiling_plan(s, spec((0, "2x2", 1), (0, "1x1", 5)))
        # wants 4 more 1x1; the free 2x2 must be deleted and re-created.
        deletes = {(o.profile, o.quantity) for o in plan.delete_ops}
        creates = {(o.profile, o.quantity) for o in plan.create_ops}
        assert ("2x2", 1) in deletes
        assert ("1x1", 4) in creates
        assert ("2x2", 1) in creates  # re-create

    def test_no_recreate_on_meshes_without_creates(self):
        s = state(
            dev("2x2", "m0", mesh=0),
            dev("2x2", "m1-a", mesh=1),
        )
        plan = new_tiling_plan(
            s, spec((0, "2x2", 1), (1, "2x2", 1), (1, "1x1", 4))
        )
        # mesh 0 satisfied: no ops for mesh 0
        assert all(o.mesh_index == 1 for o in plan.create_ops)
        assert all(o.mesh_index == 1 for o in plan.delete_ops)
        # mesh 1's free 2x2 is recreated
        assert {(o.profile, o.quantity) for o in plan.create_ops} == {
            ("1x1", 4),
            ("2x2", 1),
        }

    def test_recreate_excludes_devices_already_doomed(self):
        # A free device already being deleted (excess quantity) must not be
        # double-counted by the recreate pass.
        s = state(
            dev("2x2", "a"),
            dev("2x2", "b"),
        )
        plan = new_tiling_plan(s, spec((0, "2x2", 1), (0, "1x1", 4)))
        # Want: delete one 2x2 (excess), recreate the kept one, create 4 1x1.
        create_map = {(o.profile): o.quantity for o in plan.create_ops}
        assert create_map["1x1"] == 4
        assert create_map["2x2"] == 1
        delete_map = {o.profile: o.quantity for o in plan.delete_ops}
        assert delete_map["2x2"] == 2  # both free ones go (1 excess + 1 recreate)

    def test_used_devices_never_in_recreate(self):
        s = state(
            dev("2x2", "used", DeviceStatus.USED),
        )
        plan = new_tiling_plan(s, spec((0, "2x2", 1), (0, "1x1", 4)))
        assert plan.create_ops == [CreateOperation(0, "1x1", 4)]
        assert plan.delete_ops == []


class TestMultiMesh:
    def test_ops_carry_mesh_index(self):
        s = state(dev("2x2", "a", mesh=0), dev("1x1", "b", mesh=1))
        plan = new_tiling_plan(
            s, spec((0, "2x2", 1), (1, "1x1", 0), (1, "2x2", 1))
        )
        assert any(
            o.mesh_index == 1 and o.profile == "2x2" for o in plan.create_ops
        )
        assert any(
            o.mesh_index == 1 and o.profile == "1x1" for o in plan.delete_ops
        )


class TestPlanApplicationProperty:
    """Seeded fuzz of the differ's core invariant: simulating the
    actuator's application of a plan (delete free candidates, create
    requested) yields exactly the spec whenever no used device conflicts
    with it — `plan.go`'s purpose, checked over random states."""

    def _simulate_apply(self, state, plan):
        """Pure simulation of actuator._apply on (mesh, profile) counts."""
        from walkai_nos_tpu.tpu.tiling.profile import extract_profile_name

        counts = {}
        deleted_ids = set()
        for op in plan.delete_ops:
            remaining = op.quantity
            for device in op.candidates:
                if remaining == 0:
                    break
                if not device.is_free() or device.device_id in deleted_ids:
                    continue
                deleted_ids.add(device.device_id)
                remaining -= 1
        for idx, devs in state.items():
            for d in devs:
                if d.device_id in deleted_ids:
                    continue
                key = (idx, extract_profile_name(d.resource_name))
                counts[key] = counts.get(key, 0) + 1
        for op in plan.create_ops:
            key = (op.mesh_index, op.profile)
            counts[key] = counts.get(key, 0) + op.quantity
        return counts

    def test_random_states_converge_to_spec(self):
        import random

        from walkai_nos_tpu.api import constants
        from walkai_nos_tpu.tpu.device import Device, DeviceStatus

        rng = random.Random(7)
        profiles = ["1x1", "1x2", "2x2", "2x4"]
        for _ in range(300):
            # Random observed state: up to 6 devices on one mesh.
            devices = DeviceList()
            for i in range(rng.randrange(0, 7)):
                devices.append(
                    Device(
                        resource_name=constants.RESOURCE_TPU_SLICE_PREFIX
                        + rng.choice(profiles),
                        device_id=f"d{i}",
                        status=rng.choice(
                            [DeviceStatus.FREE, DeviceStatus.USED]
                        ),
                        mesh_index=0,
                    )
                )
            state = TilingState.from_devices(devices)
            # Random spec that keeps every used device (the planner's
            # contract: used devices are never planned away).
            used_counts: dict[str, int] = {}
            for d in devices:
                if not d.is_free():
                    from walkai_nos_tpu.tpu.tiling.profile import (
                        extract_profile_name,
                    )

                    p = extract_profile_name(d.resource_name)
                    used_counts[p] = used_counts.get(p, 0) + 1
            spec_counts = dict(used_counts)
            for p in rng.sample(profiles, rng.randrange(0, len(profiles))):
                spec_counts[p] = spec_counts.get(p, 0) + rng.randrange(1, 3)
            spec = [
                SpecAnnotation(mesh_index=0, profile=p, quantity=q)
                for p, q in spec_counts.items()
            ]
            plan = new_tiling_plan(state, spec)
            result = self._simulate_apply(state, plan)
            desired = {
                (0, p): q for p, q in spec_counts.items() if q > 0
            }
            assert result == desired, (
                f"spec {spec_counts} from state "
                f"{[(d.device_id, d.resource_name, d.status) for d in devices]}"
                f" -> plan {plan.summary()} -> {result}"
            )

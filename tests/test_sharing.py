"""Sharing (chip-count) model tests — the slicing-model analogue suite
(reference weight: `pkg/gpu/slicing/{gpu_test.go,node_test.go}` 387+515
LoC of table-driven cases)."""

import pytest

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.tpu import topology
from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpu.sharing.mesh import SharedTpuMesh
from walkai_nos_tpu.tpu.sharing.node import SharingNode
from walkai_nos_tpu.tpu.sharing.profile import (
    SharedProfile,
    shared_profile_resource_name,
)

V5E = topology.KNOWN_MODELS["tpu-v5-lite-podslice"]  # 2x4, 8 chips


def mesh(used=None, free=None):
    return SharedTpuMesh(model=V5E, used=used or {}, free=free or {})


class TestSharedProfile:
    def test_parse_and_resource_name(self):
        p = SharedProfile.parse("2c")
        assert p.chip_count() == 2
        assert p.as_resource_name() == "walkai.io/tpu-shared-2c"
        assert shared_profile_resource_name("4c") == (
            constants.RESOURCE_TPU_SHARED_PREFIX + "4c"
        )

    @pytest.mark.parametrize("bad", ["2", "c2", "2gb", "", "2x2"])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            SharedProfile.parse(bad)

    def test_ordering(self):
        assert SharedProfile.parse("1c").smaller_than(SharedProfile.parse("4c"))


class TestSharedTpuMeshValidate:
    def test_ok(self):
        mesh(used={"2c": 2}, free={"1c": 4}).validate()  # 8 chips exactly

    def test_overcommitted(self):
        with pytest.raises(GenericError):
            mesh(used={"4c": 2}, free={"1c": 1}).validate()  # 9 > 8


class TestSharedTpuMeshUpdateGeometry:
    """Mirrors the two-phase strategy cases of `slicing/gpu_test.go`."""

    def test_already_satisfied_is_noop(self):
        m = mesh(free={"2c": 2})
        assert m.update_geometry_for({"2c": 2}) is False
        assert m.geometry() == {"2c": 2}

    def test_phase1_fills_spare_chips(self):
        # 4 chips used, 4 spare: two 2c shares fit without touching free.
        m = mesh(used={"4c": 1})
        assert m.update_geometry_for({"2c": 2}) is True
        assert m.free_count("2c") == 2
        m.validate()

    def test_phase1_smallest_first(self):
        # 3 spare chips; wanting 2c+1c packs both (1c first, then 2c).
        m = mesh(used={"4c": 1, "1c": 1})
        assert m.update_geometry_for({"2c": 1, "1c": 1}) is True
        assert m.free_count("1c") == 1
        assert m.free_count("2c") == 1

    def test_phase2_deletes_free_and_repacks(self):
        # No spare chips; a free 4c must be broken up to provide 2x2c.
        m = mesh(used={"4c": 1}, free={"4c": 1})
        assert m.update_geometry_for({"2c": 2}) is True
        assert m.free_count("2c") == 2
        assert m.free_count("4c") == 0
        m.validate()

    def test_phase2_keeps_fitting_free_shares(self):
        # Free = {2c:1, 1c:2}; want one 1c more than free... already free.
        # Want a 4c: spare 0, pool = 4 chips of free -> new 4c replaces all.
        m = mesh(used={"4c": 1}, free={"2c": 1, "1c": 2})
        assert m.update_geometry_for({"4c": 1}) is True
        assert m.free_count("4c") == 1
        # old free shares no longer fit (pool exhausted)
        assert m.free_count("2c") == 0 and m.free_count("1c") == 0
        m.validate()

    def test_used_shares_never_touched(self):
        m = mesh(used={"2c": 3}, free={"2c": 1})
        before_used = dict(m.used)
        m.update_geometry_for({"4c": 1})
        assert m.used == before_used
        m.validate()

    def test_unsatisfiable_returns_false(self):
        m = mesh(used={"4c": 2})  # host full with used shares
        assert m.update_geometry_for({"1c": 1}) is False

    def test_add_pod_moves_free_to_used(self):
        m = mesh(free={"2c": 2})
        m.add_pod("2c")
        assert m.used == {"2c": 1} and m.free == {"2c": 1}
        with pytest.raises(GenericError):
            m.add_pod("4c")

    def test_clone_is_independent(self):
        m = mesh(free={"2c": 1})
        c = m.clone()
        c.add_pod("2c")
        assert m.free == {"2c": 1} and m.used == {}


def _labels():
    return {
        constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
        constants.LABEL_TPU_TOPOLOGY: "2x4",
    }


class TestSharingNode:
    def test_from_node_builds_meshes(self):
        annos = {
            "nos.walkai.io/status-tpu-0-2c-used": "1",
            "nos.walkai.io/status-tpu-0-2c-free": "2",
            "nos.walkai.io/status-tpu-0-2x2-free": "1",  # tiling: ignored
        }
        node = SharingNode.from_node("n1", _labels(), annos)
        assert node.model is not None
        assert node.geometry() == {0: {"2c": 3}}

    def test_from_node_non_tpu(self):
        node = SharingNode.from_node("n1", {}, {})
        assert node.model is None and node.meshes == []

    def test_from_node_multi_host_refused(self):
        labels = dict(_labels())
        labels[constants.LABEL_TPU_TOPOLOGY] = "4x4"
        node = SharingNode.from_node("n1", labels, {})
        assert node.model is None

    def test_has_free_capacity(self):
        empty = SharingNode.from_node("n1", _labels(), {})
        assert empty.has_free_capacity()  # 8 spare chips
        full = SharingNode.from_node(
            "n2", _labels(), {"nos.walkai.io/status-tpu-0-8c-used": "1"}
        )
        assert not full.has_free_capacity()

    def test_update_geometry_and_add_pod(self):
        node = SharingNode.from_node("n1", _labels(), {})
        assert node.update_geometry_for({"2c": 4}) is True
        assert node.provides_profiles({"2c": 4})
        node.add_pod({"2c": 2})
        assert node.geometry()[0] == {"2c": 4}
        assert node.meshes[0].used == {"2c": 2}

    def test_add_pod_rejects_unprovided(self):
        node = SharingNode.from_node("n1", _labels(), {})
        with pytest.raises(GenericError):
            node.add_pod({"2c": 1})

    def test_clone_independent(self):
        node = SharingNode.from_node(
            "n1", _labels(), {"nos.walkai.io/status-tpu-0-2c-free": "1"}
        )
        clone = node.clone()
        clone.add_pod({"2c": 1})
        assert node.meshes[0].used == {}


class TestRepackKeepsWantedProfiles:
    """Regression (review finding): a wanted profile already covered by a
    free share must survive the phase-2 repack — it must not lose its
    chips to the shortfall of a smaller profile."""

    def test_covered_profile_survives_repack(self):
        m = mesh(free={"4c": 1, "2c": 2})  # 8 chips all free
        assert m.update_geometry_for({"1c": 1, "4c": 1}) is True
        assert m.free_count("4c") == 1
        assert m.free_count("1c") == 1
        m.validate()

    def test_node_level_multi_profile_demand(self):
        node = SharingNode.from_node(
            "n1",
            {
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: "2x4",
            },
            {
                "nos.walkai.io/status-tpu-0-4c-free": "1",
                "nos.walkai.io/status-tpu-0-2c-free": "2",
            },
        )
        assert node.update_geometry_for({"1c": 1, "4c": 1}) is True
        assert node.provides_profiles({"1c": 1, "4c": 1})

"""Sequence-parallel prefill ops (`ops/sp_prefill.py`) +
`parallel/sharding.seq_shard_bounds`.

Tier-1 surface for the long-context lane's device-level pieces:

- `streamed_cache_attention` must match the dense reference tail
  (`models/lm._masked_cache_attention`, ragged) numerically — MHA and
  GQA, ragged per-row offsets, a cache length that is not a tile
  multiple, and tile sizes that force multiple online-softmax folds —
  because on TPU it REPLACES the reference inside the paged prefill
  scatter+attend (`_sp_stream_backend_ok`), so any drift would change
  served tokens;
- `sp_ring_prefill` must match single-device causal attention over an
  emulated ring mesh (the conftest's 8 virtual CPU devices) and
  reject a sequence the axis can't shard evenly;
- `seq_shard_bounds` must cover [0, length) exactly once with
  contiguous, balanced shards — every consumer of the SP plane
  agrees on which global positions a shard owns through this one
  rule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.lm import _masked_cache_attention
from walkai_nos_tpu.ops.sp_prefill import (
    sp_ring_prefill,
    streamed_cache_attention,
)
from walkai_nos_tpu.parallel.mesh import MeshAxes, build_mesh
from walkai_nos_tpu.parallel.sharding import seq_shard_bounds


def _qkv_cache(rng, batch, heads, kv_heads, steps, cache_len, d):
    q = jnp.asarray(
        rng.standard_normal((batch, heads, steps, d)), jnp.float32
    )
    k = jnp.asarray(
        rng.standard_normal((batch, kv_heads, cache_len, d)),
        jnp.float32,
    )
    v = jnp.asarray(
        rng.standard_normal((batch, kv_heads, cache_len, d)),
        jnp.float32,
    )
    return q, k, v


class TestStreamedCacheAttention:
    @pytest.mark.parametrize(
        "heads,kv_heads", [(2, 2), (4, 2)],
        ids=["mha", "gqa"],
    )
    def test_matches_dense_reference_ragged(self, heads, kv_heads):
        """Streamed == dense for ragged per-row offsets (each batch
        row at a different write position), MHA and GQA, with a tile
        small enough that every row's visible window spans several
        folds."""
        rng = np.random.default_rng(0)
        q, k, v = _qkv_cache(rng, 3, heads, kv_heads, 8, 96, 16)
        idx = jnp.asarray([0, 37, 85], jnp.int32)
        ref = _masked_cache_attention(q, k, v, idx, True)
        out = streamed_cache_attention(q, k, v, idx, tile=16)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_non_multiple_cache_len_and_tile_cap(self):
        """A cache length the tile doesn't divide is padded, and the
        padding must be invisible (masked by `k_pos < cache_len`);
        a tile larger than the cache clamps to one fold."""
        rng = np.random.default_rng(1)
        q, k, v = _qkv_cache(rng, 2, 2, 2, 4, 57, 8)
        idx = jnp.asarray([10, 56], jnp.int32)
        ref = _masked_cache_attention(q, k, v, idx, True)
        for tile in (13, 57, 4096):
            out = streamed_cache_attention(q, k, v, idx, tile=tile)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5,
                err_msg=f"tile={tile}",
            )

    def test_future_tile_skip_changes_nothing(self):
        """Rows near position 0 leave most tiles wholly future
        (the `lax.cond` skip path): the result must still equal the
        reference — the skip is an optimization, never a truncation."""
        rng = np.random.default_rng(2)
        q, k, v = _qkv_cache(rng, 2, 2, 2, 2, 128, 8)
        idx = jnp.asarray([0, 3], jnp.int32)
        ref = _masked_cache_attention(q, k, v, idx, True)
        out = streamed_cache_attention(q, k, v, idx, tile=8)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )


class TestSpRingPrefill:
    def test_matches_single_device_causal(self):
        """Sequence sharded over a 4-way ring on the emulated mesh ==
        single-device causal attention (the device-level form of the
        serving lane's schedule)."""
        mesh = build_mesh(
            jax.devices()[:4], axes=MeshAxes(model=4)
        )
        rng = np.random.default_rng(3)
        b, h, s, d = 1, 2, 64, 16
        q, k, v = (
            jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
            for _ in range(3)
        )
        out = sp_ring_prefill(q, k, v, mesh)
        scale = d ** -0.5
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, -1e30)
        ref = jnp.einsum(
            "bhqk,bhkd->bhqd", jax.nn.softmax(logits, axis=-1), v
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5
        )

    def test_uneven_sequence_rejected(self):
        mesh = build_mesh(
            jax.devices()[:4], axes=MeshAxes(model=4)
        )
        rng = np.random.default_rng(4)
        q, k, v = (
            jnp.asarray(
                rng.standard_normal((1, 2, 66, 16)), jnp.float32
            )
            for _ in range(3)
        )
        with pytest.raises(ValueError, match="equal shards"):
            sp_ring_prefill(q, k, v, mesh)


class TestSeqShardBounds:
    def test_partition_covers_exactly_once(self):
        for n_shards in (1, 2, 3, 4, 7):
            for length in (0, 1, 5, 64, 129):
                spans = [
                    seq_shard_bounds(i, n_shards, length)
                    for i in range(n_shards)
                ]
                # Contiguous, ordered, covering [0, length).
                assert spans[0][0] == 0
                assert spans[-1][1] == length
                for (a, b), (c, d) in zip(spans, spans[1:]):
                    assert b == c
                    assert a <= b and c <= d
                # Balanced: sizes differ by at most 1, remainder
                # dealt to the leading shards.
                sizes = [b - a for a, b in spans]
                assert max(sizes) - min(sizes) <= 1
                assert sorted(sizes, reverse=True) == sizes

    def test_out_of_range_shard_rejected(self):
        with pytest.raises(ValueError, match="out of range"):
            seq_shard_bounds(2, 2, 10)
        with pytest.raises(ValueError, match="out of range"):
            seq_shard_bounds(-1, 2, 10)

"""Shared test helpers (the factory/util analogue of `pkg/test/util`)."""

from __future__ import annotations

import time


def eventually(fn, timeout=10.0, interval=0.05, msg="condition"):
    """Poll `fn` until truthy — the Gomega `Eventually` analogue used by
    every controller-loop suite. Exceptions are retried (assertion helpers
    race with controllers mid-retile by design)."""
    deadline = time.monotonic() + timeout
    last_exc = None
    while time.monotonic() < deadline:
        try:
            if fn():
                return
            last_exc = None
        except Exception as e:
            last_exc = e
        time.sleep(interval)
    raise AssertionError(f"eventually timed out: {msg} (last: {last_exc})")

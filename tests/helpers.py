"""Shared test helpers (the factory/util analogue of `pkg/test/util`)."""

from __future__ import annotations

import time


def eventually(fn, timeout=10.0, interval=0.05, msg="condition"):
    """Poll `fn` until truthy — the Gomega `Eventually` analogue used by
    every controller-loop suite. Exceptions are retried (assertion helpers
    race with controllers mid-retile by design)."""
    deadline = time.monotonic() + timeout
    last_exc = None
    while time.monotonic() < deadline:
        try:
            if fn():
                return
            last_exc = None
        except Exception as e:
            last_exc = e
        time.sleep(interval)
    raise AssertionError(f"eventually timed out: {msg} (last: {last_exc})")


def make_flaky_watch(client, on_outage):
    """Patch a RestKubeClient's _watch_once to fail once, running
    `on_outage` during the simulated stream outage (shared by the rest
    client and shared-watch suites)."""
    orig = client._watch_once
    failed = []

    def flaky(kind, namespace, rv_box, stop):
        if not failed:
            failed.append(True)
            on_outage()
            from walkai_nos_tpu.kube.client import ApiError

            raise ApiError(410, "gone")
        return orig(kind, namespace, rv_box, stop)

    client._watch_once = flaky

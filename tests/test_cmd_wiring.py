"""Binary wiring: build_manager functions drive real control loops.

Uses the FakeKubeClient the way the mains use RestKubeClient (same
interface), asserting the partitioner wiring initializes a fresh TPU node —
the `cmd/` analogue of the reference's manager-boot integration tests.
"""

import time

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd.tpuagent import build_manager as build_agent_manager
from walkai_nos_tpu.cmd.tpupartitioner import build_manager as build_part_manager
from walkai_nos_tpu.config import AgentConfig, PartitionerConfig
from walkai_nos_tpu.controllers.tpuagent.shared import SharedState
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.tiling.client import DevicePluginClient, TilingClient
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient


def _tpu_node(name="host-a"):
    return {
        "metadata": {
            "name": name,
            "labels": {
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: "2x4",
                constants.LABEL_TPU_PARTITIONING: "tiling",
            },
        },
        "status": {"capacity": {}, "allocatable": {}},
    }


def _eventually(fn, timeout=10.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


class TestPartitionerWiring:
    def test_manager_initializes_fresh_node(self):
        kube = FakeKubeClient()
        kube.create("Node", _tpu_node())
        manager = build_part_manager(kube, PartitionerConfig())
        with manager:
            _eventually(
                lambda: any(
                    s.profile == "2x4"
                    for s in parse_node_annotations(
                        objects.annotations(kube.get("Node", "host-a"))
                    )[1]
                ),
                msg="node controller writes default tiling spec",
            )

    def test_controller_names_match_contract(self):
        manager = build_part_manager(FakeKubeClient(), PartitionerConfig())
        names = {c.name for c in manager.controllers}
        assert constants.PARTITIONER_CONTROLLER_NAME in names
        pod_ctrl = next(
            c
            for c in manager.controllers
            if c.name == constants.PARTITIONER_CONTROLLER_NAME
        )
        assert pod_ctrl.max_concurrent == 1  # mig_controller.go:204

    def test_pending_pod_retry_is_event_driven(self):
        """The pod controller never requeues periodically; a pending pod is
        retried when a partitioned node changes (the reference's watch
        mapping, `mig_controller.go:180-207`)."""
        from walkai_nos_tpu.controllers.partitioner import (
            PodController,
            make_node_event_mapper,
        )
        from walkai_nos_tpu.kube.runtime import Request

        kube = FakeKubeClient()
        kube.create(
            "Pod",
            {
                "metadata": {"name": "j1", "namespace": "default"},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"walkai.io/tpu-2x2": "1"}
                            },
                        }
                    ]
                },
                "status": {
                    "phase": "Pending",
                    "conditions": [
                        {
                            "type": "PodScheduled",
                            "status": "False",
                            "reason": "Unschedulable",
                        }
                    ],
                },
            },
        )
        # No nodes: reconcile must NOT schedule a retry.
        result = PodController(kube).reconcile(Request("j1", "default"))
        assert not result.requeue and result.requeue_after is None

        # A node event re-enqueues exactly the pending slice pod.
        enqueued = []
        mapper = make_node_event_mapper(kube, enqueued.append)
        kube.create(
            "Pod",
            {
                "metadata": {"name": "no-tpu", "namespace": "default"},
                "spec": {"containers": [{"name": "m", "resources": {}}]},
                "status": {"phase": "Pending"},
            },
        )
        mapper(Request("host-a"))
        assert [(r.namespace, r.name) for r in enqueued] == [("default", "j1")]


class TestAgentWiring:
    def test_reporter_writes_status_for_existing_slices(self):
        kube = FakeKubeClient()
        kube.create("Node", _tpu_node())
        tpudev = FakeTpudevClient(mesh=(2, 4))
        from walkai_nos_tpu.tpu.tiling.packing import Placement

        created = tpudev.create_slices([Placement("2x4", (0, 0), (2, 4))])
        resources = FakeResourceClient()
        from walkai_nos_tpu.tpu.device import Device, DeviceStatus

        resources.set_allocatable(
            [
                Device(
                    s.resource_name, s.slice_id, DeviceStatus.UNKNOWN
                )
                for s in created
            ]
        )
        tiling = TilingClient(resources, tpudev)
        manager, _shared = build_agent_manager(
            kube,
            tiling,
            DevicePluginClient(kube, restart_timeout=1.0),
            "host-a",
            AgentConfig(report_interval_s=0.1),
        )
        with manager:
            _eventually(
                lambda: any(
                    s.profile == "2x4" and s.status.value == "free"
                    for s in parse_node_annotations(
                        objects.annotations(kube.get("Node", "host-a"))
                    )[0]
                ),
                msg="reporter publishes free 2x4 status",
            )

"""Binary wiring: build_manager functions drive real control loops.

Uses the FakeKubeClient the way the mains use RestKubeClient (same
interface), asserting the partitioner wiring initializes a fresh TPU node —
the `cmd/` analogue of the reference's manager-boot integration tests.
"""

import time

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd.tpuagent import build_manager as build_agent_manager
from walkai_nos_tpu.cmd.tpupartitioner import build_manager as build_part_manager
from walkai_nos_tpu.config import AgentConfig, PartitionerConfig
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.tpu.annotations import parse_node_annotations
from walkai_nos_tpu.tpu.tiling.client import DevicePluginClient, TilingClient
from walkai_nos_tpu.resource.fake import FakeResourceClient
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient


def _tpu_node(name="host-a"):
    return {
        "metadata": {
            "name": name,
            "labels": {
                constants.LABEL_TPU_ACCELERATOR: "tpu-v5-lite-podslice",
                constants.LABEL_TPU_TOPOLOGY: "2x4",
                constants.LABEL_TPU_PARTITIONING: "tiling",
            },
        },
        "status": {"capacity": {}, "allocatable": {}},
    }


def _eventually(fn, timeout=10.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return
        except Exception:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out: {msg}")


class TestPartitionerWiring:
    def test_manager_initializes_fresh_node(self):
        kube = FakeKubeClient()
        kube.create("Node", _tpu_node())
        manager = build_part_manager(kube, PartitionerConfig())
        with manager:
            _eventually(
                lambda: any(
                    s.profile == "2x4"
                    for s in parse_node_annotations(
                        objects.annotations(kube.get("Node", "host-a"))
                    )[1]
                ),
                msg="node controller writes default tiling spec",
            )

    def test_controller_names_match_contract(self):
        manager = build_part_manager(FakeKubeClient(), PartitionerConfig())
        names = {c.name for c in manager.controllers}
        assert constants.PARTITIONER_CONTROLLER_NAME in names
        pod_ctrl = next(
            c
            for c in manager.controllers
            if c.name == constants.PARTITIONER_CONTROLLER_NAME
        )
        assert pod_ctrl.max_concurrent == 1  # mig_controller.go:204

    def test_pending_pod_retry_is_event_driven(self):
        """The pod controller never requeues periodically; a pending pod is
        retried when a partitioned node changes (the reference's watch
        mapping, `mig_controller.go:180-207`)."""
        from walkai_nos_tpu.controllers.partitioner import (
            PodController,
            make_node_event_mapper,
        )
        from walkai_nos_tpu.kube.runtime import Request

        kube = FakeKubeClient()
        kube.create(
            "Pod",
            {
                "metadata": {"name": "j1", "namespace": "default"},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "resources": {
                                "requests": {"walkai.io/tpu-2x2": "1"}
                            },
                        }
                    ]
                },
                "status": {
                    "phase": "Pending",
                    "conditions": [
                        {
                            "type": "PodScheduled",
                            "status": "False",
                            "reason": "Unschedulable",
                        }
                    ],
                },
            },
        )
        # No nodes: reconcile must NOT schedule a retry.
        result = PodController(kube).reconcile(Request("j1", "default"))
        assert not result.requeue and result.requeue_after is None

        # A node event re-enqueues exactly the pending slice pod.
        enqueued = []
        mapper = make_node_event_mapper(kube, enqueued.append)
        kube.create(
            "Pod",
            {
                "metadata": {"name": "no-tpu", "namespace": "default"},
                "spec": {"containers": [{"name": "m", "resources": {}}]},
                "status": {"phase": "Pending"},
            },
        )
        mapper(Request("host-a"))
        # The pending pod, plus the planner wake-up sentinel (empty
        # name) driving the stranded-pool-share sweep.
        assert [(r.namespace, r.name) for r in enqueued] == [
            ("default", "j1"), ("", ""),
        ]


class TestAgentWiring:
    def test_reporter_writes_status_for_existing_slices(self):
        kube = FakeKubeClient()
        kube.create("Node", _tpu_node())
        tpudev = FakeTpudevClient(mesh=(2, 4))
        from walkai_nos_tpu.tpu.tiling.packing import Placement

        created = tpudev.create_slices([Placement("2x4", (0, 0), (2, 4))])
        resources = FakeResourceClient()
        from walkai_nos_tpu.tpu.device import Device, DeviceStatus

        resources.set_allocatable(
            [
                Device(
                    s.resource_name, s.slice_id, DeviceStatus.UNKNOWN
                )
                for s in created
            ]
        )
        tiling = TilingClient(resources, tpudev)
        manager, _shared = build_agent_manager(
            kube,
            tiling,
            DevicePluginClient(kube, restart_timeout=1.0),
            "host-a",
            AgentConfig(report_interval_s=0.1),
        )
        with manager:
            _eventually(
                lambda: any(
                    s.profile == "2x4" and s.status.value == "free"
                    for s in parse_node_annotations(
                        objects.annotations(kube.get("Node", "host-a"))
                    )[0]
                ),
                msg="reporter publishes free 2x4 status",
            )


class TestLeaderElectedPartitioner:
    def test_failover_hands_reconciling_to_the_standby(self):
        """Two partitioner replicas, leader-elected: only the leader's
        controllers run; when it dies, the standby's manager starts and
        picks up pending work (the reference's leaderElect deployment
        shape, 2 replicas)."""
        from walkai_nos_tpu.cmd.tpupartitioner import build_manager
        from walkai_nos_tpu.config import PartitionerConfig
        from walkai_nos_tpu.kube.leader import LeaderElector
        from tests.test_pod_controller import pending_slice_pod, tiling_node

        kube = FakeKubeClient()
        kube.create("Node", tiling_node("host-a"))

        def replica(identity):
            manager = build_manager(kube, PartitionerConfig())
            elector = LeaderElector(
                kube, "partitioner-leader", identity=identity,
                lease_duration=0.5, renew_interval=0.05,
                on_started_leading=manager.start,
                on_stopped_leading=manager.stop,
            )
            elector.start()
            return manager, elector

        m1, e1 = replica("replica-1")
        m2, e2 = replica("replica-2")
        try:
            _eventually(
                lambda: e1.is_leader.is_set() ^ e2.is_leader.is_set(),
                msg="exactly one leader",
            )
            if e1.is_leader.is_set():
                leader, standby = (m1, e1), (m2, e2)
            else:
                leader, standby = (m2, e2), (m1, e1)

            # The leader initializes the node (NodeController running).
            _eventually(
                lambda: any(
                    k.startswith("nos.walkai.io/spec-tpu")
                    for k in objects.annotations(kube.get("Node", "host-a"))
                ),
                msg="leader initialized the node",
            )

            # Kill the leader; the standby must take over and serve a
            # pending pod's retile.
            leader[1].stop()
            leader[0].stop()
            _eventually(
                lambda: standby[1].is_leader.is_set(),
                msg="standby acquired the lease",
            )
            kube.create("Pod", pending_slice_pod("p1", "2x2"))
            _eventually(
                lambda: any(
                    "2x2" in k
                    for k in objects.annotations(kube.get("Node", "host-a"))
                    if k.startswith("nos.walkai.io/spec-tpu")
                ),
                msg="standby retiled for the pending pod",
            )
        finally:
            for m, e in (m1, e1), (m2, e2):
                e.stop()
                m.stop()

"""Real-gRPC kubelet boundaries over unix sockets in tmp dirs.

The pod-resources client and the device plugin talk actual protobuf/gRPC
to a fake kubelet — protocol-real, hardware-free (SURVEY.md §4).
"""

import time

import grpc
import pytest

from walkai_nos_tpu.deviceplugin import PluginManager, SliceDevicePlugin
from walkai_nos_tpu.protos_gen import deviceplugin_pb2 as dp
from walkai_nos_tpu.resource.fake_kubelet import FakeKubelet, PodDevices
from walkai_nos_tpu.resource.lister import PodResourcesClient
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpudev.fake import FakeTpudevClient


@pytest.fixture
def kubelet():
    # Short tempdir: unix socket paths cap at ~107 chars, and pytest's
    # tmp_path nesting blows through it.
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="kl-", dir="/tmp")
    k = FakeKubelet(root)
    k.start()
    yield k
    k.stop()
    shutil.rmtree(root, ignore_errors=True)


def _list_and_watch(channel):
    """Open the v1beta1 ListAndWatch stream on a plugin channel."""
    return channel.unary_stream(
        "/v1beta1.DevicePlugin/ListAndWatch",
        request_serializer=dp.Empty.SerializeToString,
        response_deserializer=dp.ListAndWatchResponse.FromString,
    )(dp.Empty())


def _allocate(channel, device_ids):
    """One v1beta1 Allocate call for `device_ids`."""
    return channel.unary_unary(
        "/v1beta1.DevicePlugin/Allocate",
        request_serializer=dp.AllocateRequest.SerializeToString,
        response_deserializer=dp.AllocateResponse.FromString,
    )(
        dp.AllocateRequest(
            container_requests=[
                dp.ContainerAllocateRequest(devicesIDs=list(device_ids))
            ]
        )
    )


class TestPodResourcesClient:
    def test_allocatable_and_used(self, kubelet):
        kubelet.set_allocatable(
            [
                ("walkai.io/tpu-2x2", "2x2@0-0"),
                ("walkai.io/tpu-2x2", "2x2@0-2"),
                ("other.io/widget", "w0"),
            ]
        )
        kubelet.set_used(
            [
                PodDevices(
                    "job-1", "default", "main", "walkai.io/tpu-2x2",
                    ["2x2@0-0"],
                )
            ]
        )
        client = PodResourcesClient(kubelet.pod_resources_socket, timeout=5.0)
        try:
            alloc = client.get_allocatable_devices("walkai.io/tpu-")
            assert [d.device_id for d in alloc] == ["2x2@0-0", "2x2@0-2"]
            used = client.get_used_devices("walkai.io/tpu-")
            assert [d.device_id for d in used] == ["2x2@0-0"]
            assert used[0].status.value == "used"
        finally:
            client.close()


class TestDevicePlugin:
    def _tpudev_with_slices(self):
        tpudev = FakeTpudevClient(mesh=(2, 4))
        tpudev.create_slices(
            [
                Placement("2x2", (0, 0), (2, 2)),
                Placement("2x2", (0, 2), (2, 2)),
            ]
        )
        return tpudev

    def test_list_and_watch_and_allocate(self, kubelet):
        tpudev = self._tpudev_with_slices()
        plugin = SliceDevicePlugin(
            "walkai.io/tpu-2x2", tpudev, kubelet.plugin_dir, dev_dir="/dev"
        )
        plugin.start()
        try:
            plugin.register(kubelet.registration_socket)
            assert [r.resource_name for r in kubelet.registrations] == [
                "walkai.io/tpu-2x2"
            ]
            assert kubelet.registrations[0].version == "v1beta1"

            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            first = next(_list_and_watch(channel))
            assert sorted(d.ID for d in first.devices) == [
                "2x2@0-0", "2x2@0-2",
            ]
            assert all(d.health == "Healthy" for d in first.devices)

            resp = _allocate(channel, ["2x2@0-0"])
            creq = resp.container_responses[0]
            assert creq.envs["TPU_VISIBLE_CHIPS"] == "0,1,4,5"
            assert creq.envs["TPU_SLICE_ID"] == "2x2@0-0"
            assert sorted(d.host_path for d in creq.devices) == [
                "/dev/accel0", "/dev/accel1", "/dev/accel4", "/dev/accel5",
            ]
            channel.close()
        finally:
            plugin.stop()

    def test_list_and_watch_streams_retile(self, kubelet):
        tpudev = self._tpudev_with_slices()
        plugin = SliceDevicePlugin(
            "walkai.io/tpu-2x2", tpudev, kubelet.plugin_dir
        )
        plugin.start()
        try:
            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            stream = _list_and_watch(channel)
            assert len(next(stream).devices) == 2
            tpudev.delete_slice("2x2@0-2")
            plugin.notify()
            assert sorted(d.ID for d in next(stream).devices) == ["2x2@0-0"]
            channel.close()
        finally:
            plugin.stop()

    def test_plugin_manager_syncs_resources(self, kubelet):
        tpudev = FakeTpudevClient(mesh=(2, 4))
        tpudev.create_slices(
            [
                Placement("2x2", (0, 0), (2, 2)),
                Placement("1x2", (0, 2), (1, 2)),
            ]
        )
        manager = PluginManager(
            tpudev,
            plugin_dir=kubelet.plugin_dir,
            kubelet_socket=kubelet.registration_socket,
            poll_interval=0.1,
        )
        manager.sync()
        try:
            assert sorted(manager.plugins) == [
                "walkai.io/tpu-1x2", "walkai.io/tpu-2x2",
            ]
            registered = sorted(
                r.resource_name for r in kubelet.registrations
            )
            assert registered == ["walkai.io/tpu-1x2", "walkai.io/tpu-2x2"]
            # Retile: 1x2 goes away; its plugin stays, serving zero devices.
            tpudev.delete_slice("1x2@0-2")
            manager.sync()
            assert sorted(manager.plugins) == [
                "walkai.io/tpu-1x2", "walkai.io/tpu-2x2",
            ]
            plugin = manager.plugins["walkai.io/tpu-1x2"]
            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            stream = _list_and_watch(channel)
            deadline = time.monotonic() + 5
            devices = list(next(stream).devices)
            while devices and time.monotonic() < deadline:
                devices = list(next(stream).devices)
            assert devices == []
            channel.close()
        finally:
            manager.stop()


class TestSharePlugin:
    """The restored sharing actuation over REAL gRPC: spec geometry ->
    SharePluginManager -> kubelet registration + ListAndWatch +
    Allocate with the share's chip env."""

    def test_share_manager_registers_and_allocates(self, kubelet, tmp_path):
        from walkai_nos_tpu.deviceplugin.share_manager import (
            SharePluginManager,
        )

        manager = SharePluginManager(
            8,
            plugin_dir=kubelet.plugin_dir,
            kubelet_socket=kubelet.registration_socket,
            poll_interval=0.1,
            state_path=str(tmp_path / "shares.json"),
        )
        manager.set_geometry({"2c": 2})
        try:
            registered = [r.resource_name for r in kubelet.registrations]
            assert registered == ["walkai.io/tpu-shared-2c"]
            plugin = manager._manager.plugins["walkai.io/tpu-shared-2c"]
            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            first = next(_list_and_watch(channel))
            assert sorted(d.ID for d in first.devices) == ["2c#0", "2c#1"]

            resp = _allocate(channel, ["2c#0"])
            env = dict(resp.container_responses[0].envs)
            assert env["TPU_VISIBLE_CHIPS"] == "0,1"
            assert env["TPU_SLICE_ID"] == "2c#0"
            paths = [
                d.host_path for d in resp.container_responses[0].devices
            ]
            assert paths == ["/dev/accel0", "/dev/accel1"]
            channel.close()
        finally:
            manager.stop()

"""Telemetry subsystem (`walkai_nos_tpu/obs/`): registry semantics,
histogram bucket boundaries, ring-buffer wraparound, Prometheus
exposition format, profile-hook gating — and the contract that makes
the trace trustworthy: per-request ttft/wall reconstructed from
lifecycle spans equal `drain_done_records()` EXACTLY, and the
engine's `occupancy()`/`kv_stats()` dicts are views of the same
registry `/metrics` exports."""

import re

import numpy as np
import pytest

from walkai_nos_tpu.obs.metrics import (
    Registry,
    log_buckets,
)
from walkai_nos_tpu.obs.profile import ProfileHook
from walkai_nos_tpu.obs.serving import ServingObs
from walkai_nos_tpu.obs.trace import RequestTrace, Ring


class TestLogBuckets:
    def test_geometric_and_covering(self):
        b = log_buckets(1e-3, 100.0, per_decade=3)
        assert b[0] == 1e-3
        assert b[-1] >= 100.0
        assert list(b) == sorted(b)
        # Constant ratio ~10^(1/3): every adjacent pair within 10%
        # of it (bounds snap to 4 significant digits).
        ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
        for r in ratios:
            assert abs(r - 10 ** (1 / 3)) / 10 ** (1 / 3) < 0.1

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            log_buckets(0, 1.0)
        with pytest.raises(ValueError):
            log_buckets(1.0, 0.5)
        with pytest.raises(ValueError):
            log_buckets(1.0, 2.0, per_decade=0)


class TestHistogram:
    def _hist(self, bounds=(1.0, 2.0, 4.0, 8.0)):
        reg = Registry()
        return reg, reg.histogram("h_seconds", "t", buckets=bounds)

    def test_bucket_boundaries_le_inclusive(self):
        """Prometheus `le` semantics: a sample exactly ON a bound
        lands in that bucket, just above goes to the next."""
        reg, h = self._hist()
        h.observe(2.0)   # == bound 2 -> bucket le=2
        h.observe(2.001)  # -> bucket le=4
        h.observe(0.0)   # below first bound -> bucket le=1
        text = reg.render()
        assert 'h_seconds_bucket{le="1"} 1' in text
        assert 'h_seconds_bucket{le="2"} 2' in text  # cumulative
        assert 'h_seconds_bucket{le="4"} 3' in text
        assert 'h_seconds_bucket{le="8"} 3' in text
        assert 'h_seconds_bucket{le="+Inf"} 3' in text

    def test_overflow_counts_only_inf(self):
        reg, h = self._hist()
        h.observe(9.5)
        assert h.count() == 1
        assert h.sum() == 9.5
        text = reg.render()
        assert 'h_seconds_bucket{le="8"} 0' in text
        assert 'h_seconds_bucket{le="+Inf"} 1' in text
        assert "h_seconds_count 1" in text

    def test_quantile_within_one_bucket(self):
        _, h = self._hist()
        for v in (0.5, 1.5, 3.0, 7.0):
            h.observe(v)
        assert h.quantile(0.0) == 1.0
        assert h.quantile(0.5) == 2.0   # rank 2 of 4 -> le=2 bucket
        assert h.quantile(1.0) == 8.0
        # Every estimate is the upper bound of the sample's bucket:
        # exact to within one bucket width.
        assert h.quantile(0.25) == 1.0

    def test_quantile_overflow_clamps_to_last_bound(self):
        _, h = self._hist()
        h.observe(100.0)
        assert h.quantile(0.99) == 8.0

    def test_quantile_empty_and_invalid(self):
        _, h = self._hist()
        assert h.quantile(0.5) is None
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_unsorted_buckets_rejected(self):
        reg = Registry()
        with pytest.raises(ValueError):
            reg.histogram("bad_seconds", "t", buckets=(2.0, 1.0))


class TestRing:
    def test_wraparound_keeps_newest_in_order(self):
        r = Ring(4)
        for i in range(10):
            r.append(i)
        assert r.snapshot() == [6, 7, 8, 9]
        assert r.dropped == 6
        assert len(r) == 4

    def test_underfill_in_order(self):
        r = Ring(8)
        for i in range(3):
            r.append(i)
        assert r.snapshot() == [0, 1, 2]
        assert r.dropped == 0
        assert len(r) == 3

    def test_exact_capacity_boundary(self):
        r = Ring(3)
        for i in range(3):
            r.append(i)
        assert r.snapshot() == [0, 1, 2] and r.dropped == 0
        r.append(3)
        assert r.snapshot() == [1, 2, 3] and r.dropped == 1

    def test_zero_capacity_rejected(self):
        with pytest.raises(ValueError):
            Ring(0)


# One Prometheus text-format sample line (after HELP/TYPE comments).
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_+][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" -?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?$"
)


class TestExposition:
    def test_every_line_is_valid_prometheus_text(self):
        reg = Registry()
        reg.counter("a_total", "help a").inc(2, {"x": "1"})
        reg.gauge("b", "help b").set(1.5)
        h = reg.histogram("c_seconds", "help c", buckets=(0.1, 1.0))
        h.observe(0.05, {"op": "q"})
        h.observe(50.0, {"op": "q"})
        text = reg.render()
        assert text.endswith("\n")
        for line in text.strip().splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                continue
            assert _SAMPLE.match(line), line

    def test_help_and_type_lines(self):
        reg = Registry()
        reg.counter("x_total", "counts xs").inc()
        text = reg.render()
        assert "# HELP x_total counts xs" in text
        assert "# TYPE x_total counter" in text

    def test_histogram_contract(self):
        """Cumulative buckets, +Inf == _count, _sum present."""
        reg = Registry()
        h = reg.histogram("d_seconds", "t", buckets=(1.0, 2.0))
        for v in (0.5, 0.6, 1.5, 9.0):
            h.observe(v)
        text = reg.render()
        assert 'd_seconds_bucket{le="1"} 2' in text
        assert 'd_seconds_bucket{le="2"} 3' in text
        assert 'd_seconds_bucket{le="+Inf"} 4' in text
        assert "d_seconds_count 4" in text
        assert "d_seconds_sum 11.6" in text

    def test_label_escaping(self):
        reg = Registry()
        reg.counter("e_total", "t").inc(1, {"r": 'bad "q"\nline'})
        out = reg.render()
        assert 'r="bad \\"q\\"\\nline"' in out

    def test_unobserved_metrics_omitted(self):
        reg = Registry()
        reg.counter("never_total", "t")
        assert "never_total" not in reg.render()

    def test_nonfinite_values_render_not_crash(self):
        """One inf/NaN gauge (a ratio whose denominator hit zero)
        must not take down the whole exposition — the format has
        spellings for them."""
        reg = Registry()
        reg.gauge("ratio", "t").set(float("inf"))
        reg.gauge("neg", "t").set(float("-inf"))
        reg.gauge("nan", "t").set(float("nan"))
        reg.gauge("ok", "t").set(1.0)
        text = reg.render()
        assert "ratio +Inf" in text
        assert "neg -Inf" in text
        assert "nan NaN" in text
        assert "ok 1" in text


class TestRegistry:
    def test_get_or_create_and_kind_conflict(self):
        reg = Registry()
        c1 = reg.counter("x_total", "first help")
        c2 = reg.counter("x_total", "different help")
        assert c1 is c2
        assert c1.help == "first help"
        with pytest.raises(ValueError):
            reg.gauge("x_total", "now a gauge?")

    def test_concurrent_registration_single_winner(self):
        """Racing threads registering the same name must converge on
        ONE instrument (creation happens under the lock) — a loser
        must never silently receive a wrong-kind instance."""
        import threading

        reg = Registry()
        out = []

        def register():
            out.append(reg.counter("race_total", "t"))

        threads = [
            threading.Thread(target=register) for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(m is out[0] for m in out)

    def test_disabled_registry_noops(self):
        reg = Registry(enabled=False)
        c = reg.counter("x_total", "t")
        c.inc(5)
        g = reg.gauge("g", "t")
        g.set(2)
        h = reg.histogram("h_seconds", "t", buckets=(1.0,))
        h.observe(0.5)
        assert c.value() == 0.0
        assert g.value() is None
        assert h.count() == 0

    def test_counter_rejects_decrease(self):
        with pytest.raises(ValueError):
            Registry().counter("x_total", "t").inc(-1)

    def test_gauge_set_min_is_low_watermark(self):
        g = Registry().gauge("w", "t")
        g.set_min(5)
        g.set_min(3)
        g.set_min(9)
        assert g.value() == 3


class TestProfileHook:
    def _patched(self, monkeypatch):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda logdir: calls.append(("start", logdir)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        return calls

    def test_window_covers_exactly_n_dispatches(self, monkeypatch):
        calls = self._patched(monkeypatch)
        hook = ProfileHook()
        hook.arm(3, "/tmp/prof")
        for _ in range(5):
            hook.on_dispatch()
        assert calls == [("start", "/tmp/prof"), ("stop",)]
        s = hook.status()
        assert s["completed_windows"] == 1
        assert s["active"] is False
        assert s["remaining_dispatches"] == 0

    def test_unarmed_is_noop(self, monkeypatch):
        calls = self._patched(monkeypatch)
        hook = ProfileHook()
        for _ in range(10):
            hook.on_dispatch()
        assert calls == []

    def test_start_failure_disarms(self, monkeypatch):
        import jax

        def boom(logdir):
            raise RuntimeError("no profiler here")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        hook = ProfileHook()
        hook.arm(2, "/tmp/prof")
        hook.on_dispatch()
        hook.on_dispatch()  # must not retry or raise
        s = hook.status()
        assert s["active"] is False
        assert "no profiler here" in s["last_error"]

    def test_arm_validation(self):
        hook = ProfileHook()
        with pytest.raises(ValueError):
            hook.arm(0, "/tmp/p")
        with pytest.raises(ValueError):
            hook.arm(3, "")

    def test_from_env(self):
        hook = ProfileHook.from_env(
            {"WALKAI_PROFILE_DIR": "/tmp/x",
             "WALKAI_PROFILE_DISPATCHES": "7"}
        )
        assert hook.status()["remaining_dispatches"] == 7
        assert ProfileHook.from_env({}).status()[
            "remaining_dispatches"
        ] == 0

    def test_disabled_bundle_never_arms_from_env(self, monkeypatch):
        """WALKAI_OBS=0 + WALKAI_PROFILE_DIR set: the no-op bundle
        must be a real no-op — no capture window on a
        telemetry-disabled engine (and no bias in the overhead A/B's
        disabled arm)."""
        monkeypatch.setenv("WALKAI_PROFILE_DIR", "/tmp/prof")
        monkeypatch.setenv("WALKAI_PROFILE_DISPATCHES", "5")
        obs = ServingObs(enabled=False)
        assert obs.profile.status()["remaining_dispatches"] == 0
        assert ServingObs(enabled=True).profile.status()[
            "remaining_dispatches"
        ] == 5


class TestRequestTraceUnit:
    def test_span_math_uses_caller_clock(self):
        tr = RequestTrace()
        tr.submit(7, 100.0, prompt_len=4, max_new=8)
        tr.admitted(7, 100.5, slot=1, blocks=2)
        tr.first_token(7, 101.25)
        tr.done(7, 103.0, "eos", 5)
        assert tr.ttft_s(7) == 1.25
        assert tr.wall_s(7) == 3.0
        tl = tr.timeline(7)
        assert tl["reason"] == "eos" and tl["slot"] == 1

    def test_done_retention_bounded(self):
        tr = RequestTrace(keep_done=2)
        for rid in range(5):
            tr.submit(rid, float(rid), 1, 1)
            tr.done(rid, float(rid) + 1, "budget", 1)
        assert tr.ttft_s(0) is None  # evicted
        assert tr.wall_s(4) == 1.0

    def test_disabled_records_nothing(self):
        tr = RequestTrace(enabled=False)
        tr.submit(1, 0.0, 1, 1)
        assert tr.timeline(1) is None
        assert tr.ring.snapshot() == []

    def test_chrome_trace_structure(self):
        tr = RequestTrace()
        tr.submit(3, 10.0, 4, 8)
        tr.admitted(3, 10.1, slot=0, blocks=1)
        tr.prefill_chunk(3, 10.15, 4, 4)
        tr.first_token(3, 10.2)
        tr.done(3, 10.9, "budget", 8)
        tr.error(11.0, "oversize_reject")
        ct = tr.chrome_trace()
        events = ct["traceEvents"]
        names = {e["name"] for e in events}
        assert {"queued", "prefill", "decode", "error"} <= names
        for e in events:
            assert e["ph"] in ("X", "i", "M")
            if e["ph"] == "X":
                assert isinstance(e["ts"], int)
                assert isinstance(e["dur"], int) and e["dur"] >= 0
        decode = next(e for e in events if e["name"] == "decode")
        assert decode["ts"] == 200_000  # 10.2 - 10.0 in us
        assert decode["dur"] == 700_000

    def test_empty_trace_exports(self):
        assert RequestTrace().chrome_trace()["traceEvents"] == []


@pytest.fixture(scope="module")
def tiny_engine_run():
    """One tiny paged engine driven to completion: shared by the
    span-parity, registry-derivation, and exposition checks (the jit
    compile is the expensive part)."""
    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
        max_seq_len=64,
    )
    params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    engine = ContinuousBatcher(
        cfg, params, slots=2, cache_len=64, prompt_bucket=16,
        chunk_steps=2,
    )
    rng = np.random.default_rng(0)
    rids = []
    for n, max_new in ((3, 5), (6, 3), (4, 4)):
        prompt = rng.integers(0, cfg.vocab_size, n).astype(np.int32)
        rids.append(engine.submit(prompt, max_new_tokens=max_new))
    while engine.has_work:
        engine.step()
    records = engine.drain_done_records()
    return engine, rids, records


class TestEngineObsIntegration:
    def test_span_timeline_parity_is_exact(self, tiny_engine_run):
        """ttft_s/wall_s reconstructed from lifecycle spans equal
        drain_done_records EXACTLY (same clock reads, not a second
        measurement)."""
        engine, rids, records = tiny_engine_run
        assert set(records) == set(rids)
        for rid, rec in records.items():
            assert engine.obs.trace.ttft_s(rid) == rec["ttft_s"]
            assert engine.obs.trace.wall_s(rid) == rec["wall_s"]
            tl = engine.obs.trace.timeline(rid)
            assert tl["n_tokens"] == len(rec["tokens"])
            assert tl["reason"] == "budget"  # no eos_id set

    def test_histograms_agree_with_records_within_one_bucket(
        self, tiny_engine_run
    ):
        engine, _, records = tiny_engine_run
        obs = engine.obs
        assert obs.ttft.count() == len(records)
        assert obs.wall.count() == len(records)
        max_ttft = max(r["ttft_s"] for r in records.values())
        bound = next(
            b for b in obs.ttft.bounds if b >= max_ttft
        )
        assert obs.ttft.quantile(1.0) == bound

    def test_occupancy_and_kv_stats_are_registry_views(
        self, tiny_engine_run
    ):
        engine, _, records = tiny_engine_run
        obs = engine.obs
        occ = engine.occupancy()
        assert occ["busy_slot_steps"] == int(obs.busy_steps.value())
        assert occ["total_slot_steps"] == int(obs.total_steps.value())
        assert occ["total_slot_steps"] == (
            int(obs.dispatches.value()) * engine.slots
            * engine.chunk_steps
        )
        kv = engine.kv_stats()
        assert kv["kv_bytes_dispatch_acc"] == obs.kv_bytes.value()
        assert kv["kv_resident_dispatch_acc"] == int(
            obs.kv_resident.value()
        )
        assert kv["admission_stall_s"] == round(obs.stall.value(), 6)
        assert kv["kv_hbm_bytes_per_resident_token"] == (
            obs.kv_ratio.value()
        )
        assert engine.admission_stall_s == obs.stall.value()

    def test_counters_and_gauges_after_drain(self, tiny_engine_run):
        engine, rids, records = tiny_engine_run
        obs = engine.obs
        assert obs.submitted.value() == len(rids)
        assert obs.completed.value({"reason": "budget"}) == len(rids)
        total_tokens = sum(len(r["tokens"]) for r in records.values())
        assert obs.tokens.value() == total_tokens
        assert obs.queue_depth.value() == 0
        assert engine.queue_depth == 0
        assert obs.dispatch_latency.count() == int(
            obs.dispatches.value()
        )
        # Paged pool drained back to fully free; watermark recorded.
        free = engine.pool_blocks - 1
        assert obs.pool_blocks.value({"state": "free"}) == free
        assert obs.pool_blocks.value({"state": "used"}) == 0
        assert obs.pool_min_free.value() < free
        assert engine.seconds_since_last_dispatch is not None

    def test_metrics_render_parses(self, tiny_engine_run):
        engine, _, _ = tiny_engine_run
        text = engine.obs.render()
        assert "# TYPE cb_ttft_seconds histogram" in text
        assert "cb_requests_submitted_total 3" in text
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            assert _SAMPLE.match(line), line

    def test_error_taxonomy_labels(self, tiny_engine_run):
        engine, _, _ = tiny_engine_run
        obs = engine.obs
        with pytest.raises(ValueError):
            engine.submit([1] * 70, max_new_tokens=5)  # > cache_len
        assert obs.errors.value({"reason": "oversize_reject"}) == 1
        with pytest.raises(ValueError):
            engine.submit([1, 2], max_new_tokens=5, temperature=-1.0)
        assert obs.errors.value({"reason": "bad_request"}) == 1

    def test_pool_overflow_label(self):
        """A request that fits the cache but not the pool is a
        distinct reject reason."""
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=256,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        engine = ContinuousBatcher(
            cfg, params, slots=1, cache_len=256, prompt_bucket=16,
            chunk_steps=2, pool_blocks=2,
        )
        with pytest.raises(ValueError, match="pool"):
            engine.submit([1] * 4, max_new_tokens=200)  # 2 blocks > 1
        assert engine.obs.errors.value(
            {"reason": "pool_overflow"}
        ) == 1

    def test_disabled_obs_keeps_api_shape(self):
        """obs=False (the bench's A/B arm): no recording, but the
        occupancy/kv_stats dict shapes survive."""
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=64,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        engine = ContinuousBatcher(
            cfg, params, slots=2, cache_len=64, prompt_bucket=16,
            chunk_steps=2, obs=False,
        )
        rid = engine.submit([1, 2, 3], max_new_tokens=4)
        out = engine.run()
        assert len(out[rid]) == 4
        occ = engine.occupancy()
        assert set(occ) == {
            "busy_slot_steps", "total_slot_steps", "occupancy",
            "obs_disabled",
        }
        assert occ["total_slot_steps"] == 0  # disabled records nothing
        # ...and the zeros are FLAGGED, not presented as measurements.
        assert occ["obs_disabled"] is True
        kv = engine.kv_stats()
        assert kv["obs_disabled"] is True
        assert kv["kv_hbm_bytes_per_resident_token"] is None
        assert engine.obs.trace.timeline(rid) is None


class TestHealthzPayload:
    def _demo_module(self):
        import importlib.util
        import pathlib
        import sys

        path = (
            pathlib.Path(__file__).resolve().parents[1]
            / "demos" / "tpu-sharing-comparison" / "app" / "main.py"
        )
        spec = importlib.util.spec_from_file_location(
            "walkai_demo_app", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules["walkai_demo_app"] = mod
        spec.loader.exec_module(mod)
        return mod

    def test_engine_block_fields(self):
        mod = self._demo_module()

        class Stub:
            queue_depth = 5
            seconds_since_last_dispatch = 0.1234
            has_work = True
            slots = 8

        payload = mod.engine_health(Stub(), True)
        assert payload == {
            "alive": True,
            "queue_depth": 5,
            "seconds_since_last_dispatch": 0.123,
            "has_work": True,
            "slots": 8,
        }

    def test_no_engine_and_never_dispatched(self):
        mod = self._demo_module()
        assert mod.engine_health(None, False) is None

        class Fresh:
            queue_depth = 0
            seconds_since_last_dispatch = None
            has_work = False
            slots = 2

        payload = mod.engine_health(Fresh(), True)
        assert payload["seconds_since_last_dispatch"] is None


class TestInstallExporterRegistry:
    def test_inventory_as_gauges(self):
        from walkai_nos_tpu.cmd.metricsexporter import (
            registry_from_metrics,
        )

        text = registry_from_metrics({
            "installation_uuid": "u-1",
            "components": {"tpuagent": True, "scheduler": False},
            "nodes": [{
                "name": "n1",
                "capacity": {
                    "google.com/tpu": "8",
                    "memory": "16Gi",
                    "bogus": "not-a-quantity",
                },
            }],
        }).render()
        assert 'nos_install_info{installation_uuid="u-1"} 1' in text
        assert (
            'nos_install_component_enabled{component="tpuagent"} 1'
            in text
        )
        assert (
            'nos_install_component_enabled{component="scheduler"} 0'
            in text
        )
        assert (
            'nos_install_node_capacity{node="n1",'
            'resource="google.com/tpu"} 8' in text
        )
        assert "nos_install_nodes 1" in text
        assert "bogus" not in text  # unparseable quantity skipped

    def test_health_metrics_is_the_same_registry(self):
        """The kube binaries' Metrics IS the obs Registry (one
        implementation, adapter API on top)."""
        from walkai_nos_tpu.health import Metrics

        m = Metrics()
        assert isinstance(m, Registry)
        m.counter_add("nos_reconcile_total", 1,
                      {"controller": "c", "result": "ok"},
                      help_text="Reconciliations")
        out = m.render()
        assert "# TYPE nos_reconcile_total counter" in out
        assert (
            'nos_reconcile_total{controller="c",result="ok"} 1' in out
        )


class TestServingObsBundle:
    def test_catalog_attrs_built(self):
        from walkai_nos_tpu.obs.catalog import serving_specs

        obs = ServingObs()
        for spec in serving_specs():
            inst = getattr(obs, spec.attr)
            assert inst.name == spec.name
            assert inst.kind == spec.kind

    def test_overhead_key_is_headline(self):
        """The gated key must survive driver-side tail truncation:
        it has to be in bench.py's headline tuple (the measured A/B
        itself runs in tests/test_bench_serving.py — compile-heavy)."""
        import inspect

        import bench

        assert "obs_overhead_pct" in inspect.getsource(bench.main)

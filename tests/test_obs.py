"""Telemetry subsystem (`walkai_nos_tpu/obs/`): registry semantics,
histogram bucket boundaries, ring-buffer wraparound, Prometheus
exposition format, profile-hook gating — and the contract that makes
the trace trustworthy: per-request ttft/wall reconstructed from
lifecycle spans equal `drain_done_records()` EXACTLY, and the
engine's `occupancy()`/`kv_stats()` dicts are views of the same
registry `/metrics` exports."""

import re

import numpy as np
import pytest

from walkai_nos_tpu.obs.attrib import (
    DISPATCH_KINDS,
    DispatchAttribution,
    classify_dispatch,
)
from walkai_nos_tpu.obs.metrics import (
    Registry,
    log_buckets,
)
from walkai_nos_tpu.obs.profile import ProfileHook
from walkai_nos_tpu.obs.serving import ServingObs
from walkai_nos_tpu.obs.slo import BucketRing, SloTracker
from walkai_nos_tpu.obs.trace import RequestTrace, Ring


class TestLogBuckets:
    def test_geometric_and_covering(self):
        b = log_buckets(1e-3, 100.0, per_decade=3)
        assert b[0] == 1e-3
        assert b[-1] >= 100.0
        assert list(b) == sorted(b)
        # Constant ratio ~10^(1/3): every adjacent pair within 10%
        # of it (bounds snap to 4 significant digits).
        ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
        for r in ratios:
            assert abs(r - 10 ** (1 / 3)) / 10 ** (1 / 3) < 0.1

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            log_buckets(0, 1.0)
        with pytest.raises(ValueError):
            log_buckets(1.0, 0.5)
        with pytest.raises(ValueError):
            log_buckets(1.0, 2.0, per_decade=0)


class TestHistogram:
    def _hist(self, bounds=(1.0, 2.0, 4.0, 8.0)):
        reg = Registry()
        return reg, reg.histogram("h_seconds", "t", buckets=bounds)

    def test_bucket_boundaries_le_inclusive(self):
        """Prometheus `le` semantics: a sample exactly ON a bound
        lands in that bucket, just above goes to the next."""
        reg, h = self._hist()
        h.observe(2.0)   # == bound 2 -> bucket le=2
        h.observe(2.001)  # -> bucket le=4
        h.observe(0.0)   # below first bound -> bucket le=1
        text = reg.render()
        assert 'h_seconds_bucket{le="1"} 1' in text
        assert 'h_seconds_bucket{le="2"} 2' in text  # cumulative
        assert 'h_seconds_bucket{le="4"} 3' in text
        assert 'h_seconds_bucket{le="8"} 3' in text
        assert 'h_seconds_bucket{le="+Inf"} 3' in text

    def test_overflow_counts_only_inf(self):
        reg, h = self._hist()
        h.observe(9.5)
        assert h.count() == 1
        assert h.sum() == 9.5
        text = reg.render()
        assert 'h_seconds_bucket{le="8"} 0' in text
        assert 'h_seconds_bucket{le="+Inf"} 1' in text
        assert "h_seconds_count 1" in text

    def test_quantile_within_one_bucket(self):
        _, h = self._hist()
        for v in (0.5, 1.5, 3.0, 7.0):
            h.observe(v)
        assert h.quantile(0.0) == 1.0
        assert h.quantile(0.5) == 2.0   # rank 2 of 4 -> le=2 bucket
        assert h.quantile(1.0) == 8.0
        # Every estimate is the upper bound of the sample's bucket:
        # exact to within one bucket width.
        assert h.quantile(0.25) == 1.0

    def test_quantile_overflow_clamps_to_last_bound(self):
        _, h = self._hist()
        h.observe(100.0)
        assert h.quantile(0.99) == 8.0

    def test_quantile_empty_and_invalid(self):
        _, h = self._hist()
        assert h.quantile(0.5) is None
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_unsorted_buckets_rejected(self):
        reg = Registry()
        with pytest.raises(ValueError):
            reg.histogram("bad_seconds", "t", buckets=(2.0, 1.0))


class TestRing:
    def test_wraparound_keeps_newest_in_order(self):
        r = Ring(4)
        for i in range(10):
            r.append(i)
        assert r.snapshot() == [6, 7, 8, 9]
        assert r.dropped == 6
        assert len(r) == 4

    def test_underfill_in_order(self):
        r = Ring(8)
        for i in range(3):
            r.append(i)
        assert r.snapshot() == [0, 1, 2]
        assert r.dropped == 0
        assert len(r) == 3

    def test_exact_capacity_boundary(self):
        r = Ring(3)
        for i in range(3):
            r.append(i)
        assert r.snapshot() == [0, 1, 2] and r.dropped == 0
        r.append(3)
        assert r.snapshot() == [1, 2, 3] and r.dropped == 1

    def test_zero_capacity_rejected(self):
        with pytest.raises(ValueError):
            Ring(0)


# One Prometheus text-format sample line (after HELP/TYPE comments).
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_+][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" -?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?$"
)


class TestExposition:
    def test_every_line_is_valid_prometheus_text(self):
        reg = Registry()
        reg.counter("a_total", "help a").inc(2, {"x": "1"})
        reg.gauge("b", "help b").set(1.5)
        h = reg.histogram("c_seconds", "help c", buckets=(0.1, 1.0))
        h.observe(0.05, {"op": "q"})
        h.observe(50.0, {"op": "q"})
        text = reg.render()
        assert text.endswith("\n")
        for line in text.strip().splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                continue
            assert _SAMPLE.match(line), line

    def test_help_and_type_lines(self):
        reg = Registry()
        reg.counter("x_total", "counts xs").inc()
        text = reg.render()
        assert "# HELP x_total counts xs" in text
        assert "# TYPE x_total counter" in text

    def test_histogram_contract(self):
        """Cumulative buckets, +Inf == _count, _sum present."""
        reg = Registry()
        h = reg.histogram("d_seconds", "t", buckets=(1.0, 2.0))
        for v in (0.5, 0.6, 1.5, 9.0):
            h.observe(v)
        text = reg.render()
        assert 'd_seconds_bucket{le="1"} 2' in text
        assert 'd_seconds_bucket{le="2"} 3' in text
        assert 'd_seconds_bucket{le="+Inf"} 4' in text
        assert "d_seconds_count 4" in text
        assert "d_seconds_sum 11.6" in text

    def test_label_escaping(self):
        reg = Registry()
        reg.counter("e_total", "t").inc(1, {"r": 'bad "q"\nline'})
        out = reg.render()
        assert 'r="bad \\"q\\"\\nline"' in out

    def test_unobserved_metrics_omitted(self):
        reg = Registry()
        reg.counter("never_total", "t")
        assert "never_total" not in reg.render()

    def test_nonfinite_values_render_not_crash(self):
        """One inf/NaN gauge (a ratio whose denominator hit zero)
        must not take down the whole exposition — the format has
        spellings for them."""
        reg = Registry()
        reg.gauge("ratio", "t").set(float("inf"))
        reg.gauge("neg", "t").set(float("-inf"))
        reg.gauge("nan", "t").set(float("nan"))
        reg.gauge("ok", "t").set(1.0)
        text = reg.render()
        assert "ratio +Inf" in text
        assert "neg -Inf" in text
        assert "nan NaN" in text
        assert "ok 1" in text


class TestRegistry:
    def test_get_or_create_and_kind_conflict(self):
        reg = Registry()
        c1 = reg.counter("x_total", "first help")
        c2 = reg.counter("x_total", "different help")
        assert c1 is c2
        assert c1.help == "first help"
        with pytest.raises(ValueError):
            reg.gauge("x_total", "now a gauge?")

    def test_concurrent_registration_single_winner(self):
        """Racing threads registering the same name must converge on
        ONE instrument (creation happens under the lock) — a loser
        must never silently receive a wrong-kind instance."""
        import threading

        reg = Registry()
        out = []

        def register():
            out.append(reg.counter("race_total", "t"))

        threads = [
            threading.Thread(target=register) for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(m is out[0] for m in out)

    def test_disabled_registry_noops(self):
        reg = Registry(enabled=False)
        c = reg.counter("x_total", "t")
        c.inc(5)
        g = reg.gauge("g", "t")
        g.set(2)
        h = reg.histogram("h_seconds", "t", buckets=(1.0,))
        h.observe(0.5)
        assert c.value() == 0.0
        assert g.value() is None
        assert h.count() == 0

    def test_counter_rejects_decrease(self):
        with pytest.raises(ValueError):
            Registry().counter("x_total", "t").inc(-1)

    def test_gauge_set_min_is_low_watermark(self):
        g = Registry().gauge("w", "t")
        g.set_min(5)
        g.set_min(3)
        g.set_min(9)
        assert g.value() == 3

    def test_gauge_remove_drops_one_series(self):
        """remove() drops a label set from exposition entirely — the
        retired-fleet-member shape, where the last value would export
        a dead member as live and 0 would read as 'observed idle'."""
        reg = Registry()
        g = reg.gauge("members", "t")
        g.set(0.7, labels={"replica": "a"})
        g.set(0.2, labels={"replica": "b"})
        g.remove(labels={"replica": "a"})
        assert g.value(labels={"replica": "a"}) is None
        assert g.value(labels={"replica": "b"}) == 0.2
        assert 'members{replica="a"}' not in reg.render()
        assert 'members{replica="b"} 0.2' in reg.render()
        g.remove(labels={"replica": "a"})  # absent: no-op


class TestProfileHook:
    def _patched(self, monkeypatch):
        import jax

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda logdir: calls.append(("start", logdir)),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace", lambda: calls.append(("stop",))
        )
        return calls

    def test_window_covers_exactly_n_dispatches(self, monkeypatch):
        calls = self._patched(monkeypatch)
        hook = ProfileHook()
        hook.arm(3, "/tmp/prof")
        for _ in range(5):
            hook.on_dispatch()
        assert calls == [("start", "/tmp/prof"), ("stop",)]
        s = hook.status()
        assert s["completed_windows"] == 1
        assert s["active"] is False
        assert s["remaining_dispatches"] == 0

    def test_unarmed_is_noop(self, monkeypatch):
        calls = self._patched(monkeypatch)
        hook = ProfileHook()
        for _ in range(10):
            hook.on_dispatch()
        assert calls == []

    def test_start_failure_disarms(self, monkeypatch):
        import jax

        def boom(logdir):
            raise RuntimeError("no profiler here")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        hook = ProfileHook()
        hook.arm(2, "/tmp/prof")
        hook.on_dispatch()
        hook.on_dispatch()  # must not retry or raise
        s = hook.status()
        assert s["active"] is False
        assert "no profiler here" in s["last_error"]

    def test_arm_validation(self):
        hook = ProfileHook()
        with pytest.raises(ValueError):
            hook.arm(0, "/tmp/p")
        with pytest.raises(ValueError):
            hook.arm(3, "")

    def test_from_env(self):
        hook = ProfileHook.from_env(
            {"WALKAI_PROFILE_DIR": "/tmp/x",
             "WALKAI_PROFILE_DISPATCHES": "7"}
        )
        assert hook.status()["remaining_dispatches"] == 7
        assert ProfileHook.from_env({}).status()[
            "remaining_dispatches"
        ] == 0

    def test_disabled_bundle_never_arms_from_env(self, monkeypatch):
        """WALKAI_OBS=0 + WALKAI_PROFILE_DIR set: the no-op bundle
        must be a real no-op — no capture window on a
        telemetry-disabled engine (and no bias in the overhead A/B's
        disabled arm)."""
        monkeypatch.setenv("WALKAI_PROFILE_DIR", "/tmp/prof")
        monkeypatch.setenv("WALKAI_PROFILE_DISPATCHES", "5")
        obs = ServingObs(enabled=False)
        assert obs.profile.status()["remaining_dispatches"] == 0
        assert ServingObs(enabled=True).profile.status()[
            "remaining_dispatches"
        ] == 5


class TestRequestTraceUnit:
    def test_span_math_uses_caller_clock(self):
        tr = RequestTrace()
        tr.submit(7, 100.0, prompt_len=4, max_new=8)
        tr.admitted(7, 100.5, slot=1, blocks=2)
        tr.first_token(7, 101.25)
        tr.done(7, 103.0, "eos", 5)
        assert tr.ttft_s(7) == 1.25
        assert tr.wall_s(7) == 3.0
        tl = tr.timeline(7)
        assert tl["reason"] == "eos" and tl["slot"] == 1

    def test_done_retention_bounded(self):
        tr = RequestTrace(keep_done=2)
        for rid in range(5):
            tr.submit(rid, float(rid), 1, 1)
            tr.done(rid, float(rid) + 1, "budget", 1)
        assert tr.ttft_s(0) is None  # evicted
        assert tr.wall_s(4) == 1.0

    def test_disabled_records_nothing(self):
        tr = RequestTrace(enabled=False)
        tr.submit(1, 0.0, 1, 1)
        assert tr.timeline(1) is None
        assert tr.ring.snapshot() == []

    def test_chrome_trace_structure(self):
        tr = RequestTrace()
        tr.submit(3, 10.0, 4, 8)
        tr.admitted(3, 10.1, slot=0, blocks=1)
        tr.prefill_chunk(3, 10.15, 4, 4)
        tr.first_token(3, 10.2)
        tr.done(3, 10.9, "budget", 8)
        tr.error(11.0, "oversize_reject")
        ct = tr.chrome_trace()
        events = ct["traceEvents"]
        names = {e["name"] for e in events}
        assert {"queued", "prefill", "decode", "error"} <= names
        for e in events:
            assert e["ph"] in ("X", "i", "M")
            if e["ph"] == "X":
                assert isinstance(e["ts"], int)
                assert isinstance(e["dur"], int) and e["dur"] >= 0
        decode = next(e for e in events if e["name"] == "decode")
        assert decode["ts"] == 200_000  # 10.2 - 10.0 in us
        assert decode["dur"] == 700_000

    def test_empty_trace_exports(self):
        assert RequestTrace().chrome_trace()["traceEvents"] == []


@pytest.fixture(scope="module")
def tiny_engine_run():
    """One tiny paged engine driven to completion: shared by the
    span-parity, registry-derivation, and exposition checks (the jit
    compile is the expensive part)."""
    import jax

    from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
    from walkai_nos_tpu.models.serve import ContinuousBatcher

    cfg = LMConfig(
        vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
        max_seq_len=64,
    )
    params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
    engine = ContinuousBatcher(
        cfg, params, slots=2, cache_len=64, prompt_bucket=16,
        chunk_steps=2,
    )
    rng = np.random.default_rng(0)
    rids = []
    for n, max_new in ((3, 5), (6, 3), (4, 4)):
        prompt = rng.integers(0, cfg.vocab_size, n).astype(np.int32)
        rids.append(engine.submit(prompt, max_new_tokens=max_new))
    while engine.has_work:
        engine.step()
    records = engine.drain_done_records()
    return engine, rids, records


class TestEngineObsIntegration:
    def test_span_timeline_parity_is_exact(self, tiny_engine_run):
        """ttft_s/wall_s reconstructed from lifecycle spans equal
        drain_done_records EXACTLY (same clock reads, not a second
        measurement)."""
        engine, rids, records = tiny_engine_run
        assert set(records) == set(rids)
        for rid, rec in records.items():
            assert engine.obs.trace.ttft_s(rid) == rec["ttft_s"]
            assert engine.obs.trace.wall_s(rid) == rec["wall_s"]
            tl = engine.obs.trace.timeline(rid)
            assert tl["n_tokens"] == len(rec["tokens"])
            assert tl["reason"] == "budget"  # no eos_id set

    def test_histograms_agree_with_records_within_one_bucket(
        self, tiny_engine_run
    ):
        engine, _, records = tiny_engine_run
        obs = engine.obs
        assert obs.ttft.count() == len(records)
        assert obs.wall.count() == len(records)
        max_ttft = max(r["ttft_s"] for r in records.values())
        bound = next(
            b for b in obs.ttft.bounds if b >= max_ttft
        )
        assert obs.ttft.quantile(1.0) == bound

    def test_occupancy_and_kv_stats_are_registry_views(
        self, tiny_engine_run
    ):
        engine, _, records = tiny_engine_run
        obs = engine.obs
        occ = engine.occupancy()
        assert occ["busy_slot_steps"] == int(obs.busy_steps.value())
        assert occ["total_slot_steps"] == int(obs.total_steps.value())
        assert occ["total_slot_steps"] == (
            int(obs.dispatches.value()) * engine.slots
            * engine.chunk_steps
        )
        kv = engine.kv_stats()
        assert kv["kv_bytes_dispatch_acc"] == obs.kv_bytes.value()
        assert kv["kv_resident_dispatch_acc"] == int(
            obs.kv_resident.value()
        )
        assert kv["admission_stall_s"] == round(obs.stall.value(), 6)
        assert kv["kv_hbm_bytes_per_resident_token"] == (
            obs.kv_ratio.value()
        )
        assert engine.admission_stall_s == obs.stall.value()

    def test_counters_and_gauges_after_drain(self, tiny_engine_run):
        engine, rids, records = tiny_engine_run
        obs = engine.obs
        assert obs.submitted.value() == len(rids)
        assert obs.completed.value({"reason": "budget"}) == len(rids)
        total_tokens = sum(len(r["tokens"]) for r in records.values())
        assert obs.tokens.value() == total_tokens
        assert obs.queue_depth.value() == 0
        assert engine.queue_depth == 0
        assert obs.dispatch_latency.count() == int(
            obs.dispatches.value()
        )
        # Paged pool drained back to fully free; watermark recorded.
        free = engine.pool_blocks - 1
        assert obs.pool_blocks.value({"state": "free"}) == free
        assert obs.pool_blocks.value({"state": "used"}) == 0
        assert obs.pool_min_free.value() < free
        assert engine.seconds_since_last_dispatch is not None

    def test_metrics_render_parses(self, tiny_engine_run):
        engine, _, _ = tiny_engine_run
        text = engine.obs.render()
        assert "# TYPE cb_ttft_seconds histogram" in text
        assert "cb_requests_submitted_total 3" in text
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            assert _SAMPLE.match(line), line

    def test_error_taxonomy_labels(self, tiny_engine_run):
        engine, _, _ = tiny_engine_run
        obs = engine.obs
        with pytest.raises(ValueError):
            engine.submit([1] * 70, max_new_tokens=5)  # > cache_len
        assert obs.errors.value({"reason": "oversize_reject"}) == 1
        with pytest.raises(ValueError):
            engine.submit([1, 2], max_new_tokens=5, temperature=-1.0)
        assert obs.errors.value({"reason": "bad_request"}) == 1

    def test_pool_overflow_label(self):
        """A request that fits the cache but not the pool is a
        distinct reject reason."""
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=256,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        engine = ContinuousBatcher(
            cfg, params, slots=1, cache_len=256, prompt_bucket=16,
            chunk_steps=2, pool_blocks=2,
        )
        with pytest.raises(ValueError, match="pool"):
            engine.submit([1] * 4, max_new_tokens=200)  # 2 blocks > 1
        assert engine.obs.errors.value(
            {"reason": "pool_overflow"}
        ) == 1

    def test_disabled_obs_keeps_api_shape(self):
        """obs=False (the bench's A/B arm): no recording, but the
        occupancy/kv_stats dict shapes survive."""
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=64,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        engine = ContinuousBatcher(
            cfg, params, slots=2, cache_len=64, prompt_bucket=16,
            chunk_steps=2, obs=False,
        )
        rid = engine.submit([1, 2, 3], max_new_tokens=4)
        out = engine.run()
        assert len(out[rid]) == 4
        occ = engine.occupancy()
        assert set(occ) == {
            "busy_slot_steps", "total_slot_steps", "occupancy",
            "obs_disabled",
        }
        assert occ["total_slot_steps"] == 0  # disabled records nothing
        # ...and the zeros are FLAGGED, not presented as measurements.
        assert occ["obs_disabled"] is True
        kv = engine.kv_stats()
        assert kv["obs_disabled"] is True
        assert kv["kv_hbm_bytes_per_resident_token"] is None
        assert engine.obs.trace.timeline(rid) is None
        # The new attribution/SLO views keep the SAME dict shapes with
        # telemetry off, flagged obs_disabled (the /stats convention):
        # zeros read as "not recorded", not "measured zero".
        slo = engine.slo_stats()
        assert slo["obs_disabled"] is True
        assert set(slo["windows"]) == {"ttft", "tpot", "dispatch"}
        assert slo["windows"]["ttft"] == {
            "count": 0, "p50": None, "p99": None, "span_s": 0.0,
        }
        assert slo["saturation"]["value"] is None
        at = engine.attrib_stats()
        assert at["obs_disabled"] is True
        assert at["device_step_ms"] is None
        assert all(
            k["dispatches"] == 0 for k in at["kinds"].values()
        )
        assert engine.saturation is None
        assert engine.slo_ok is None
        # And the fenced snapshot still assembles (pool counts sum).
        state = engine.debug_state()
        pool = state["pool"]
        assert (
            pool["free"] + pool["parked"] + pool["in_use"]
            == pool["blocks_total"] - 1
        )


class TestHealthzPayload:
    def _demo_module(self):
        import importlib.util
        import pathlib
        import sys

        path = (
            pathlib.Path(__file__).resolve().parents[1]
            / "demos" / "tpu-sharing-comparison" / "app" / "main.py"
        )
        spec = importlib.util.spec_from_file_location(
            "walkai_demo_app", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules["walkai_demo_app"] = mod
        spec.loader.exec_module(mod)
        return mod

    def test_engine_block_fields(self):
        mod = self._demo_module()

        class Stub:
            queue_depth = 5
            seconds_since_last_dispatch = 0.1234
            has_work = True
            slots = 8
            saturation = 0.62518
            slo_ok = True

        payload = mod.engine_health(Stub(), True)
        assert payload == {
            "alive": True,
            "queue_depth": 5,
            "seconds_since_last_dispatch": 0.123,
            "has_work": True,
            # Drain lifecycle bit (Stub predates drain(): getattr
            # default False keeps old engines readable).
            "draining": False,
            "slots": 8,
            # Scale signals for kube probes/autoscalers: the composed
            # saturation and windowed SLO compliance ride /healthz so
            # consumers need not scrape Prometheus text.
            "saturation": 0.6252,
            "slo_ok": True,
        }

    def test_no_engine_and_never_dispatched(self):
        mod = self._demo_module()
        assert mod.engine_health(None, False) is None

        class Fresh:
            queue_depth = 0
            seconds_since_last_dispatch = None
            has_work = False
            slots = 2
            saturation = None
            slo_ok = None

        payload = mod.engine_health(Fresh(), True)
        assert payload["seconds_since_last_dispatch"] is None
        # Before the first dispatch (or with telemetry off) the scale
        # signals are None — "not measured", never a fake healthy 0.
        assert payload["saturation"] is None
        assert payload["slo_ok"] is None


class TestInstallExporterRegistry:
    def test_inventory_as_gauges(self):
        from walkai_nos_tpu.cmd.metricsexporter import (
            registry_from_metrics,
        )

        text = registry_from_metrics({
            "installation_uuid": "u-1",
            "components": {"tpuagent": True, "scheduler": False},
            "nodes": [{
                "name": "n1",
                "capacity": {
                    "google.com/tpu": "8",
                    "memory": "16Gi",
                    "bogus": "not-a-quantity",
                },
            }],
        }).render()
        assert 'nos_install_info{installation_uuid="u-1"} 1' in text
        assert (
            'nos_install_component_enabled{component="tpuagent"} 1'
            in text
        )
        assert (
            'nos_install_component_enabled{component="scheduler"} 0'
            in text
        )
        assert (
            'nos_install_node_capacity{node="n1",'
            'resource="google.com/tpu"} 8' in text
        )
        assert "nos_install_nodes 1" in text
        assert "bogus" not in text  # unparseable quantity skipped

    def test_health_metrics_is_the_same_registry(self):
        """The kube binaries' Metrics IS the obs Registry (one
        implementation, adapter API on top)."""
        from walkai_nos_tpu.health import Metrics

        m = Metrics()
        assert isinstance(m, Registry)
        m.counter_add("nos_reconcile_total", 1,
                      {"controller": "c", "result": "ok"},
                      help_text="Reconciliations")
        out = m.render()
        assert "# TYPE nos_reconcile_total counter" in out
        assert (
            'nos_reconcile_total{controller="c",result="ok"} 1' in out
        )


class TestServingObsBundle:
    def test_catalog_attrs_built(self):
        from walkai_nos_tpu.obs.catalog import serving_specs

        obs = ServingObs()
        for spec in serving_specs():
            inst = getattr(obs, spec.attr)
            assert inst.name == spec.name
            assert inst.kind == spec.kind

    def test_overhead_key_is_headline(self):
        """The gated key must survive driver-side tail truncation:
        it has to be in bench.py's headline tuple (the measured A/B
        itself runs in tests/test_bench_serving.py — compile-heavy)."""
        import inspect

        import bench

        assert "obs_overhead_pct" in inspect.getsource(bench.main)

    def test_attribution_and_slo_keys_are_headline(self):
        """The attribution/SLO PR's gated and acceptance keys must
        survive driver-side tail truncation too."""
        import inspect

        import bench

        src = inspect.getsource(bench.main)
        for key in (
            "cb_device_step_ms", "cb_host_overhead_frac",
            "cb_device_roofline_fraction", "cb_slo_ttft_p99",
            "cb_saturation",
        ):
            assert key in src, key


class TestBucketRing:
    """Ring-of-buckets windowed views (obs/slo.py) over a cumulative
    histogram: rotation, expiry of old buckets, partial-window reads,
    the empty-window sentinel, and the windowed-vs-cumulative p99
    divergence after a latency regime change — the property the whole
    layer exists for."""

    def _ring(self, window_s=10.0, buckets=5):
        reg = Registry()
        h = reg.histogram(
            "w_seconds", "t", buckets=(1.0, 2.0, 4.0, 8.0)
        )
        return h, BucketRing(h, window_s=window_s, buckets=buckets)

    def test_partial_window_reads_everything_since_start(self):
        h, ring = self._ring()
        ring.advance(0.0)
        h.observe(0.5)
        h.observe(1.5)
        # No snapshot is a full window old yet: the read covers the
        # partial span since start, baseline zero.
        delta, total, span = ring.window_counts(3.0)
        assert total == 2
        assert span == 3.0
        assert ring.quantile(1.0, 3.0) == 2.0

    def test_empty_window_is_none_not_zero(self):
        h, ring = self._ring()
        ring.advance(0.0)
        assert ring.quantile(0.99, 0.0) is None
        assert ring.frac_over(1.0, 0.0) is None
        h.observe(0.5)
        # ...and once the sample ages out of the window, None again.
        for t in range(2, 26, 2):
            ring.advance(float(t))
        assert ring.quantile(0.99, 24.0) is None

    def test_rotation_and_expiry(self):
        h, ring = self._ring(window_s=10.0, buckets=5)  # bucket_s = 2
        ring.advance(0.0)
        h.observe(0.5)
        h.observe(0.5)
        ring.advance(2.0)   # snapshot captures the 2 old samples
        h.observe(8.0)      # regime change
        for t in (4.0, 6.0, 8.0, 10.0, 12.0):
            ring.advance(t)
        # At t=12 the t=2 snapshot is exactly window-old: it is the
        # baseline, so the window holds ONLY the post-change sample.
        delta, total, span = ring.window_counts(12.0)
        assert total == 1
        assert span == 10.0
        assert ring.quantile(0.99, 12.0) == 8.0
        # Ring stays bounded: snapshots older than the baseline are
        # expired, so a long run holds ~window_s/bucket_s entries.
        assert len(ring._snaps) <= 5 + 2

    def test_window_p99_diverges_from_cumulative_after_regime_change(
        self,
    ):
        h, ring = self._ring(window_s=10.0, buckets=5)
        ring.advance(0.0)
        for _ in range(1000):
            h.observe(0.5)  # a thousand fast samples, old regime
        for t in (2.0, 4.0, 6.0, 8.0, 10.0, 12.0):
            ring.advance(t)
        for _ in range(5):
            h.observe(7.0)  # slow regime begins after the window
        ring.advance(14.0)
        # Cumulative p99: rank 995 of 1005 still lands in the fast
        # bucket — the lifetime histogram cannot see the regression.
        assert h.quantile(0.99) == 1.0
        # Windowed p99: only the 5 slow samples are in the window.
        assert ring.quantile(0.99, 14.0) == 8.0

    def test_idle_window_ages_out_without_advance(self):
        """Reads are wall-clock probes, rotation happens only on
        dispatch: an engine idle past the window must read EMPTY at
        probe time — the baseline is the NEWEST snapshot at or
        before the cutoff, so a stale burst is never replayed as the
        'current' window (a probe frozen on a 5-minute-old breach
        would keep a replica unready forever)."""
        h, ring = self._ring(window_s=10.0, buckets=5)
        ring.advance(0.0)
        h.observe(9.0)
        h.observe(9.0)
        ring.advance(2.0)  # snapshot captures the burst
        # Shortly after: the burst is (correctly) in the window.
        assert ring.quantile(0.99, 3.0) is not None
        # Minutes later, with NO dispatches to rotate the ring:
        delta, total, span = ring.window_counts(300.0)
        assert total == 0
        assert ring.quantile(0.99, 300.0) is None
        assert ring.frac_over(1.0, 300.0) is None

    def test_overflow_clamps_and_frac_over(self):
        h, ring = self._ring()
        ring.advance(0.0)
        h.observe(100.0)  # +Inf overflow
        h.observe(0.5)
        assert ring.quantile(1.0, 1.0) == 8.0  # clamp to last bound
        assert ring.frac_over(1.0, 1.0) == pytest.approx(0.5)
        assert ring.frac_over(200.0, 1.0) == pytest.approx(0.5)

    def test_invalid_args(self):
        h, ring = self._ring()
        with pytest.raises(ValueError):
            BucketRing(h, window_s=0, buckets=5)
        with pytest.raises(ValueError):
            BucketRing(h, window_s=1.0, buckets=0)
        with pytest.raises(ValueError):
            ring.quantile(1.5, 0.0)


class TestSloTracker:
    def _tracker(self, **kw):
        obs = ServingObs()
        kw.setdefault("slots", 4)
        kw.setdefault("window_s", 10.0)
        kw.setdefault("refresh_s", 1.0)
        return obs, SloTracker(obs, **kw)

    def test_unknown_objective_rejected(self):
        obs = ServingObs()
        with pytest.raises(ValueError, match="unknown SLO objective"):
            SloTracker(obs, slots=2, objectives={"nope_p99": 1.0})

    def test_windowed_gauges_and_compliance(self):
        obs, slo = self._tracker(
            objectives={"ttft_p99_s": 1.0}
        )
        for v in (0.1, 0.2, 0.3):
            obs.ttft.observe(v)
        slo.on_sync(
            0.0, queue_depth=0, busy_slots=2, headroom_frac=0.75
        )
        assert obs.slo_ttft_p99.value() is not None
        assert slo.ok is True
        assert slo.stats(0.0)["slo_ok"]["ttft_p99_s"] is True
        assert obs.slo_ok_gauge.value(
            {"objective": "ttft_p99_s"}
        ) == 1.0
        # Regime change: every new sample breaches the objective.
        for _ in range(20):
            obs.ttft.observe(5.0)
        slo.on_sync(
            2.0, queue_depth=0, busy_slots=2, headroom_frac=0.75
        )
        assert slo.ok is False
        burn = obs.slo_burn.value({"objective": "ttft_p99_s"})
        assert burn is not None and burn > 1.0
        # Windowed quantile view reflects the breach live.
        assert slo.stats(2.0)["windows"]["ttft"]["p99"] >= 5.0

    def test_refresh_throttled_but_rings_advance(self):
        obs, slo = self._tracker(refresh_s=5.0)
        slo.on_sync(0.0, queue_depth=0, busy_slots=0,
                    headroom_frac=1.0)
        sat0 = slo.saturation
        slo.on_sync(1.0, queue_depth=8, busy_slots=4,
                    headroom_frac=0.0)
        # Inside the refresh interval: gauges unchanged...
        assert slo.saturation == sat0
        slo.on_sync(6.0, queue_depth=8, busy_slots=4,
                    headroom_frac=0.0)
        # ...past it: the saturation refresh sees the pressure.
        assert slo.saturation == 1.0

    def test_saturation_components(self):
        obs, slo = self._tracker()  # slots=4
        slo.on_sync(0.0, queue_depth=0, busy_slots=1,
                    headroom_frac=0.9)
        comp = slo.stats(0.0)["saturation"]["components"]
        assert comp["busy"] == 0.25
        assert comp["queue"] == 0.0
        assert comp["pool"] == pytest.approx(0.1)
        assert slo.saturation == 0.25  # max of components
        # Queue growth over the window drives the trend component.
        slo.on_sync(2.0, queue_depth=6, busy_slots=4,
                    headroom_frac=0.5)
        comp = slo.stats(2.0)["saturation"]["components"]
        assert comp["busy"] == 1.0
        assert comp["queue"] == 0.75   # 6 / (2*4)
        assert comp["queue_trend"] == 1.0  # +6 over 4 slots, clamped
        assert slo.saturation == 1.0

    def test_dense_engine_has_no_pool_component(self):
        obs, slo = self._tracker()
        slo.on_sync(0.0, queue_depth=0, busy_slots=0,
                    headroom_frac=None)
        assert slo.stats(0.0)["saturation"]["components"][
            "pool"
        ] is None
        assert slo.saturation == 0.0

    def test_compliance_is_live_not_last_refresh(self):
        """A request burst can land entirely inside one refresh
        interval: the stats()/ok_at() compliance must be computed
        over the CURRENT window, not echo the (possibly empty)
        last-refresh snapshot — the /healthz probe sees breaches the
        throttled gauges haven't caught up to yet."""
        obs, slo = self._tracker(
            objectives={"ttft_p99_s": 1.0}, refresh_s=1.0
        )
        # First sync refreshes on an empty window: unknown.
        slo.on_sync(0.0, queue_depth=0, busy_slots=0,
                    headroom_frac=1.0)
        # Breaching burst, all within the refresh interval.
        for t in (0.1, 0.2, 0.3):
            obs.ttft.observe(9.0)
            slo.on_sync(t, queue_depth=0, busy_slots=1,
                        headroom_frac=1.0)
        st = slo.stats(0.3)
        assert st["slo_ok"]["ttft_p99_s"] is False
        assert st["burn_rate"]["ttft_p99_s"] > 1.0
        assert st["ok"] is False
        assert slo.ok_at(0.3) is False
        # ...and once the breach burst ages out of the window (no
        # dispatches needed), the probe clears: no fresh evidence of
        # breach, compliance unknown-therefore-ok again.
        assert slo.ok_at(300.0) is True
        assert slo.stats(300.0)["windows"]["ttft"]["count"] == 0
        # Before any sync at all, compliance is None (not measured).
        obs2, slo2 = self._tracker(objectives={"ttft_p99_s": 1.0})
        assert slo2.ok_at(0.0) is None

    def test_empty_window_compliance_is_unknown(self):
        obs, slo = self._tracker(objectives={"ttft_p99_s": 1.0})
        slo.on_sync(0.0, queue_depth=0, busy_slots=0,
                    headroom_frac=1.0)
        st = slo.stats(0.0)
        # No TTFT samples: compliance unknown (None), never a breach
        # — and overall ok stays True (no evidence against it).
        assert st["slo_ok"]["ttft_p99_s"] is None
        assert st["burn_rate"]["ttft_p99_s"] is None
        assert slo.ok is True


class TestClassifyDispatch:
    def test_all_compositions(self):
        assert classify_dispatch(3, 0, False) == "decode"
        assert classify_dispatch(0, 2, False) == "prefill"
        # The mixed case: prefill lane + live decode in ONE step
        # program dispatch.
        assert classify_dispatch(3, 2, False) == "mixed"
        assert classify_dispatch(3, 0, True) == "spec"
        # ...and prefill + decode + spec fused in one dispatch.
        assert classify_dispatch(3, 2, True) == "spec_prefill"
        assert classify_dispatch(0, 2, True) == "spec_prefill"

    def test_kinds_tuple_is_exhaustive(self):
        got = {
            classify_dispatch(b, l, s)
            for b in (0, 2) for l in (0, 1) for s in (False, True)
        }
        assert got <= set(DISPATCH_KINDS)


class TestDispatchAttribution:
    def test_window_gauges_and_cost_model(self):
        obs = ServingObs()
        attr = DispatchAttribution(
            obs, param_bytes=1000, kv_bytes_per_token=10,
            hbm_bytes_per_s=1e6, window=2,
        )
        attr.record(
            kind="decode", steps=2, host_s=0.001, device_s=0.004,
            resident_tokens=100,
        )
        # bytes/step = 1000 + 100*10 = 2000; ideal = 2*2000/1e6 =
        # 0.004 s == measured device -> roofline exactly 1.0.
        assert obs.device_step_ms.value() == 2.0
        assert obs.host_overhead.value() == 0.2
        assert obs.device_roofline.value() == 1.0
        assert obs.hbm_step_bytes.value() == 2000.0
        assert obs.dispatch_kind.value({"kind": "decode"}) == 1
        assert obs.device_sync.count() == 1
        # Trailing window (2): a third record evicts the first, so
        # the gauges average ONLY the newest two.
        attr.record(kind="decode", steps=1, host_s=0.0,
                    device_s=0.010, resident_tokens=100)
        attr.record(kind="decode", steps=1, host_s=0.0,
                    device_s=0.010, resident_tokens=100)
        assert obs.device_step_ms.value() == 10.0
        st = attr.stats()
        assert st["window_dispatches"] == 2
        assert st["kinds"]["decode"]["dispatches"] == 3

    def test_roofline_clamped_and_absent_without_bandwidth(self):
        obs = ServingObs()
        attr = DispatchAttribution(
            obs, param_bytes=1000, kv_bytes_per_token=0,
            hbm_bytes_per_s=1e9,
        )
        # Measured device faster than the analytic floor (timer noise
        # / overlap): the fraction clamps at 1.0, never reports >1.
        attr.record(kind="decode", steps=1, host_s=0.0,
                    device_s=1e-9, resident_tokens=0)
        assert obs.device_roofline.value() == 1.0
        obs2 = ServingObs()
        no_bw = DispatchAttribution(obs2, param_bytes=1000,
                                    kv_bytes_per_token=10)
        no_bw.record(kind="decode", steps=1, host_s=0.001,
                     device_s=0.001, resident_tokens=10)
        # No published bandwidth: the roofline gauges are simply
        # never set (absent from /metrics, None in the view).
        assert obs2.device_roofline.value() is None
        assert no_bw.stats()["roofline_fraction"] is None
        assert no_bw.stats()["hbm_bytes_per_step"] is None

    def test_disabled_noops(self):
        obs = ServingObs(enabled=False)
        attr = DispatchAttribution(obs, param_bytes=1, window=4)
        attr.record(kind="decode", steps=1, host_s=1.0, device_s=1.0,
                    resident_tokens=1)
        st = attr.stats()
        assert st["obs_disabled"] is True
        assert st["window_dispatches"] == 0
        assert obs.dispatch_kind.value({"kind": "decode"}) == 0


class TestEngineAttribution:
    """The engine's attribution classification at its real dispatch
    seams — including the mixed (prefill+decode) and fused spec
    (draft+verify+prefill) compositions."""

    def _build(self, **kw):
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=64,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        return cfg, params, ContinuousBatcher(
            cfg, params, slots=2, cache_len=32, prompt_bucket=8,
            chunk_steps=2, **kw,
        )

    def test_kind_invariant_and_views(self, tiny_engine_run):
        engine, _, _ = tiny_engine_run
        obs = engine.obs
        kinds_total = sum(
            obs.dispatch_kind.value({"kind": k})
            for k in DISPATCH_KINDS
        )
        # Every dispatch is classified exactly once, at its sync.
        assert kinds_total == obs.dispatches.value()
        assert obs.device_sync.count() == int(obs.dispatches.value())
        at = engine.attrib_stats()
        assert at["device_step_ms"] == obs.device_step_ms.value()
        assert at["device_step_ms"] is not None
        assert 0.0 <= at["host_overhead_frac"] <= 1.0
        slo = engine.slo_stats()
        # The windowed TTFT view saw the finished requests (<= 3: on
        # a compile-slowed host the earliest sample may age out of
        # the 30 s window; it must never read MORE than happened).
        assert 1 <= slo["windows"]["ttft"]["count"] <= 3
        assert engine.saturation is not None

    def test_mixed_dispatch_classification(self):
        _, _, engine = self._build()
        engine.submit([1, 2, 3], max_new_tokens=8)
        engine.step()  # dispatch 1: lane only -> "prefill"
        engine.submit([4, 5, 6], max_new_tokens=4)
        engine.step()  # dispatch 2: live slot + lane -> "mixed"
        engine.run()
        obs = engine.obs
        assert obs.dispatch_kind.value({"kind": "prefill"}) >= 1
        assert obs.dispatch_kind.value({"kind": "mixed"}) >= 1
        assert obs.dispatch_kind.value({"kind": "decode"}) >= 1
        assert obs.dispatch_kind.value({"kind": "spec"}) == 0

    def test_spec_dispatch_classification(self):
        import jax

        from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
        from walkai_nos_tpu.models.serve import ContinuousBatcher

        cfg = LMConfig(
            vocab_size=64, hidden_dim=32, num_layers=1, num_heads=2,
            max_seq_len=64,
        )
        params = DecoderLM(cfg).init_params(jax.random.PRNGKey(0))
        engine = ContinuousBatcher(
            cfg, params, slots=2, cache_len=32, prompt_bucket=8,
            chunk_steps=2, spec=True, spec_k=2, draft_cfg=cfg,
            draft_params=params, spec_min_accept=0.0,
        )
        engine.submit([1, 2, 3], max_new_tokens=6)
        engine.step()  # round 1: lane riding the spec round
        engine.run()
        obs = engine.obs
        # Prefill + decode + speculative draft/verify fused in one
        # dispatch classifies as spec_prefill; pure rounds as spec.
        assert obs.dispatch_kind.value({"kind": "spec_prefill"}) >= 1
        assert obs.dispatch_kind.value({"kind": "spec"}) >= 1
        assert obs.dispatch_kind.value({"kind": "decode"}) == 0
        at = engine.attrib_stats()
        assert at["kinds"]["spec"]["device_s"] > 0


# -- fleet observability plane (obs/federation.py, obs/anomaly.py) ----


from walkai_nos_tpu.obs.anomaly import (  # noqa: E402
    AnomalyDetector,
    FlightRecorder,
)
from walkai_nos_tpu.obs.federation import (  # noqa: E402
    FEDERATED_PREFIXES,
    federate,
    first_value,
    merge_fleet_trace,
    parse_exposition,
)
from walkai_nos_tpu.obs.trace import RouterTrace  # noqa: E402


def _exposition(**values) -> str:
    """A small real exposition rendered by the real registry (the
    only format the federator consumes)."""
    registry = Registry()
    counter = registry.counter(
        "cb_requests_submitted_total", "requests"
    )
    counter.inc(values.get("submitted", 1))
    gauge = registry.gauge("cb_saturation", "pressure")
    gauge.set(values.get("saturation", 0.5))
    errors = registry.counter("cb_request_errors_total", "errors")
    errors.inc(labels={"reason": "bad_request"})
    hist = registry.histogram(
        "cb_ttft_seconds", "ttft", buckets=(0.1, 1.0)
    )
    hist.observe(values.get("ttft", 0.05))
    # Non-federated families must NOT ride through.
    other = registry.counter("router_requests_total", "own series")
    other.inc()
    return registry.render()


class TestExpositionRoundTrip:
    def test_parse_render_reparse(self):
        """render -> parse -> federate -> parse again: every federated
        family survives with its kind, labels, and values intact plus
        the injected replica label (the satellite's round-trip pin)."""
        text = _exposition(submitted=3, ttft=0.05)
        families = parse_exposition(text)
        assert families["cb_requests_submitted_total"]["kind"] == (
            "counter"
        )
        assert families["cb_ttft_seconds"]["kind"] == "histogram"
        # Histogram sub-series attach to their family.
        names = {
            s[0] for s in families["cb_ttft_seconds"]["samples"]
        }
        assert names == {
            "cb_ttft_seconds_bucket", "cb_ttft_seconds_sum",
            "cb_ttft_seconds_count",
        }
        fed = federate({"r0": text, "r1": text})
        refed = parse_exposition(fed)
        assert set(refed) == {
            "cb_requests_submitted_total", "cb_saturation",
            "cb_request_errors_total", "cb_ttft_seconds",
        }  # router_* filtered out
        for name, family in refed.items():
            for _, labels, _ in family["samples"]:
                assert labels["replica"] in ("r0", "r1"), name
        sub = [
            (labels["replica"], value)
            for sample, labels, value in refed[
                "cb_requests_submitted_total"
            ]["samples"]
        ]
        assert sorted(sub) == [("r0", 3.0), ("r1", 3.0)]
        # One TYPE line per family, not one per source replica.
        assert fed.count("# TYPE cb_ttft_seconds histogram") == 1

    def test_replica_label_never_trusted(self):
        """A source that self-labels `replica` is overwritten: the
        router's handle name is the identity."""
        registry = Registry()
        gauge = registry.gauge("cb_saturation", "pressure")
        gauge.set(0.9, labels={"replica": "spoofed"})
        fed = federate({"real": registry.render()})
        assert 'replica="real"' in fed
        assert "spoofed" not in fed

    def test_label_values_escape_roundtrip(self):
        registry = Registry()
        counter = registry.counter("cb_request_errors_total", "errs")
        counter.inc(labels={"reason": 'a"b\\c\nd'})
        families = parse_exposition(registry.render())
        (_, labels, value), = families[
            "cb_request_errors_total"
        ]["samples"]
        assert labels["reason"] == 'a"b\\c\nd'
        assert value == 1.0

    def test_first_value_and_prefixes(self):
        text = _exposition(saturation=0.25)
        assert first_value(text, "cb_saturation") == 0.25
        assert first_value(text, "cb_nonexistent") is None
        assert FEDERATED_PREFIXES == ("cb_",)
        assert federate({}) == ""

    def test_negative_exponent_values_survive(self):
        """repr of |v| < 1e-4 renders with a negative exponent
        (5e-05): a fast replica's sub-100µs dispatch p99 must ride
        the federation, not silently vanish at the parse (regression:
        the sample-value regex once lacked '-' after the exponent)."""
        registry = Registry()
        gauge = registry.gauge("cb_slo_dispatch_p99", "fast")
        gauge.set(5e-05)
        neg = registry.gauge("cb_saturation", "signed")
        neg.set(-1.5e-07)
        families = parse_exposition(registry.render())
        assert families["cb_slo_dispatch_p99"]["samples"] == [
            ("cb_slo_dispatch_p99", {}, 5e-05),
        ]
        assert families["cb_saturation"]["samples"] == [
            ("cb_saturation", {}, -1.5e-07),
        ]
        fed = federate({"fast": registry.render()})
        assert 'cb_slo_dispatch_p99{replica="fast"} 5e-05' in fed


class TestAnomalyDetector:
    def test_straggler_flips_after_sustained_deviation(self):
        """A replica pinned at ~6x the peer median dispatch p99 flags
        after a few EWMA ticks — never after one (one noisy window
        must not flag anything) — and the healthy peers stay clean."""
        detector = AnomalyDetector()
        signals = {
            "good0": {"dispatch_p99_s": 0.01},
            "good1": {"dispatch_p99_s": 0.011},
            "bad": {"dispatch_p99_s": 0.1},
        }
        first = detector.update(signals)
        assert first["bad"]["flagged"] is False  # one tick never flags
        flipped_at = None
        for tick in range(2, 8):
            verdicts = detector.update(signals)
            if verdicts["bad"]["flagged"]:
                flipped_at = tick
                break
        assert flipped_at is not None
        assert verdicts["good0"]["flagged"] is False
        assert verdicts["good1"]["flagged"] is False
        assert verdicts["bad"]["score"] > verdicts["good0"]["score"]

    def test_hysteresis_clears_below_clear_threshold(self):
        detector = AnomalyDetector(alpha=1.0)  # no smoothing: direct
        bad = {"dispatch_p99_s": 1.0}
        good = {"dispatch_p99_s": 0.01}
        for _ in range(3):
            verdicts = detector.update({
                "a": good, "b": dict(bad),
            })
        assert verdicts["b"]["flagged"] is True
        # Recovered but still above `clear`: the flag HOLDS.
        verdicts = detector.update({
            "a": good, "b": {"dispatch_p99_s": 0.025},
        })
        assert verdicts["b"]["flagged"] is True
        # Fully recovered: score decays under clear -> unflag.
        for _ in range(4):
            verdicts = detector.update({"a": good, "b": dict(good)})
        assert verdicts["b"]["flagged"] is False

    def test_lower_is_worse_signal(self):
        """roofline_fraction inverts: the replica running FURTHER
        from its roofline is the suspect."""
        detector = AnomalyDetector(alpha=1.0)
        for _ in range(3):
            verdicts = detector.update({
                "healthy": {"roofline_fraction": 0.9},
                "degraded": {"roofline_fraction": 0.2},
                "fine": {"roofline_fraction": 0.85},
            })
        assert verdicts["degraded"]["flagged"] is True
        assert verdicts["healthy"]["flagged"] is False

    def test_single_replica_never_flags(self):
        detector = AnomalyDetector(alpha=1.0)
        for _ in range(5):
            verdicts = detector.update({
                "only": {"dispatch_p99_s": 99.0},
            })
        assert verdicts["only"] == {
            "score": 0.0, "flagged": False, "signals": {},
        }

    def test_forget_and_absent_none_signals(self):
        detector = AnomalyDetector(alpha=1.0)
        for _ in range(3):
            detector.update({
                "a": {"dispatch_p99_s": 0.01},
                "b": {"dispatch_p99_s": 1.0},
            })
        assert detector.flagged("b") is True
        # A replica reporting None (obs off / not scraped yet)
        # contributes nothing and scores nothing.
        verdicts = detector.update({
            "a": {"dispatch_p99_s": 0.01},
            "b": {"dispatch_p99_s": 1.0},
            "c": {"dispatch_p99_s": None},
        })
        assert verdicts["c"]["score"] == 0.0
        detector.forget("b")
        assert detector.flagged("b") is False
        assert detector.score("b") == 0.0

    def test_validation(self):
        with pytest.raises(ValueError):
            AnomalyDetector(alpha=0.0)
        with pytest.raises(ValueError):
            AnomalyDetector(threshold=-1.0)
        with pytest.raises(ValueError):
            AnomalyDetector(threshold=2.0, clear=2.0)


class TestFlightRecorder:
    def test_dump_prune_and_bundles(self, tmp_path):
        recorder = FlightRecorder(
            str(tmp_path), keep=2, min_interval_s=0.0
        )
        paths = [
            recorder.dump(f"anomaly", {"n": n})
            for n in range(4)
        ]
        assert all(p is not None for p in paths)
        bundles = recorder.bundles()
        assert len(bundles) == 2  # oldest pruned
        assert [b["n"] for b in bundles] == [2, 3]
        assert all(b["trigger"] == "anomaly" for b in bundles)
        assert all(b["_file"].endswith(".json") for b in bundles)

    def test_throttle(self, tmp_path):
        recorder = FlightRecorder(
            str(tmp_path), keep=8, min_interval_s=100.0
        )
        assert recorder.dump("slo_breach", {}, now=0.0) is not None
        assert recorder.dump("slo_breach", {}, now=50.0) is None
        assert recorder.dump("slo_breach", {}, now=200.0) is not None

    def test_seq_continues_across_instances(self, tmp_path):
        first = FlightRecorder(
            str(tmp_path), keep=8, min_interval_s=0.0
        )
        first.dump("anomaly", {"gen": 1})
        second = FlightRecorder(
            str(tmp_path), keep=8, min_interval_s=0.0
        )
        second.dump("anomaly", {"gen": 2})
        assert [b["gen"] for b in second.bundles()] == [1, 2]

    def test_unserializable_payload_is_stringified(self, tmp_path):
        recorder = FlightRecorder(
            str(tmp_path), keep=2, min_interval_s=0.0
        )
        assert recorder.dump(
            "anomaly", {"obj": object()}
        ) is not None
        assert len(recorder.bundles()) == 1


class TestFleetTraceMerge:
    def _engine_trace(self, origin: float, trace_id: str) -> dict:
        tr = RequestTrace()
        tr.submit(0, origin + 0.10, 4, 8, trace_id=trace_id)
        tr.admitted(0, origin + 0.20, slot=0, blocks=1)
        tr.first_token(0, origin + 0.35)
        tr.done(0, origin + 0.90, "budget", 8)
        return tr.chrome_trace()

    def test_skewed_clocks_align_and_order_monotonic(self):
        """Two replicas whose monotonic clocks sit 100 s apart in
        OPPOSITE directions merge into one router-frame timeline in
        true event order — and span args survive the merge exactly."""
        router_trace = RouterTrace()
        router_trace.submit(
            0, trace_id="t-a", t_submit=1000.0, t_routed=1000.01,
            replica="ra", policy="p2c", t_enqueue=999.99,
        )
        router_trace.submit(
            1, trace_id="t-b", t_submit=1000.05, t_routed=1000.06,
            replica="rb", policy="affinity",
        )
        router_trace.collected(0, 1001.0)
        router_trace.collected(1, 1001.1)
        # Replica A's clock runs 100 s AHEAD of the router's, B's
        # 100 s behind; both served "their" request starting ~1000.01
        # in router time.
        trace_a = self._engine_trace(1100.01 - 0.10, "t-a")
        trace_b = self._engine_trace(900.06 - 0.10, "t-b")
        merged = merge_fleet_trace(router_trace.chrome_trace(), [
            {"name": "ra", "trace": trace_a, "offset_s": 100.0},
            {"name": "rb", "trace": trace_b, "offset_s": -100.0},
        ])
        processes = merged["otherData"]["processes"]
        assert set(processes.values()) == {
            "router", "replica ra", "replica rb",
        }
        events = [
            e for e in merged["traceEvents"] if e.get("ph") != "M"
        ]
        assert [e["ts"] for e in events] == sorted(
            e["ts"] for e in events
        )
        # Request A: the router's route span precedes replica A's
        # queued span, which precedes its decode — in ROUTER time.
        def of(name, trace_id):
            return next(
                e for e in events
                if e["name"] == name
                and e.get("args", {}).get("trace_id") == trace_id
            )

        route_a = of("route", "t-a")
        queued_a = of("queued", "t-a")
        decode_a = of("decode", "t-a")
        assert route_a["ts"] <= queued_a["ts"] <= decode_a["ts"]
        # The 100 s skew is GONE: replica A's submit landed ~10 ms
        # after the router's pick in router time, not 100 s away.
        assert queued_a["ts"] - route_a["ts"] < 1_000_000
        # Exact span floats ride through args untouched.
        assert decode_a["args"]["ttft_s"] == pytest.approx(
            0.25, abs=1e-12
        )
        # Same for the opposite-skew replica.
        route_b = of("route", "t-b")
        queued_b = of("queued", "t-b")
        assert queued_b["ts"] - route_b["ts"] < 1_000_000

    def test_sources_without_origin_are_skipped(self):
        router_trace = RouterTrace()
        router_trace.submit(
            0, trace_id="t", t_submit=1.0, t_routed=1.01,
            replica="r", policy="p2c",
        )
        legacy = {"traceEvents": [{"name": "x", "ph": "i", "ts": 5}]}
        merged = merge_fleet_trace(router_trace.chrome_trace(), [
            {"name": "legacy", "trace": legacy, "offset_s": 0.0},
            {"name": "empty", "trace": RequestTrace().chrome_trace(),
             "offset_s": 0.0},
            {"name": "dead", "trace": None, "offset_s": 0.0},
        ])
        assert merged["otherData"]["skipped"] == ["replica legacy"]
        assert set(
            merged["otherData"]["processes"].values()
        ) == {"router"}

    def test_empty_everything(self):
        merged = merge_fleet_trace(RouterTrace().chrome_trace(), [])
        assert merged["traceEvents"] == []
        assert merged["otherData"]["clock_origin_monotonic_s"] is None


class TestRouterTrace:
    def test_spans_and_ring_export(self):
        tr = RouterTrace()
        tr.submit(
            7, trace_id="id7", t_submit=10.0, t_routed=10.02,
            replica="r0", policy="affinity", t_enqueue=9.99,
            affinity_key=0xDEADBEEF,
        )
        tr.event("scale_up", 10.5, replica="spare0",
                 reason="saturation")
        tr.collected(7, 11.0)
        ct = tr.chrome_trace()
        events = ct["traceEvents"]
        names = [e["name"] for e in events if e.get("ph") == "X"]
        assert names == ["queue_wait", "route", "replica_roundtrip"]
        route = next(e for e in events if e["name"] == "route")
        assert route["args"]["trace_id"] == "id7"
        assert route["args"]["replica"] == "r0"
        assert route["args"]["affinity_key"] == "deadbeef"
        roundtrip = next(
            e for e in events if e["name"] == "replica_roundtrip"
        )
        assert roundtrip["dur"] == 980_000  # 10.02 -> 11.0
        scale = next(e for e in events if e["name"] == "scale_up")
        assert scale["ph"] == "i" and scale["tid"] == 0
        assert ct["otherData"]["clock_origin_monotonic_s"] == 9.99

    def test_retention_and_disabled(self):
        tr = RouterTrace(keep_done=1)
        for rid in range(3):
            tr.submit(
                rid, trace_id=f"t{rid}", t_submit=float(rid),
                t_routed=float(rid) + 0.1, replica="r", policy="p2c",
            )
            tr.collected(rid, float(rid) + 0.5)
        assert len(tr.spans()) == 1
        off = RouterTrace(enabled=False)
        off.submit(
            0, trace_id="x", t_submit=0.0, t_routed=0.1,
            replica="r", policy="p2c",
        )
        off.event("scale_up", 0.0)
        assert off.spans() == []
        assert off.chrome_trace()["traceEvents"] == []


class TestRequestTraceFleetContract:
    def test_trace_id_rides_spans_and_chrome_args(self):
        tr = RequestTrace()
        tr.submit(3, 10.0, 4, 8, trace_id="abc-123")
        tr.admitted(3, 10.1, slot=0, blocks=1)
        tr.first_token(3, 10.2)
        tr.done(3, 10.9, "budget", 8)
        assert tr.timeline(3)["trace_id"] == "abc-123"
        ct = tr.chrome_trace()
        decode = next(
            e for e in ct["traceEvents"] if e["name"] == "decode"
        )
        assert decode["args"]["trace_id"] == "abc-123"
        # EXACT floats, not microsecond-rounded: the PR 3 convention
        # survives the fleet merge through args.
        assert decode["args"]["ttft_s"] == tr.ttft_s(3)
        assert decode["args"]["wall_s"] == tr.wall_s(3)
        assert ct["otherData"]["clock_origin_monotonic_s"] == 10.0

    def test_empty_trace_carries_null_origin(self):
        ct = RequestTrace().chrome_trace()
        assert ct["traceEvents"] == []
        assert ct["otherData"]["clock_origin_monotonic_s"] is None

"""Quota scheduler chaos sweep: random churn, steady-state invariants.

Random pod arrivals, deletions, and phase transitions across three
quotas (one borrowing-capped, one uncapped, one at its min) against the
full scheduler manager (scheduler + capacity labeler + quota
reconcilers). At quiesce the cluster must satisfy the elastic-quota
contract regardless of the interleaving:

  1. node capacity is never oversubscribed,
  2. a quota with `max` never holds more than max,
  3. total over-quota usage never exceeds what other quotas' unused
     min actually lends,
  4. capacity labels agree with each quota's aggregate position.
"""

import random
import time

from walkai_nos_tpu.api import constants
from walkai_nos_tpu.cmd.tpuscheduler import build_manager
from walkai_nos_tpu.kube import objects
from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.quota.labeler import LABEL_CAPACITY, OVER_QUOTA
from walkai_nos_tpu.quota.resources import pod_quota_request
from walkai_nos_tpu.quota.state import ClusterQuotaState, pod_holds_quota

TPU = constants.RESOURCE_TPU
CHIPS = constants.RESOURCE_TPU_CHIPS
CAPACITY = 16


def _quota(name, ns, min_chips, max_chips=None):
    spec = {"min": {CHIPS: str(min_chips)}}
    if max_chips is not None:
        spec["max"] = {CHIPS: str(max_chips)}
    return {
        "kind": "ElasticQuota",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


def _pod(name, ns, chips, created):
    return {
        "metadata": {
            "name": name, "namespace": ns,
            "creationTimestamp": created, "labels": {},
        },
        "spec": {
            "schedulerName": "walkai-nos-scheduler",
            "containers": [
                {"resources": {"requests": {TPU: str(chips)}}}
            ],
        },
        "status": {"phase": "Pending"},
    }


def test_random_churn_preserves_quota_invariants():
    for seed in range(6):
        rng = random.Random(seed)
        kube = FakeKubeClient()
        kube.create("Node", {
            "metadata": {"name": "host-a"},
            "status": {"allocatable": {TPU: str(CAPACITY)}},
        })
        kube.create("ElasticQuota", _quota("qa", "team-a", 4, 8), "team-a")
        kube.create("ElasticQuota", _quota("qb", "team-b", 4), "team-b")
        kube.create("ElasticQuota", _quota("qc", "team-c", 8, 8), "team-c")

        counter = 0
        with build_manager(kube):
            for tick in range(rng.randrange(20, 40)):
                op = rng.random()
                pods = kube.list("Pod")
                if op < 0.55 or not pods:
                    counter += 1
                    ns = rng.choice(["team-a", "team-b", "team-c"])
                    kube.create("Pod", _pod(
                        f"p{counter}", ns, rng.choice([1, 2, 4]),
                        f"2026-01-01T00:{tick:02d}:{counter % 60:02d}Z",
                    ), ns)
                elif op < 0.8:
                    victim = rng.choice(pods)
                    try:
                        kube.delete(
                            "Pod", objects.name(victim),
                            objects.namespace(victim),
                        )
                    except Exception:
                        pass
                else:
                    pod = rng.choice(pods)
                    if pod["spec"].get("nodeName"):
                        try:
                            kube.patch(
                                "Pod", objects.name(pod),
                                {"status": {"phase": "Running"}},
                                objects.namespace(pod),
                            )
                        except Exception:
                            pass
                time.sleep(rng.random() * 0.03)

            # Quiesce: bound pods all Running, then let the loops settle
            # until the pod set is stable across a full settle window.
            deadline = time.time() + 30
            stable_since = None
            snapshot = None
            while time.time() < deadline:
                for pod in kube.list("Pod"):
                    if pod["spec"].get("nodeName") and (
                        pod["status"].get("phase") == "Pending"
                    ):
                        kube.patch(
                            "Pod", objects.name(pod),
                            {"status": {"phase": "Running"}},
                            objects.namespace(pod),
                        )
                view = sorted(
                    (
                        objects.namespace(p), objects.name(p),
                        p["spec"].get("nodeName", ""),
                        objects.labels(p).get(LABEL_CAPACITY, ""),
                    )
                    for p in kube.list("Pod")
                )
                if view == snapshot:
                    if stable_since and time.time() - stable_since > 1.5:
                        break
                    stable_since = stable_since or time.time()
                else:
                    snapshot, stable_since = view, None
                time.sleep(0.1)

        pods = kube.list("Pod")
        held = [p for p in pods if pod_holds_quota(p)]

        # (1) capacity
        total = sum(pod_quota_request(p).get(CHIPS, 0) for p in held)
        assert total <= CAPACITY, (seed, total)

        # (2) + (3) via the scheduler's own accounting
        state = ClusterQuotaState.build(
            kube.list("ElasticQuota"), pods
        )
        for q in state.quotas:
            used = q.used.get(CHIPS, 0)
            if q.max:
                assert used <= q.max.get(CHIPS, CAPACITY), (seed, q.name, used)
            over = q.over_quota_usage(CHIPS)
            lendable = state.lendable_over_quotas(q, CHIPS)
            assert over <= lendable, (seed, q.name, over, lendable)

        # (4) labels agree with the aggregate position
        for q in state.quotas:
            ns = q.namespaces[0]
            ns_pods = [
                p for p in held if objects.namespace(p) == ns
                and p["status"].get("phase") == "Running"
            ]
            over_labeled = [
                p for p in ns_pods
                if objects.labels(p).get(LABEL_CAPACITY) == OVER_QUOTA
            ]
            if q.used.get(CHIPS, 0) <= q.min.get(CHIPS, 0):
                assert not over_labeled, (seed, q.name)
            elif ns_pods:
                assert over_labeled, (seed, q.name)

"""Annotation codec tests (reference: `pkg/gpu/annotation_test.go`, 449 LoC)."""

import pytest

from walkai_nos_tpu.tpu.annotations import (
    AnnotationParseError,
    SpecAnnotation,
    StatusAnnotation,
    parse_node_annotations,
    parse_spec_annotation,
    parse_status_annotation,
    spec_annotations_from_node_partitioning,
    spec_matches_status,
    status_annotations_to_geometry,
)
from walkai_nos_tpu.tpu.device import DeviceStatus


class TestSpecAnnotation:
    def test_round_trip(self):
        a = SpecAnnotation(mesh_index=0, profile="2x2", quantity=2)
        assert a.key == "nos.walkai.io/spec-tpu-0-2x2"
        assert a.value == "2"
        assert parse_spec_annotation(a.key, a.value) == a

    @pytest.mark.parametrize(
        "key,value",
        [
            ("nos.walkai.io/spec-tpu-0-2x2", "nan"),
            ("nos.walkai.io/spec-tpu-x-2x2", "1"),
            ("nos.walkai.io/spec-tpu-0", "1"),
            ("nos.walkai.io/spec-tpu-0-", "1"),
            ("other/spec-tpu-0-2x2", "1"),
        ],
    )
    def test_invalid(self, key, value):
        with pytest.raises(AnnotationParseError):
            parse_spec_annotation(key, value)


class TestStatusAnnotation:
    def test_round_trip_free(self):
        a = StatusAnnotation(0, "2x2", DeviceStatus.FREE, 1)
        assert a.key == "nos.walkai.io/status-tpu-0-2x2-free"
        assert parse_status_annotation(a.key, a.value) == a

    def test_round_trip_used(self):
        a = StatusAnnotation(1, "1x1", DeviceStatus.USED, 3)
        assert a.key == "nos.walkai.io/status-tpu-1-1x1-used"
        assert parse_status_annotation(a.key, a.value) == a

    @pytest.mark.parametrize(
        "key",
        [
            "nos.walkai.io/status-tpu-0-2x2-busy",
            "nos.walkai.io/status-tpu-0-2x2",
            "nos.walkai.io/status-tpu-0-2x2-unknown",
            "nos.walkai.io/status-tpu-a-2x2-free",
        ],
    )
    def test_invalid(self, key):
        with pytest.raises(AnnotationParseError):
            parse_status_annotation(key, "1")


class TestParseNodeAnnotations:
    def test_splits_and_skips(self):
        annotations = {
            "nos.walkai.io/spec-tpu-0-2x2": "2",
            "nos.walkai.io/spec-tpu-0-1x1": "4",
            "nos.walkai.io/status-tpu-0-2x2-free": "1",
            "nos.walkai.io/status-tpu-0-2x2-used": "1",
            "nos.walkai.io/spec-partitioning-plan": "12345",
            "nos.walkai.io/spec-tpu-garbage": "zz",  # malformed -> skipped
            "unrelated.io/foo": "bar",
        }
        status, spec = parse_node_annotations(annotations)
        assert len(spec) == 2
        assert len(status) == 2
        assert {s.profile for s in spec} == {"2x2", "1x1"}

    def test_empty(self):
        assert parse_node_annotations({}) == ([], [])


class TestSpecMatchesStatus:
    def test_matches(self):
        spec = [SpecAnnotation(0, "2x2", 2)]
        status = [
            StatusAnnotation(0, "2x2", DeviceStatus.FREE, 1),
            StatusAnnotation(0, "2x2", DeviceStatus.USED, 1),
        ]
        assert spec_matches_status(spec, status)

    def test_quantity_mismatch(self):
        spec = [SpecAnnotation(0, "2x2", 2)]
        status = [StatusAnnotation(0, "2x2", DeviceStatus.FREE, 1)]
        assert not spec_matches_status(spec, status)

    def test_profile_mismatch(self):
        spec = [SpecAnnotation(0, "2x2", 1)]
        status = [StatusAnnotation(0, "1x2", DeviceStatus.FREE, 1)]
        assert not spec_matches_status(spec, status)

    def test_extra_status_profile(self):
        spec = [SpecAnnotation(0, "2x2", 1)]
        status = [
            StatusAnnotation(0, "2x2", DeviceStatus.FREE, 1),
            StatusAnnotation(0, "1x1", DeviceStatus.FREE, 1),
        ]
        assert not spec_matches_status(spec, status)

    def test_zero_quantities_ignored(self):
        spec = [SpecAnnotation(0, "2x2", 1), SpecAnnotation(0, "1x1", 0)]
        status = [
            StatusAnnotation(0, "2x2", DeviceStatus.USED, 1),
            StatusAnnotation(0, "1x1", DeviceStatus.FREE, 0),
        ]
        assert spec_matches_status(spec, status)

    def test_both_empty(self):
        assert spec_matches_status([], [])


class TestHelpers:
    def test_spec_from_partitioning(self):
        out = spec_annotations_from_node_partitioning({0: {"2x2": 2, "1x1": 0}})
        assert out == [SpecAnnotation(0, "2x2", 2)]

    def test_status_to_geometry(self):
        status = [
            StatusAnnotation(0, "2x2", DeviceStatus.FREE, 1),
            StatusAnnotation(0, "2x2", DeviceStatus.USED, 1),
            StatusAnnotation(1, "1x1", DeviceStatus.FREE, 2),
        ]
        assert status_annotations_to_geometry(status, 0) == {"2x2": 2}
        assert status_annotations_to_geometry(status, 1) == {"1x1": 2}


class TestNegativeQuantitiesRejected:
    def test_negative_spec(self):
        with pytest.raises(AnnotationParseError, match="negative"):
            parse_spec_annotation("nos.walkai.io/spec-tpu-0-2x2", "-1")

    def test_negative_status(self):
        with pytest.raises(AnnotationParseError, match="negative"):
            parse_status_annotation("nos.walkai.io/status-tpu-0-2x2-free", "-3")

    def test_parse_node_annotations_skips_negative(self):
        st, sp = parse_node_annotations(
            {"nos.walkai.io/status-tpu-0-2x2-free": "-3"}
        )
        assert st == [] and sp == []


class TestRoundTripProperty:
    """Seeded fuzz: random spec/status sets survive key/value round-trips
    through parse_node_annotations unchanged (codec bijectivity, the
    invariant `annotation_test.go` exercises case by case)."""

    def test_random_round_trips(self):
        import random

        from walkai_nos_tpu.tpu.annotations import (
            SpecAnnotation,
            StatusAnnotation,
            parse_node_annotations,
        )
        from walkai_nos_tpu.tpu.device import DeviceStatus

        rng = random.Random(42)
        profiles = ["1x1", "1x2", "2x2", "2x4", "1x1x2", "2c", "4c"]
        for _ in range(300):
            spec = {
                SpecAnnotation(
                    mesh_index=rng.randrange(0, 4),
                    profile=rng.choice(profiles),
                    quantity=rng.randrange(1, 9),
                )
                for _ in range(rng.randrange(0, 5))
            }
            status = {
                StatusAnnotation(
                    mesh_index=rng.randrange(0, 4),
                    profile=rng.choice(profiles),
                    status=rng.choice(
                        [DeviceStatus.USED, DeviceStatus.FREE]
                    ),
                    quantity=rng.randrange(1, 9),
                )
                for _ in range(rng.randrange(0, 5))
            }
            annotations = {a.key: a.value for a in spec}
            annotations.update({a.key: a.value for a in status})
            # unrelated annotations must be ignored, not break parsing
            annotations["unrelated.io/foo"] = "bar"
            parsed_status, parsed_spec = parse_node_annotations(annotations)
            # key collisions merge: compare as {key: value} maps
            assert {a.key: a.value for a in parsed_spec} == {
                a.key: a.value for a in spec
            }
            assert {a.key: a.value for a in parsed_status} == {
                a.key: a.value for a in status
            }

"""The shared percentile helpers: one definition for every benchmark
surface, pinned by value — the interpolated variant decides the
noisy-neighbor CI, so an indexing drift must fail here, not shift the
published verdict silently."""

from walkai_nos_tpu.utils.stats import percentile, percentile_interp


class TestPercentile:
    def test_empty_is_none(self):
        assert percentile([], 50) is None
        assert percentile_interp([], 99) is None

    def test_singleton(self):
        assert percentile([7], 99) == 7
        assert percentile_interp([7], 1) == 7

    def test_nearest_rank(self):
        vals = list(range(1, 101))  # 1..100
        assert percentile(vals, 50) == 50
        assert percentile(vals, 99) == 99
        assert percentile(vals, 100) == 100
        assert percentile([1, 2, 3], 50) == 2
        assert percentile([1, 2, 3], 90) == 3

    def test_interpolated(self):
        assert percentile_interp([0, 10], 50) == 5.0
        assert percentile_interp([1, 2, 3, 4], 50) == 2.5
        # 0..100: position q maps exactly onto the value q.
        vals = list(range(101))
        for q in (0, 25, 50, 95, 99, 100):
            assert abs(percentile_interp(vals, q) - q) < 1e-9
        # Between order statistics: linear blend.
        assert abs(percentile_interp([0, 100], 75) - 75.0) < 1e-9

    def test_interp_smoother_than_rank(self):
        """The property the CI path relies on: a small sample change
        moves the interpolated estimate continuously, not by a whole
        order statistic."""
        a = [0.1] * 99 + [0.2]
        b = [0.1] * 98 + [0.2, 0.2]
        jump_rank = abs(percentile(b, 99) - percentile(a, 99))
        jump_interp = abs(
            percentile_interp(b, 99) - percentile_interp(a, 99)
        )
        assert jump_interp <= jump_rank

"""Shared watch multiplexer: one upstream stream, informer semantics."""

import threading
import time

from walkai_nos_tpu.kube.fake import FakeKubeClient
from walkai_nos_tpu.kube.sharedwatch import SharedWatchClient


class CountingClient(FakeKubeClient):
    """Fake that counts watch() streams opened per kind."""

    def __init__(self):
        super().__init__()
        self.watch_opens: dict[str, int] = {}

    def watch(self, kind, namespace=None, stop=None):
        self.watch_opens[kind] = self.watch_opens.get(kind, 0) + 1
        return super().watch(kind, namespace, stop)


def _collect(shared, kind, out, stop_flag, started):
    it = shared.watch(kind, stop=lambda: stop_flag.is_set())
    started.set()
    for event in it:
        out.append(event)


def _eventually(fn, timeout=10.0, msg=""):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out: {msg}")


class TestSharedWatch:
    def test_abandoned_subscriber_is_evicted(self, monkeypatch):
        """A consumer that drops its iterator without closing it leaves
        the queue registered until GC; once its backlog passes the cap
        the pump evicts it instead of filling it forever."""
        from walkai_nos_tpu.kube import sharedwatch

        monkeypatch.setattr(sharedwatch, "MAX_SUBSCRIBER_BACKLOG", 5)
        upstream = CountingClient()
        shared = SharedWatchClient(upstream)
        # An iterator never advanced past its snapshot: its queue is
        # registered but nothing drains it.
        it = shared.watch("Pod", stop=lambda: False)
        next(it)  # SYNCED of the empty cache: now registered
        try:
            stream = shared._streams[("Pod", None)]
            assert len(stream._subscribers) == 1
            for i in range(20):
                upstream.create(
                    "Pod",
                    {"metadata": {"name": f"p{i}", "namespace": "d"}},
                    "d",
                )
            _eventually(
                lambda: len(stream._subscribers) == 0,
                msg="abandoned subscriber evicted",
            )
        finally:
            it.close()
            shared.close()

    def test_two_subscribers_one_upstream_stream(self):
        upstream = CountingClient()
        upstream.create("Pod", {"metadata": {"name": "a", "namespace": "d"}}, "d")
        shared = SharedWatchClient(upstream)
        stop = threading.Event()
        outs: list[list] = [[], []]
        threads = []
        try:
            for i in range(2):
                started = threading.Event()
                t = threading.Thread(
                    target=_collect,
                    args=(shared, "Pod", outs[i], stop, started),
                    daemon=True,
                )
                t.start()
                threads.append(t)
                started.wait(5)

            _eventually(
                lambda: all(
                    any(e == "ADDED" for e, _ in out) for out in outs
                ),
                msg="both subscribers saw the existing pod",
            )
            upstream.create(
                "Pod", {"metadata": {"name": "b", "namespace": "d"}}, "d"
            )
            _eventually(
                lambda: all(
                    any(
                        e == "ADDED"
                        and o.get("metadata", {}).get("name") == "b"
                        for e, o in out
                    )
                    for out in outs
                ),
                msg="both subscribers saw the live event",
            )
            assert upstream.watch_opens.get("Pod") == 1
        finally:
            stop.set()
            shared.close()
            for t in threads:
                t.join(timeout=5)

    def test_late_subscriber_replays_cache(self):
        upstream = CountingClient()
        upstream.create("Pod", {"metadata": {"name": "a", "namespace": "d"}}, "d")
        shared = SharedWatchClient(upstream)
        stop = threading.Event()
        first: list = []
        started = threading.Event()
        t1 = threading.Thread(
            target=_collect, args=(shared, "Pod", first, stop, started),
            daemon=True,
        )
        t1.start()
        started.wait(5)
        t2 = None
        try:
            _eventually(
                lambda: any(e == "SYNCED" for e, _ in first),
                msg="first subscriber synced",
            )
            upstream.create(
                "Pod", {"metadata": {"name": "b", "namespace": "d"}}, "d"
            )
            _eventually(
                lambda: sum(1 for e, _ in first if e == "ADDED") >= 2,
                msg="cache holds both pods",
            )
            # Late joiner: must see both pods from the replay cache,
            # not a second upstream watch.
            late: list = []
            started2 = threading.Event()
            t2 = threading.Thread(
                target=_collect, args=(shared, "Pod", late, stop, started2),
                daemon=True,
            )
            t2.start()
            started2.wait(5)
            _eventually(
                lambda: sum(1 for e, _ in late if e == "ADDED") >= 2
                and any(e == "SYNCED" for e, _ in late),
                msg="late subscriber replayed both pods + SYNCED",
            )
            assert upstream.watch_opens.get("Pod") == 1
        finally:
            stop.set()
            shared.close()
            t1.join(timeout=5)
            if t2 is not None:
                t2.join(timeout=5)

    def test_deletion_drops_from_replay(self):
        upstream = CountingClient()
        upstream.create("Pod", {"metadata": {"name": "a", "namespace": "d"}}, "d")
        shared = SharedWatchClient(upstream)
        stop = threading.Event()
        first: list = []
        started = threading.Event()
        t1 = threading.Thread(
            target=_collect, args=(shared, "Pod", first, stop, started),
            daemon=True,
        )
        t1.start()
        started.wait(5)
        t2 = None
        try:
            _eventually(
                lambda: any(e == "ADDED" for e, _ in first),
                msg="subscriber saw pod",
            )
            upstream.delete("Pod", "a", "d")
            _eventually(
                lambda: any(e == "DELETED" for e, _ in first),
                msg="subscriber saw deletion",
            )
            late: list = []
            started2 = threading.Event()
            t2 = threading.Thread(
                target=_collect, args=(shared, "Pod", late, stop, started2),
                daemon=True,
            )
            t2.start()
            started2.wait(5)
            time.sleep(0.3)
            assert not any(e == "ADDED" for e, _ in late), late
        finally:
            stop.set()
            shared.close()
            t1.join(timeout=5)
            if t2 is not None:
                t2.join(timeout=5)

    def test_crud_delegates(self):
        shared = SharedWatchClient(FakeKubeClient())
        shared.create("Node", {"metadata": {"name": "n1"}})
        assert shared.get("Node", "n1")["metadata"]["name"] == "n1"
        assert len(shared.list("Node")) == 1
        shared.delete("Node", "n1")
        assert shared.list("Node") == []


    def test_empty_snapshot_still_emits_synced(self):
        """The initial burst must END with SYNCED even with zero
        objects — that marker is what lets a re-subscribing Controller
        prune its stale cache (the upstream watch contract)."""
        shared = SharedWatchClient(CountingClient())
        stop = threading.Event()
        events: list = []
        started = threading.Event()
        t = threading.Thread(
            target=_collect, args=(shared, "Pod", events, stop, started),
            daemon=True,
        )
        t.start()
        started.wait(5)
        try:
            _eventually(
                lambda: any(e == "SYNCED" for e, _ in events),
                msg="empty stream still framed with SYNCED",
            )
            assert not any(e == "ADDED" for e, _ in events)
        finally:
            stop.set()
            shared.close()
            t.join(timeout=5)

    def test_manager_stop_closes_shared_streams(self):
        """build_manager wraps the client; manager exit must stop the
        pump threads (no watch outliving the manager)."""
        from walkai_nos_tpu.cmd.tpuscheduler import build_manager

        upstream = CountingClient()
        before = {
            th.name for th in threading.enumerate()
            if th.name.startswith("sharedwatch-")
        }
        with build_manager(upstream):
            _eventually(
                lambda: any(
                    th.name.startswith("sharedwatch-")
                    for th in threading.enumerate()
                ),
                msg="pump threads running under the manager",
            )
        _eventually(
            lambda: {
                th.name for th in threading.enumerate()
                if th.name.startswith("sharedwatch-") and th.is_alive()
            } <= before,
            msg="pump threads stopped with the manager",
        )


class TestSharedWatchOverTheWire:
    """SharedWatchClient over the real RestKubeClient watch protocol,
    including an outage: late subscribers must wait out the RESYNC
    window and receive a clean post-outage snapshot."""

    def test_late_join_during_outage_sees_pruned_world(self):
        from tests.apiserver import MiniApiServer
        from tests.helpers import make_flaky_watch
        from walkai_nos_tpu.kube.rest import RestKubeClient

        api = MiniApiServer()
        url = api.start()
        try:
            client = RestKubeClient(server=url)
            admin = RestKubeClient(server=url)
            admin.create("Node", {"metadata": {"name": "n1"}})
            admin.create("Node", {"metadata": {"name": "n2"}})
            # One upstream outage during which n2 is deleted.
            make_flaky_watch(client, lambda: admin.delete("Node", "n2"))
            shared = SharedWatchClient(client)
            stop = threading.Event()
            first: list = []
            started = threading.Event()
            t1 = threading.Thread(
                target=_collect, args=(shared, "Node", first, stop, started),
                daemon=True,
            )
            t1.start()
            started.wait(5)
            t2 = None

            def outage_resolved():
                # Two orderings are legitimate: the subscriber rides the
                # outage (sees RESYNC framing, two SYNCEDs), or under
                # load it only acquires the stream lock after the relist
                # and replays the already-pruned world (one SYNCED, no
                # n2). Either way the stream is post-outage.
                synced = sum(1 for e, _ in first if e == "SYNCED")
                if synced >= 2:
                    return True
                saw_n2 = any(
                    o.get("metadata", {}).get("name") == "n2"
                    for e, o in first
                    if e in ("ADDED", "MODIFIED")
                )
                return synced >= 1 and not saw_n2

            try:
                _eventually(outage_resolved, msg="outage resolved")
                # Late joiner AFTER the outage: snapshot must contain
                # only the survivor.
                late: list = []
                started2 = threading.Event()
                t2 = threading.Thread(
                    target=_collect,
                    args=(shared, "Node", late, stop, started2),
                    daemon=True,
                )
                t2.start()
                started2.wait(5)
                _eventually(
                    lambda: any(e == "SYNCED" for e, _ in late),
                    msg="late joiner synced",
                )
                added = [
                    o["metadata"]["name"] for e, o in late if e == "ADDED"
                ]
                assert added == ["n1"], added
            finally:
                stop.set()
                shared.close()
                t1.join(timeout=5)
                if t2 is not None:
                    t2.join(timeout=5)
        finally:
            api.stop()

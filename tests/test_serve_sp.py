"""Sequence-parallel prefill lane + length-aware admission
(`models/serve.py` `sp_prefill`).

Tier-1 surface for the long-context serving lane: a LONG prompt
(>= `sp_min_tokens`) fans its chunk window across spare lane rows in
ONE dispatch, and that fan-out must be TOKEN-IDENTICAL to the serial
lane — greedy and sampled, prefix cache on and off, tp 1 and 2 on the
emulated mesh, prompts crossing the 128-row block boundary, and with
shorts admitted mid-prefill beside the live sp entry. The admission
side has its own contract: at most one sp entry prefills at a time,
a held long is jumped by the first short behind it (never the other
way round), and the holds/requests/rows counters feed the fairness
bench. The capture plane closes the loop: a capture recorded sp-on
must replay token-identically sp-off (PR 15's digest check is the
machine proof the mode changes scheduling, not tokens).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from walkai_nos_tpu.models.decode import make_generate_fn
from walkai_nos_tpu.models.lm import DecoderLM, LMConfig
from walkai_nos_tpu.models.serve import ContinuousBatcher
from walkai_nos_tpu.sim.replay import (
    ENGINE_KNOBS,
    load_capture,
    replay_capture,
)

CFG = LMConfig(
    vocab_size=64, hidden_dim=32, num_layers=2, num_heads=2,
    max_seq_len=512,
)

# fp32 twin for the tp=2 arm (same rationale as test_serve_tp.py:
# bf16 ulp noise under the psum's changed reduction order could flip
# a near-tied argmax).
CFG_TP = LMConfig(
    vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
    num_kv_heads=2, max_seq_len=256, dtype="float32",
    norm="rmsnorm", mlp="swiglu", mlp_dim=128, rope=True,
    use_bias=False, head_bias=False,
)


@pytest.fixture(scope="module")
def params():
    return DecoderLM(CFG).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def params_tp():
    return DecoderLM(CFG_TP).init_params(jax.random.PRNGKey(0))


def _prompt(n, seed=0, vocab=CFG.vocab_size):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, n).astype(np.int32)


def _expected(params, prompt, max_new, cfg=CFG):
    gen = make_generate_fn(cfg)
    out = gen(params, jnp.asarray(prompt[None]), max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out)[0]]


def _engine(params, sp, **kw):
    """Engine with the long lane armed low enough that tiny-config
    prompts exercise it: 96-token threshold, 32-token chunks, span 3
    so a long claims up to 2 spare rows per dispatch."""
    base = dict(
        slots=3, cache_len=384, chunk_steps=3, paged=True,
        prefill_chunk=32, prefill_lanes=4,
        sp_prefill=sp, sp_min_tokens=96, sp_span=3,
    )
    base.update(kw)
    return ContinuousBatcher(CFG, params, **base)


def _run_specs(eng, specs, **submit_kw):
    rids = {
        eng.submit(_prompt(n, seed=n), max_new_tokens=m, **submit_kw):
            (n, m)
        for n, m in specs
    }
    res = eng.run()
    return {rids[r]: toks for r, toks in res.items()}


class TestSpParity:
    # Two longs (140 crosses the 128-row block edge mid-prefill, 300
    # spans three blocks), one boundary-threshold long (97 just over
    # sp_min_tokens), one short riding beside them.
    SPECS = [(140, 9), (20, 12), (300, 8), (97, 11)]

    @pytest.mark.parametrize("prefix", [True, False],
                             ids=["prefix-on", "prefix-off"])
    def test_greedy_identity_sp_on_off(self, params, prefix):
        """sp-on == sp-off == standalone generation, token for token,
        with the fan-out PROVABLY engaged (rows_total > requests_total
        would be vacuous parity otherwise)."""
        outs = {}
        for sp in (True, False):
            eng = _engine(params, sp, prefix_cache=prefix)
            outs[sp] = _run_specs(eng, self.SPECS)
            if sp:
                st = eng.sp_stats()
                assert st["requests_total"] == 3  # 140, 300, 97
                assert st["rows_total"] > st["requests_total"]
                assert st["active"] == 0  # all drained
        for n, m in self.SPECS:
            want = _expected(params, _prompt(n, seed=n), m)
            assert outs[True][(n, m)] == want, (n, m)
            assert outs[False][(n, m)] == want, (n, m)

    def test_sampled_identity_sp_on_off(self, params):
        """(prompt, knobs, seed) fully determines sampled output in
        both modes — the span's finishing row must seed the slot PRNG
        exactly like the serial lane's final chunk."""
        specs = [(140, 8), (20, 8)]
        outs = {}
        for sp in (True, False):
            eng = _engine(params, sp)
            outs[sp] = _run_specs(
                eng, specs, temperature=0.9, top_k=16, top_p=0.95,
                seed=123,
            )
        assert outs[True] == outs[False]

    def test_block_boundary_prompts(self, params):
        """Lengths straddling the 128-row page edge (127/128/129) and
        an exact two-page prompt: the span's per-row scatter must land
        each window in the right block with no off-by-one at the
        seam."""
        specs = [(127, 6), (128, 6), (129, 6), (256, 5)]
        eng = _engine(params, True, slots=2, cache_len=384)
        outs = _run_specs(eng, specs)
        for n, m in specs:
            want = _expected(params, _prompt(n, seed=n), m)
            assert outs[(n, m)] == want, (n, m)
        assert eng.sp_stats()["requests_total"] == 4

    def test_mid_prefill_admission_beside_live_sp_lane(self, params):
        """Shorts submitted AFTER the long's fan-out is in flight
        admit onto the remaining lane rows and decode beside it —
        and everyone's tokens still match standalone generation."""
        eng = _engine(params, True)
        long_rid = eng.submit(_prompt(300, seed=300), max_new_tokens=8)
        eng.step()  # long admitted, span dispatched
        assert eng.sp_stats()["active"] == 1
        short_rids = {
            eng.submit(_prompt(n, seed=n), max_new_tokens=7): n
            for n in (30, 45)
        }
        saw_concurrent = False
        out = {}
        while eng.has_work:
            eng.step()
            if (eng.sp_stats()["active"] == 1
                    and len(eng._prefilling) >= 2):
                saw_concurrent = True
            out.update(eng.drain_done())
        assert saw_concurrent
        assert out[long_rid] == _expected(
            params, _prompt(300, seed=300), 8
        )
        for rid, n in short_rids.items():
            assert out[rid] == _expected(params, _prompt(n, seed=n), 7)

    def test_tp2_mesh_identity(self, params_tp):
        """The sp lane composes with tensor parallelism: sp-on tp=2
        (emulated model-axis mesh) == sp-off tp=2 == sp-off tp=1."""
        specs = [(137, 8), (7, 8)]
        outs = {}
        for sp, tp in ((True, 2), (False, 2), (False, 1)):
            cfg = dataclasses.replace(CFG_TP, tp_devices=tp)
            eng = ContinuousBatcher(
                cfg, params_tp, slots=2, cache_len=256, chunk_steps=4,
                paged=True, prefill_chunk=32, prefill_lanes=4,
                sp_prefill=sp, sp_min_tokens=96, sp_span=2,
            )
            rids = {
                eng.submit(
                    _prompt(n, seed=n, vocab=CFG_TP.vocab_size),
                    max_new_tokens=m,
                ): (n, m)
                for n, m in specs
            }
            res = eng.run()
            outs[(sp, tp)] = {rids[r]: t for r, t in res.items()}
            if sp:
                assert eng.sp_stats()["requests_total"] == 1
        assert outs[(True, 2)] == outs[(False, 2)] == outs[(False, 1)]

    def test_stream_seam_token_identical(self, params, monkeypatch):
        """WALKAI_SP_STREAM=1 swaps the dense reference tail for the
        streamed online-softmax fold inside the span's attend — same
        tokens required (the off-TPU CI form of the on-TPU default)."""
        monkeypatch.setenv("WALKAI_SP_STREAM", "1")
        specs = [(140, 9), (20, 12)]
        eng = _engine(params, True)
        outs = _run_specs(eng, specs)
        for n, m in specs:
            want = _expected(params, _prompt(n, seed=n), m)
            assert outs[(n, m)] == want, (n, m)


class TestLengthAwareAdmission:
    def test_second_long_held_and_short_jumps(self, params):
        """One sp entry at a time: with a long already prefilling, a
        queued long is HELD (holds_total counts the turn) while the
        short behind it admits — the starvation guard's whole point.
        Both longs still finish with the right tokens."""
        eng = _engine(params, True)
        specs = [(140, 6), (150, 6), (20, 6)]
        rids = {
            eng.submit(_prompt(n, seed=n), max_new_tokens=m): (n, m)
            for n, m in specs
        }
        eng.step()
        st = eng.sp_stats()
        assert st["active"] == 1
        assert st["requests_total"] == 1  # 150 held, not admitted
        assert st["holds_total"] >= 1
        # The short jumped the held long: only the second long is
        # still queued (the short admitted and is prefilling or
        # already decoding on its slot).
        assert [len(r.prompt) for r in eng._pending] == [150]
        out = {rids[r]: t for r, t in eng.run().items()}
        assert eng.sp_stats()["requests_total"] == 2
        for n, m in specs:
            assert out[(n, m)] == _expected(
                params, _prompt(n, seed=n), m
            ), (n, m)

    def test_short_only_traffic_never_touches_sp(self, params):
        """With sp on but every prompt under the threshold, behavior
        is byte-for-byte the serial lane: no sp admissions, no rows,
        no holds."""
        eng = _engine(params, True)
        outs = _run_specs(eng, [(20, 8), (40, 8), (64, 8)])
        st = eng.sp_stats()
        assert st["requests_total"] == 0
        assert st["rows_total"] == 0
        assert st["holds_total"] == 0
        for n, m in [(20, 8), (40, 8), (64, 8)]:
            assert outs[(n, m)] == _expected(
                params, _prompt(n, seed=n), m
            )


class TestSpContract:
    def test_requires_paged_engine(self, params):
        with pytest.raises(ValueError, match="requires the paged"):
            ContinuousBatcher(
                CFG, params, slots=2, cache_len=256, paged=False,
                sp_prefill=True,
            )

    def test_knob_validation(self, params):
        with pytest.raises(ValueError, match="sp_min_tokens"):
            _engine(params, True, sp_min_tokens=0)
        with pytest.raises(ValueError, match="sp_span"):
            _engine(params, True, sp_span=-1)

    def test_span_auto_sizes_and_surfaces(self, params):
        """sp_span=0 auto-sizes (>= 2); the knobs show up in
        sp_stats, debug_state's `sp` block, and the capture
        fingerprint, and all three sp knobs are replayable engine
        knobs."""
        eng = _engine(params, True, sp_span=0)
        assert eng.sp_span >= 2
        st = eng.sp_stats()
        assert st["enabled"] is True
        assert st["sp_min_tokens"] == 96
        assert st["sp_span"] == eng.sp_span
        assert eng.debug_state()["sp"] == st
        fp = eng.config_fingerprint()["engine"]
        assert fp["sp_prefill"] is True
        assert fp["sp_min_tokens"] == 96
        assert fp["sp_span"] == eng.sp_span
        for knob in ("sp_prefill", "sp_min_tokens", "sp_span"):
            assert knob in ENGINE_KNOBS


class TestSpCaptureDigest:
    def test_sp_on_capture_replays_sp_off(self, params, tmp_path):
        """PR 15's digest check as the machine parity proof: a
        capture recorded with the fan-out live must replay with zero
        divergences on the serial lane (and vice versa via the
        override), because sp changes scheduling, never tokens."""
        d = str(tmp_path)
        eng = _engine(params, True, capture=d)
        eng.submit(_prompt(140, seed=140), max_new_tokens=6)
        eng.submit(_prompt(20, seed=20), max_new_tokens=6)
        eng.submit(
            _prompt(97, seed=97), max_new_tokens=5, temperature=0.9,
            top_k=16, seed=7,
        )
        live = eng.run()
        assert eng.sp_stats()["requests_total"] == 2
        cap = load_capture(d)
        assert cap.fingerprint["engine"]["sp_prefill"] is True
        assert {r.rid: r.tokens for r in cap.records} == live
        for overrides in (None, {"sp_prefill": False}):
            report = replay_capture(cap, params, overrides=overrides)
            assert report.ok, report.summary()

"""Deployment manifests sanity: parseable YAML, consistent contracts."""

from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parents[1]


def _all_docs():
    for path in sorted(REPO.glob("deploy/**/*.yaml")) + sorted(
        REPO.glob("demos/**/manifests/**/*.yaml")
    ):
        for doc in yaml.safe_load_all(path.read_text()):
            if doc:
                yield path, doc


def test_all_manifests_parse():
    docs = list(_all_docs())
    assert len(docs) >= 10


def test_kinds_and_namespaces():
    for path, doc in _all_docs():
        if doc.get("kind") == "Kustomization":
            continue
        assert "kind" in doc and "apiVersion" in doc, path
        # kustomize bases/overlays get their namespace from kustomization.yaml
        if {"base", "overlays"} & set(path.parts):
            continue
        if doc["kind"] in ("Deployment", "DaemonSet", "ConfigMap", "Secret"):
            assert doc["metadata"].get("namespace"), (path, doc["kind"])


def test_agent_daemonset_contract():
    """The agent DS must carry the pieces the code contracts require."""
    for path, doc in _all_docs():
        if doc["kind"] == "DaemonSet" and doc["metadata"]["name"] == "tpuagent":
            spec = doc["spec"]["template"]["spec"]
            container = spec["containers"][0]
            env_names = {e["name"] for e in container["env"]}
            assert "NODE_NAME" in env_names  # cmd/tpuagent requires it
            mounts = {m["mountPath"] for m in container["volumeMounts"]}
            assert "/var/lib/kubelet/pod-resources" in mounts
            assert "/var/lib/kubelet/device-plugins" in mounts
            assert spec["nodeSelector"] == {
                "nos.walkai.io/tpu-partitioning": "tiling"
            }
            return
    raise AssertionError("tpuagent DaemonSet not found")


def test_crds_define_quota_kinds():
    kinds = {
        doc["spec"]["names"]["kind"]
        for _, doc in _all_docs()
        if doc["kind"] == "CustomResourceDefinition"
    }
    assert {"ElasticQuota", "CompositeElasticQuota"} <= kinds


def test_demo_requests_slice_resources():
    for path, doc in _all_docs():
        if (
            doc["kind"] == "Deployment"
            and doc["metadata"]["name"] == "tpu-inference"
        ):
            spec = doc["spec"]["template"]["spec"]
            assert spec["schedulerName"] == "walkai-nos-scheduler"
            limits = spec["containers"][0]["resources"]["limits"]
            assert any(k.startswith("walkai.io/tpu-") for k in limits)
            return
    raise AssertionError("demo deployment not found")


def test_kustomization_resources_exist():
    """`kubectl apply -k deploy/` must not dangle: every resource listed
    in a kustomization.yaml resolves to a file on disk."""
    kustomizations = sorted(REPO.glob("deploy/**/kustomization.yaml"))
    assert kustomizations, "deploy/ kustomize entry point missing"
    for path in kustomizations:
        doc = yaml.safe_load(path.read_text())
        for res in doc.get("resources", []):
            assert (path.parent / res).exists(), (path, res)


def test_prometheus_monitors_target_real_apps():
    """Each PodMonitor selector must match a workload that exists in
    deploy/ (scraping :8080, the config-default metrics bind), and each
    ServiceMonitor must match a Service defined alongside it."""
    app_ports: dict = {}
    service_labels = []
    for _, doc in _all_docs():
        if doc.get("kind") in ("Deployment", "DaemonSet"):
            template = doc.get("spec", {}).get("template", {})
            labels = template.get("metadata", {}).get("labels", {})
            ports = set()
            for container in template.get("spec", {}).get("containers", []):
                for port in container.get("ports", []):
                    ports.add(port.get("name"))
            app_ports[labels.get("app")] = ports
        elif doc.get("kind") == "Service":
            service_labels.append(doc["metadata"].get("labels", {}))
    monitors = REPO / "deploy" / "prometheus" / "monitors.yaml"
    for doc in yaml.safe_load_all(monitors.read_text()):
        if not doc:
            continue
        if doc["kind"] == "PodMonitor":
            (app,) = doc["spec"]["selector"]["matchLabels"].values()
            assert app in app_ports, app
            for ep in doc["spec"]["podMetricsEndpoints"]:
                # prometheus-operator keep-relabels on the DECLARED
                # container port; a port the workload doesn't declare
                # matches zero targets, silently.
                assert ep["port"] in app_ports[app], (app, ep)
        elif doc["kind"] == "ServiceMonitor":
            want = doc["spec"]["selector"]["matchLabels"]
            assert any(
                all(labels.get(k) == v for k, v in want.items())
                for labels in service_labels
            ), want


def test_docs_site_structure():
    """The docs tree is a buildable site: nav complete, no orphan
    pages, relative links resolve (hack/check_docs.py — the stdlib half
    of CI's `mkdocs build --strict`)."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run(
        [sys.executable, str(repo / "hack" / "check_docs.py")],
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr

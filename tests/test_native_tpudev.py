"""Native tpudev library, driven through the ctypes binding.

Builds libtpudev.so once per session (the toolchain is part of the dev
environment). Hardware is emulated with a temp device dir of fake accelN
nodes — the native layer itself is under test, not a TPU (SURVEY.md §4).
"""

import os
import subprocess
from pathlib import Path

import pytest

from walkai_nos_tpu.tpu.errors import GenericError
from walkai_nos_tpu.tpu.tiling.packing import Placement

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="session")
def libtpudev() -> Path:
    subprocess.run(
        ["make", "-C", str(REPO / "native" / "tpudev")],
        check=True,
        capture_output=True,
    )
    return REPO / "native" / "tpudev" / "build" / "libtpudev.so"


@pytest.fixture
def host_env(tmp_path, monkeypatch):
    """Fake v5e-8 host: 8 accel nodes + empty state dir."""
    dev = tmp_path / "dev"
    dev.mkdir()
    for i in range(8):
        (dev / f"accel{i}").touch()
    state = tmp_path / "state"
    monkeypatch.setenv("TPUDEV_DEV_DIR", str(dev))
    monkeypatch.setenv("TPUDEV_STATE_DIR", str(state))
    monkeypatch.setenv("TPUDEV_MESH", "2x4")
    return tmp_path


def _spawn_client_subprocess(lib, code):
    """Native state is process-global (init reads env once), so each test
    case runs its client in a subprocess with its own env."""
    script = f"""
import sys
sys.path.insert(0, {str(REPO)!r})
from walkai_nos_tpu.tpudev.native import NativeTpudevClient
from walkai_nos_tpu.tpu.tiling.packing import Placement
from walkai_nos_tpu.tpu.errors import GenericError, NotFoundError
client = NativeTpudevClient({str(lib)!r})
{code}
"""
    return subprocess.run(
        ["python3", "-c", script],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


class TestNativeTpudev:
    def test_topology(self, libtpudev, host_env):
        r = _spawn_client_subprocess(
            libtpudev,
            "t = client.get_topology()\n"
            "assert t.mesh == (2, 4), t.mesh\n"
            "assert t.chip_count == 8\n"
            "assert t.chips[5].coords == (1, 1), t.chips[5]\n"
            "assert t.chips[5].device_path.endswith('accel5')\n"
            "print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_create_list_delete_roundtrip(self, libtpudev, host_env):
        r = _spawn_client_subprocess(
            libtpudev,
            "p = Placement(profile='2x2', offset=(0, 0), orientation=(2, 2))\n"
            "created = client.create_slices([p])\n"
            "assert [s.slice_id for s in created] == ['2x2@0-0']\n"
            "assert created[0].chip_ids == (0, 1, 4, 5), created[0].chip_ids\n"
            "assert created[0].env['TPU_VISIBLE_CHIPS'] == '0,1,4,5'\n"
            "assert client.get_slice_mesh_index('2x2@0-0') == 0\n"
            "client.delete_slice('2x2@0-0')\n"
            "assert client.list_slices() == []\n"
            "print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_pool_share_covers_whole_host(self, libtpudev, host_env):
        """A pool share (profile spanning more chips than the host has)
        is valid only as a full-host placement at offset zero; partial
        coverage is rejected (tpudev.cc pool-share rule)."""
        r = _spawn_client_subprocess(
            libtpudev,
            # Valid: the host's 2x4 share of a 4x8 (4-host v5e) slice.
            "p = Placement(profile='4x8', offset=(0, 0), orientation=(2, 4))\n"
            "created = client.create_slices([p])\n"
            "assert [s.slice_id for s in created] == ['4x8@0-0']\n"
            "assert created[0].profile == '4x8'\n"
            "assert len(created[0].chip_ids) == 8, created[0].chip_ids\n"
            "client.delete_slice('4x8@0-0')\n"
            # Invalid: pool profile on a partial placement.
            "bad = Placement(profile='4x8', offset=(0, 0), orientation=(2, 2))\n"
            "try:\n"
            "    client.create_slices([bad])\n"
            "    raise SystemExit('partial pool share accepted')\n"
            "except GenericError:\n"
            "    pass\n"
            # Invalid: profile bigger than orientation but <= host chips
            # must NOT slip through as a mislabeled slice.\n"
            "bad2 = Placement(profile='2x4', offset=(0, 0), orientation=(1, 2))\n"
            "try:\n"
            "    client.create_slices([bad2])\n"
            "    raise SystemExit('mislabeled slice accepted')\n"
            "except GenericError:\n"
            "    pass\n"
            "print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_overlap_and_duplicate_rejected(self, libtpudev, host_env):
        r = _spawn_client_subprocess(
            libtpudev,
            "p = Placement(profile='2x2', offset=(0, 0), orientation=(2, 2))\n"
            "client.create_slices([p])\n"
            "q = Placement(profile='2x2', offset=(0, 1), orientation=(2, 2))\n"
            "try:\n"
            "    client.create_slices([q])\n"
            "    raise SystemExit('overlap accepted')\n"
            "except GenericError:\n"
            "    pass\n"
            "try:\n"
            "    client.create_slices([p])\n"
            "    raise SystemExit('duplicate accepted')\n"
            "except GenericError:\n"
            "    pass\n"
            "print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_state_survives_process_restart(self, libtpudev, host_env):
        """Slices persist host-side (the GI/CI-in-driver analogue)."""
        r1 = _spawn_client_subprocess(
            libtpudev,
            "client.create_slices([Placement('2x4', (0, 0), (2, 4))])\n"
            "print('OK')",
        )
        assert r1.returncode == 0, r1.stderr
        r2 = _spawn_client_subprocess(
            libtpudev,
            "s = client.list_slices()\n"
            "assert [x.slice_id for x in s] == ['2x4@0-0'], s\n"
            "assert s[0].chip_ids == (0, 1, 2, 3, 4, 5, 6, 7)\n"
            "deleted = client.delete_all_slices_except(set())\n"
            "assert deleted == ['2x4@0-0']\n"
            "print('OK')",
        )
        assert r2.returncode == 0, r2.stderr
        assert "OK" in r2.stdout

    def test_delete_missing_is_notfound(self, libtpudev, host_env):
        r = _spawn_client_subprocess(
            libtpudev,
            "try:\n"
            "    client.delete_slice('2x2@9-9')\n"
            "    raise SystemExit('missing delete accepted')\n"
            "except NotFoundError:\n"
            "    print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_partial_failure_tolerated(self, libtpudev, host_env):
        """One bad placement among good ones: good ones create, like
        mig.Client.CreateMigDevices (`client.go:50-74`)."""
        r = _spawn_client_subprocess(
            libtpudev,
            "good = Placement('2x2', (0, 0), (2, 2))\n"
            "bad = Placement('2x2', (3, 3), (2, 2))\n"
            "created = client.create_slices([good, bad])\n"
            "assert [s.slice_id for s in created] == ['2x2@0-0']\n"
            "print('OK')",
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout

    def test_no_chips_fails_init(self, libtpudev, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUDEV_DEV_DIR", str(tmp_path / "empty"))
        monkeypatch.setenv("TPUDEV_STATE_DIR", str(tmp_path / "state"))
        r = _spawn_client_subprocess(libtpudev, "print('UNREACHABLE')")
        assert r.returncode != 0
        assert "no TPU chips" in r.stderr

    def test_stub_fallback_when_lib_missing(self, monkeypatch):
        from walkai_nos_tpu.tpudev import native as native_mod
        from walkai_nos_tpu.tpudev.stub import StubTpudevClient

        monkeypatch.setenv("WALKAI_TPUDEV_LIB", "/nonexistent/libtpudev.so")
        client = native_mod.load_client()
        assert isinstance(client, StubTpudevClient)


class TestHardening:
    """Regression cases from the native-layer deep review."""

    def test_corrupt_slice_record_refused(self, libtpudev, host_env):
        # A truncated/corrupt record must fail the listing loudly — a
        # silently dropped record would free its chips for re-allocation
        # under a running pod.
        state = host_env / "state"
        state.mkdir(exist_ok=True)
        (state / "broken.slice").write_text("not-a-placement\n")
        r = _spawn_client_subprocess(
            libtpudev,
            "client.list_slices()",
        )
        assert r.returncode != 0
        assert "corrupt" in (r.stderr or "")

    def test_corrupt_record_blocks_creates(self, libtpudev, host_env):
        state = host_env / "state"
        state.mkdir(exist_ok=True)
        (state / "broken.slice").write_text("garbage\n")
        r = _spawn_client_subprocess(
            libtpudev,
            "client.create_slices([Placement('2x2', (0, 0), (2, 2))])",
        )
        assert r.returncode != 0
        assert "corrupt" in (r.stderr or "")

    def test_multi_host_tpu_topology_falls_back_to_local_mesh(
        self, libtpudev, host_env, monkeypatch
    ):
        # TPU_TOPOLOGY describes the whole (multi-host) slice; a host
        # with fewer chips must infer its local mesh instead of failing.
        monkeypatch.delenv("TPUDEV_MESH")
        monkeypatch.setenv("TPU_TOPOLOGY", "4x4")  # 16 chips; host has 8
        r = _spawn_client_subprocess(
            libtpudev,
            "print(client.get_topology().mesh)",
        )
        assert r.returncode == 0, r.stderr
        assert "(2, 4)" in r.stdout  # inferred local v5e-8 mesh


class TestFakeGrammarParity:
    def test_fake_rejects_non_permutation_orientation(self):
        from walkai_nos_tpu.tpudev.fake import FakeTpudevClient

        fake = FakeTpudevClient(mesh=(2, 4))
        with pytest.raises(GenericError):
            fake.create_slices([Placement("2x2", (0, 0), (2, 3))])
        with pytest.raises(GenericError):
            fake.create_slices([Placement("bogus", (0, 0), (2, 2))])


class TestAbiHandshake:
    def test_matching_version_loads(self, libtpudev):
        # Every constructed client already passed the handshake; check
        # the exported symbol agrees with the wrapper's constant.
        import ctypes

        from walkai_nos_tpu.tpudev import native

        lib = ctypes.CDLL(str(libtpudev))
        assert int(lib.tpudev_abi_version()) == native.EXPECTED_ABI_VERSION

    def test_mismatch_refused(self, libtpudev, monkeypatch):
        from walkai_nos_tpu.tpudev import native

        monkeypatch.setattr(native, "EXPECTED_ABI_VERSION", 999)
        with pytest.raises(GenericError, match="ABI mismatch"):
            native.NativeTpudevClient(lib_path=str(libtpudev))

    def test_load_client_does_not_stub_over_a_mismatch(
        self, libtpudev, monkeypatch
    ):
        """The stub fallback is for a MISSING library; a present-but-
        wrong-ABI one must stop the process, not degrade silently."""
        from walkai_nos_tpu.tpudev import native

        monkeypatch.setenv("WALKAI_TPUDEV_LIB", str(libtpudev))
        monkeypatch.setattr(native, "EXPECTED_ABI_VERSION", 999)
        with pytest.raises(native.AbiMismatchError):
            native.load_client()
